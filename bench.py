"""Headline benchmark: scheduling decisions/sec at 100k tasks × 10k nodes.

Matches BASELINE.json config 4 scale (the reference's
BenchmarkScheduler100kNodes*/1kNodes* family,
manager/scheduler/scheduler_test.go:3338-3376): one big task group scheduled
onto a 10k-node cluster through the full path — store → scheduler tick →
(TPU plan | host oracle) → columnar store commit — measured from tick start
to all ASSIGNED rows committed, median of BENCH_TRIALS runs.

Baseline: the Go toolchain is not present in this image, so the reference's
own benches cannot run here.  ``vs_baseline`` therefore compares against the
**host oracle path** (the faithful reimplementation of the reference
algorithm running on the same store) measured in this same process on a
proportionally scaled workload (same 10k nodes, BENCH_BASELINE_TASKS tasks),
normalized per decision.  See BASELINE.md for the methodology note.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "decisions/sec", "vs_baseline": N, ...}

Env overrides: BENCH_NODES, BENCH_TASKS, BENCH_BASELINE_TASKS,
BENCH_SKIP_HOST, BENCH_TRIALS.
"""

import gc
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_TASKS = int(os.environ.get("BENCH_TASKS", 100_000))
BASELINE_TASKS = int(os.environ.get("BENCH_BASELINE_TASKS", 5_000))
SKIP_HOST = os.environ.get("BENCH_SKIP_HOST", "") == "1"
TRIALS = int(os.environ.get("BENCH_TRIALS", 3))


def build_cluster(n_nodes, n_tasks):
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
        Placement, ReplicatedService, Resources, ResourceRequirements,
        Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id

    store = MemoryStore()
    nodes = [
        Node(id=new_id(),
             spec=NodeSpec(annotations=Annotations(
                 name=f"node-{i:05d}", labels={"rack": f"r{i % 20}"})),
             status=NodeStatus(state=NodeState.READY),
             description=NodeDescription(
                 hostname=f"node-{i:05d}",
                 resources=Resources(nano_cpus=32 * 10**9,
                                     memory_bytes=128 << 30)))
        for i in range(n_nodes)
    ]
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(annotations=Annotations(name="bench"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks)),
        spec_version=Version(index=1))
    shared_spec = TaskSpec(
        resources=ResourceRequirements(
            reservations=Resources(nano_cpus=10**9,
                                   memory_bytes=1 << 30)))
    tasks = [
        Task(id=new_id(), service_id=svc.id, slot=s,
             desired_state=TaskState.RUNNING, spec=shared_spec,
             spec_version=Version(index=1),
             status=TaskStatus(state=TaskState.PENDING))
        for s in range(1, n_tasks + 1)
    ]

    def setup(tx):
        for n in nodes:
            tx.create(n)
        tx.create(svc)

    store.update(setup)

    def add_tasks(tx):
        for t in tasks:
            tx.create(t)

    store.update(add_tasks)
    return store, svc


def run_path(n_nodes, n_tasks, planner):
    """One full tick on a fresh cluster; returns timing detail."""
    from swarmkit_tpu.scheduler import Scheduler

    store, svc = build_cluster(n_nodes, n_tasks)
    sched = Scheduler(store, batch_planner=planner)
    store.view(sched._setup_tasks_list)
    gc.collect()
    gc.freeze()   # long-lived store objects out of GC scan range
    t0 = time.perf_counter()
    n_dec = sched.tick()
    dt = time.perf_counter() - t0
    gc.unfreeze()
    assert n_dec == n_tasks, f"scheduled {n_dec}/{n_tasks}"
    if planner is not None:
        # fail loudly if a regression silently routed tasks to the host
        # fallback: the headline number must measure the device path
        assert planner.stats["groups_planned"] >= 1, planner.stats
        assert planner.stats["tasks_planned"] == n_tasks, planner.stats
    return {
        "decisions": n_dec,
        "tick_s": dt,
        "plan_s": planner.stats["plan_seconds"] if planner else 0.0,
        "commit_s": sched.stats["commit_seconds"],
    }


def main():
    from swarmkit_tpu.ops import TPUPlanner

    # warm the kernel compile cache out of the timed region — must use the
    # same node count so the padded N bucket (and thus the jit cache key)
    # matches the measured run
    run_path(N_NODES, 64, TPUPlanner())

    trials = [run_path(N_NODES, N_TASKS, TPUPlanner()) for _ in range(TRIALS)]
    ticks = sorted(t["tick_s"] for t in trials)
    med = statistics.median(ticks)
    rep = min(trials, key=lambda t: abs(t["tick_s"] - med))
    tpu_dps = N_TASKS / med

    if SKIP_HOST:
        host_dps = None
        vs = 0.0
    else:
        host_trials = [run_path(N_NODES, BASELINE_TASKS, None)
                       for _ in range(TRIALS)]
        host_med = statistics.median(t["tick_s"] for t in host_trials)
        host_dps = BASELINE_TASKS / host_med
        vs = tpu_dps / host_dps

    print(json.dumps({
        "metric": f"scheduling decisions/sec, {N_TASKS // 1000}k tasks x "
                  f"{N_NODES // 1000}k nodes (single tick, store-committed)",
        "value": round(tpu_dps, 1),
        "unit": "decisions/sec",
        "vs_baseline": round(vs, 2),
        "tick_p50_s": round(med, 3),
        "tick_p99_s": round(ticks[-1], 3),
        "plan_phase_s": round(rep["plan_s"], 3),
        "commit_phase_s": round(rep["commit_s"], 3),
        "plan_phase_decisions_per_sec": round(N_TASKS / rep["plan_s"], 1)
        if rep["plan_s"] else None,
        "trials": TRIALS,
        "baseline": "host-oracle path, same store+commit framework "
                    "(Go toolchain unavailable; see BASELINE.md)",
        "baseline_decisions_per_sec": round(host_dps, 1) if host_dps else None,
    }))


if __name__ == "__main__":
    main()
