"""Benchmark: all five BASELINE.json configs through the full path —
store → scheduler tick → TPU plan → columnar store commit.

Headline (the driver's one JSON line) is config 4's scale: 100k tasks ×
10k nodes, median of BENCH_TRIALS runs with p50/p99 and plan/commit phase
breakdown.  The other configs run once each and are embedded in the same
JSON line under "configs":

  1. 1k tasks × 100 nodes, no constraints (spread-only baseline)
  2. 10k × 1k with CPU/memory reservations (ResourceFilter bin-packing)
  3. 50k × 5k with node.labels + platform constraints
  4. 100k × 10k mixed replicated+global with spread-by-label preference
  5. reschedule storm: 500k tasks on 10k nodes, drain 1k nodes → re-place
     the displaced tasks in one tick (plus a 500k cold-storm single tick)

Baseline: the Go toolchain is not present in this image, so the reference's
own benches cannot run here.  ``vs_baseline`` compares against the **host
oracle path** (the faithful reimplementation of the reference algorithm on
the same store) measured in this process on a proportionally scaled
workload, normalized per decision.  See BASELINE.md.

An end-to-end "phone-home" measurement (reference: cmd/swarm-bench) runs
the full pipeline — control API -> orchestrator -> device scheduler ->
dispatcher -> agents -> RUNNING status writeback — and reports
time-to-RUNNING percentiles per task.

Observability: the obs tracer records per-phase spans (plan dispatch /
D2H / apply, scheduler batch-build / host-fallback / commit) during every
timed trial; the full Chrome trace is written to ``BENCH_TRACE_OUT``
(default bench_trace.json — load in chrome://tracing or Perfetto) and a
per-config phase table derived from that same trace is embedded in the
output JSON, including the plan↔commit overlap fraction ROADMAP item 1
needs.  Tracing overhead is measured directly: alternating tracer-on/off
trials of the headline config, median of each half under "obs".
Planner routing counters are read from the metrics registry (deltas per
trial), not from ad-hoc dict fields.

Env overrides: BENCH_NODES, BENCH_TASKS, BENCH_BASELINE_TASKS,
BENCH_SKIP_HOST, BENCH_TRIALS, BENCH_SKIP_CONFIGS, BENCH_SKIP_E2E,
BENCH_SKIP_OBS, BENCH_TRACE_OUT, BENCH_CFG6_SERVICES,
BENCH_CFG7_SERVICES/NODES/TASKS,
BENCH_CFG10_NODES/BASE_TASKS/WINDOWS/SEED, SWARM_PLANNER_MESH.
"""

import gc
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_TASKS = int(os.environ.get("BENCH_TASKS", 100_000))
BASELINE_TASKS = int(os.environ.get("BENCH_BASELINE_TASKS", 5_000))
SKIP_HOST = os.environ.get("BENCH_SKIP_HOST", "") == "1"
SKIP_CONFIGS = os.environ.get("BENCH_SKIP_CONFIGS", "") == "1"
# run only the named configs, e.g. BENCH_CONFIGS="4 6" (empty = all);
# the headline always runs.  scripts/bench_repro.py uses this to repeat
# the cfg6 bar cheaply.
CONFIGS_ONLY = set(
    os.environ.get("BENCH_CONFIGS", "").replace(",", " ").split())
SKIP_E2E = os.environ.get("BENCH_SKIP_E2E", "") == "1"
# skips the alternating on/off overhead pairs (2x TRIALS extra headline
# trials); smoke/CI runs that don't read overhead_pct can turn it off
SKIP_OBS = os.environ.get("BENCH_SKIP_OBS", "") == "1"
TRIALS = int(os.environ.get("BENCH_TRIALS", 3))
# best-of-N per config (r4->r5 showed a 17x swing on identical code from
# one-off XLA recompiles landing inside a single timed trial)
CONFIG_TRIALS = int(os.environ.get("BENCH_CONFIG_TRIALS", 2))
# variance guard: a config whose worst trial is >1.3x its best gets one
# extra trial so a single recompile/GC hiccup cannot own the number
VARIANCE_GUARD_X = float(os.environ.get("BENCH_VARIANCE_GUARD_X", 1.3))
VARIANCE_RETRIES = int(os.environ.get("BENCH_VARIANCE_RETRIES", 1))
TRACE_OUT = os.environ.get("BENCH_TRACE_OUT", "bench_trace.json")
# flight-recorder post-mortem written when a trial trips the variance
# guard — the evidence trail for "why did this config swing"
FLIGHTREC_OUT = os.environ.get("BENCH_FLIGHTREC_OUT",
                               "bench_flightrec.json")
# every run appends its per-config summary here (bench_compare.py diffs
# entries); set to "" to disable
HISTORY_OUT = os.environ.get("BENCH_HISTORY", "BENCH_HISTORY.jsonl")


def _mesh_devices() -> int:
    """Planner mesh size (SWARM_PLANNER_MESH), 1 when unset/garbage —
    same parse rules as parallel.sharded.mesh_from_env."""
    raw = os.environ.get("SWARM_PLANNER_MESH", "").strip()
    try:
        return max(1, int(raw)) if raw else 1
    except ValueError:
        return 1


def _mesh_crossover():
    """The mesh crossover artifact (scripts/mesh_crossover.py), trimmed
    to the headline fields, or None when it has not been measured."""
    path = os.environ.get("BENCH_MESH_CROSSOVER", "MULTICHIP_r07.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return {
        "winner_by_shape": doc.get("winner_by_shape"),
        "placements_equal_across_mesh":
            doc.get("placements_equal_across_mesh"),
        "strategy_host_fallbacks": doc.get("strategy_host_fallbacks"),
        "skipped": doc.get("skipped"),
        "curves": {nb: s.get("curve")
                   for nb, s in (doc.get("shapes") or {}).items()},
        "decisions_per_sec": {
            nb: s.get("decisions_per_sec")
            for nb, s in (doc.get("shapes") or {}).items()},
    }


def _cfg_enabled(n: int) -> bool:
    if SKIP_CONFIGS:
        return False
    return not CONFIGS_ONLY or str(n) in CONFIGS_ONLY


def _planner_counters():
    """Routing-counter keys, derived from the planner's own route map so
    a label rename there can never silently zero bench's numbers (the
    planner increments stats dict and registry through one helper, and
    bench reports the registry's numbers)."""
    from swarmkit_tpu.ops import TPUPlanner
    keys = {stat_key: f'swarm_planner_groups{{route="{route}"}}'
            for stat_key, route in TPUPlanner._ROUTE.items()}
    keys["tasks_planned"] = "swarm_planner_tasks_planned"
    return keys


def _planner_counter_snapshot():
    from swarmkit_tpu.utils.metrics import registry
    return registry.counters_snapshot("swarm_planner_")


def _planner_counter_delta(snap):
    cur = _planner_counter_snapshot()
    return {stat_key: int(cur.get(reg_key, 0.0) - snap.get(reg_key, 0.0))
            for stat_key, reg_key in _planner_counters().items()}


_COMPILE_PREFIX = 'swarm_planner_compiles{bucket="'


def _compile_delta(snap):
    """Per-bucket XLA compile counts since ``snap`` (zeros included, so
    the artifact names every bucket the run touched — "this bucket
    existed and did NOT recompile" is the common, load-bearing case)."""
    cur = _planner_counter_snapshot()
    out = {}
    for key in set(cur) | set(snap):
        if not key.startswith(_COMPILE_PREFIX):
            continue
        bucket = key[len(_COMPILE_PREFIX):-2]
        out[bucket] = int(cur.get(key, 0.0) - snap.get(key, 0.0))
    return dict(sorted(out.items()))


def build_cluster(n_nodes, n_tasks, node_labels=None, reservations=None,
                  constraints=None, platforms=None, prefs=None,
                  node_platform=None, global_share=0.0, assigned_state=None,
                  n_services=1):
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
        Placement, Platform, ReplicatedService, Resources,
        ResourceRequirements, Service, ServiceMode, ServiceSpec, Task,
        TaskSpec, TaskState, TaskStatus, Version,
    )
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id

    store = MemoryStore()
    nodes = []
    for i in range(n_nodes):
        labels = dict(node_labels(i)) if node_labels else \
            {"rack": f"r{i % 20}"}
        platform = Platform(**node_platform(i)) if node_platform else \
            Platform(os="linux", architecture="amd64")
        nodes.append(Node(
            id=new_id(),
            spec=NodeSpec(annotations=Annotations(
                name=f"node-{i:05d}", labels=labels)),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname=f"node-{i:05d}", platform=platform,
                resources=Resources(nano_cpus=64 * 10**9,
                                    memory_bytes=256 << 30))))
    shared_spec = TaskSpec(
        placement=Placement(constraints=constraints or [],
                            platforms=platforms or [],
                            preferences=prefs or []),
        resources=ResourceRequirements(
            reservations=reservations
            or Resources(nano_cpus=10**8, memory_bytes=64 << 20)))

    # n_services > 1 splits the task count over distinct services: each
    # becomes its own (service, spec-version) scheduling group, the unit
    # the pipelined tick overlaps (plan group i+1 while committing i)
    services = []
    tasks = []
    per = n_tasks // n_services
    for si in range(n_services):
        count = per if si < n_services - 1 else n_tasks - per * si
        svc = Service(
            id=new_id(),
            spec=ServiceSpec(annotations=Annotations(name=f"bench-{si}"),
                             mode=ServiceMode.REPLICATED,
                             replicated=ReplicatedService(replicas=count)),
            spec_version=Version(index=1))
        services.append(svc)
        n_global = int(count * global_share)
        for s in range(1, count + 1):
            t = Task(id=new_id(), service_id=svc.id, slot=s,
                     desired_state=TaskState.RUNNING, spec=shared_spec,
                     spec_version=Version(index=1),
                     status=TaskStatus(state=TaskState.PENDING))
            if s <= n_global:
                # global-service style: preassigned to a node
                t.slot = 0
                t.node_id = nodes[s % n_nodes].id
            if assigned_state is not None and s > n_global:
                t.node_id = nodes[s % n_nodes].id
                t.status = TaskStatus(state=assigned_state)
            tasks.append(t)
    svc = services[0]

    def create_nodes(tx):
        for n in nodes:
            tx.create(n)
        for s in services:
            tx.create(s)

    store.update(create_nodes)

    def create_tasks(tx):
        for t in tasks:
            tx.create(t)

    store.update(create_tasks)
    return store, svc, nodes, tasks


def one_tick(store, planner, preassigned=False):
    from swarmkit_tpu.scheduler import Scheduler

    sched = Scheduler(store, batch_planner=planner)
    store.view(sched._setup_tasks_list)
    n_pre = len(sched.pending_preassigned_tasks)
    gc.collect()
    gc.freeze()
    t0 = time.perf_counter()
    if preassigned:
        sched._process_preassigned_tasks()
    n_dec = sched.tick()
    if preassigned:
        # only preassigned tasks that actually confirmed count
        n_dec += n_pre - len(sched.pending_preassigned_tasks)
    dt = time.perf_counter() - t0
    gc.unfreeze()
    return sched, n_dec, dt


def _trim_heap():
    """Release the previous config's multi-GB object graph back to the
    OS between configs: leftover arenas inflate later configs' GC and
    allocator costs (cfg4/storm measured ~2x slower inside the full run
    than in isolation before this)."""
    gc.collect()
    try:
        import ctypes
        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except Exception:
        pass


# name -> {"path", "sha256"} of flight-recorder dumps written because a
# config tripped the variance guard (read back into the artifact)
_flightrec_dumps = {}


def _dump_flightrec_on_trip(name):
    """A trial swung past the guard: dump the black box NOW, before the
    retry overwrites the evidence (the recent spans — including any
    plan.compile — and counter samples around the slow trial)."""
    from swarmkit_tpu.obs import flightrec
    base, ext = os.path.splitext(FLIGHTREC_OUT)
    path = f"{base}_{name}{ext}" if name else FLIGHTREC_OUT
    try:
        sha = flightrec.dump(path)
    except OSError:
        return
    _flightrec_dumps[name or "headline"] = {"path": path, "sha256": sha}


def run_with_variance_guard(trial, n_trials=None, name=None):
    """Best-of-N with the variance guard: run ``trial`` (returning a
    tuple whose first element is the timed seconds) n_trials times, then
    keep re-running while the worst trial exceeds VARIANCE_GUARD_X of
    the best (up to VARIANCE_RETRIES extras).  A tripped guard dumps the
    flight recorder so the swing is explainable after the fact.
    Returns (results, retries)."""
    results = [trial() for _ in range(n_trials or CONFIG_TRIALS)]
    retries = 0
    while retries < VARIANCE_RETRIES:
        dts = [r[0] for r in results]
        if max(dts) <= VARIANCE_GUARD_X * min(dts):
            break
        if retries == 0:
            _dump_flightrec_on_trip(name)
        retries += 1
        results.append(trial())
    return results, retries


def _spread_stats(dts):
    """Trial-spread fields shared by every multi-trial config."""
    best = min(dts)
    return {
        "trials": len(dts),
        "tick_s": round(best, 3),                      # headline = best
        "tick_s_median": round(statistics.median(dts), 3),
        "tick_s_stdev": round(statistics.stdev(dts), 4)
        if len(dts) > 1 else 0.0,
        "variance_x": round(max(dts) / best, 2),
    }


def run_config(name, n_nodes, n_tasks, planner_factory, expect=None, **kw):
    """Best-of-CONFIG_TRIALS with a per-config shape warm-up pass and a
    variance guard, so a one-off XLA recompile can never be the headline
    (VERDICT Weak #2)."""
    from swarmkit_tpu.models import Task as _Task, TaskState

    from swarmkit_tpu.utils.metrics import registry

    preassigned = kw.get("global_share", 0.0) > 0

    # per-config warm-up: tiny task count, IDENTICAL node shape and
    # constraint/preference mix, so every jit signature this config hits
    # is compiled before any timed trial.  The tracer is off for the
    # warm-up: its spans (which absorb any XLA compile) must not land in
    # this config's bench.config window and contaminate the phase table.
    from swarmkit_tpu.obs import tracer
    _trim_heap()
    was_tracing = tracer.enabled
    tracer.disable()
    try:
        warm_store, *_ = build_cluster(n_nodes, 64, **kw)
        warm_planner = planner_factory()
        warm_planner.enable_small_group_routing = False
        one_tick(warm_store, warm_planner, preassigned=preassigned)
        del warm_store, warm_planner
    finally:
        tracer.enabled = was_tracing

    # per-config metrics isolation: counters/gauges zeroed, timers reset
    # in place, so this config's quantiles are its own
    registry.reset()

    def trial():
        _trim_heap()
        snap = _planner_counter_snapshot()
        store, svc, nodes, tasks = build_cluster(n_nodes, n_tasks, **kw)
        planner = planner_factory()
        sched, n_dec, dt = one_tick(store, planner,
                                    preassigned=preassigned)
        routed = _planner_counter_delta(snap)
        expected = expect if expect is not None else n_tasks
        n_assigned = sum(
            1 for t in store.view(lambda tx: tx.find(_Task))
            if t.status.state >= TaskState.ASSIGNED and t.node_id)
        assert n_assigned >= expected, \
            f"{name}: only {n_assigned}/{expected} tasks ASSIGNED"
        if routed["tasks_planned"] == 0:
            # legitimate only when the adaptive router sent every group
            # to the host because the device round-trip won't amortize
            assert routed["groups_small_to_host"] > 0 \
                and routed["groups_fallback"] == 0, \
                f"{name}: TPU path did not engage: {routed}"
        return dt, n_dec, planner, sched, routed

    results, retries = run_with_variance_guard(trial, name=name)
    dts = [r[0] for r in results]
    dt, n_dec, planner, sched, routed = min(results, key=lambda r: r[0])
    out = {
        "nodes": n_nodes, "tasks": n_tasks,
        "decisions": n_dec,
        "decisions_per_sec": round(n_dec / dt, 1),
        "plan_s": round(planner.stats["plan_seconds"], 3),
        "commit_s": round(sched.stats["commit_seconds"], 3),
        # routing counters from the metrics registry (per-trial deltas)
        "fallback_groups": routed["groups_fallback"],
        "groups_small_to_host": routed["groups_small_to_host"],
        "groups_device": routed["groups_planned"],
        "variance_reruns": retries,
        "path": "host-routed" if routed["tasks_planned"] == 0
        else "device",
        # per-bucket XLA compiles inside the timed trials (registry was
        # reset post-warm-up, so any nonzero count here is a compile
        # that landed in a timed region — the r4/r5 swing explained)
        "compiles": _compile_delta({}),
    }
    if name in _flightrec_dumps:
        out["flightrec_dump"] = _flightrec_dumps[name]
    out.update(_spread_stats(dts))
    return out


def run_storm(planner_factory):
    """Config 5: 500k tasks running on 10k nodes; 1k nodes are drained and
    the tasks they hosted must be re-placed on the remaining 9k nodes in
    one tick.  The cluster is built post-drain: drained nodes carry
    availability=DRAIN with their old tasks already SHUT DOWN (what the
    orchestrator/enforcer do), and one PENDING replacement per displaced
    task sits in the queue.  Best-of-CONFIG_TRIALS with the same variance
    guard as run_config (this config showed the 17x r4/r5 swing)."""
    from swarmkit_tpu.models import (
        NodeAvailability, Task, TaskState, TaskStatus,
    )
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.utils import new_id

    from swarmkit_tpu.utils.metrics import registry

    n_nodes, n_tasks, n_drained = 10_000, 500_000, 1_000
    registry.reset()   # per-config metrics isolation
    # no per-config warm-up needed (unlike run_config): jit signatures
    # are shape-bucketed and main()'s warm-up pass already compiled this
    # node bucket with no preferences; task count is a traced scalar, so
    # 500k tasks hits the same compiled program and no compile time can
    # land in this config's spans

    def trial():
        _trim_heap()
        snap = _planner_counter_snapshot()
        store, svc, nodes, tasks = build_cluster(
            n_nodes, n_tasks, assigned_state=TaskState.RUNNING)

        drained = set(n.id for n in nodes[:n_drained])

        def drain_nodes(tx):
            for n in nodes[:n_drained]:
                cur = tx.get(type(n), n.id).copy()
                cur.spec.availability = NodeAvailability.DRAIN
                tx.update(cur)

        store.update(drain_nodes)

        displaced = [t for t in tasks if t.node_id in drained]
        replacements = []
        for t in displaced:
            r = t.copy()
            r.id = new_id()
            r.node_id = ""
            r.status = TaskStatus(state=TaskState.PENDING)
            replacements.append(r)

        def shutdown_and_replace(batch):
            for t in displaced:
                def down(tx, t=t):
                    cur = tx.get(Task, t.id).copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    cur.status = TaskStatus(state=TaskState.SHUTDOWN)
                    tx.update(cur)
                batch.update(down)
            for r in replacements:
                batch.update(lambda tx, r=r: tx.create(r))

        store.batch(shutdown_and_replace)

        planner = planner_factory()
        sched = Scheduler(store, batch_planner=planner)
        store.view(sched._setup_tasks_list)

        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        n_dec = sched.tick()
        dt = time.perf_counter() - t0
        gc.unfreeze()
        assert n_dec == len(replacements), (n_dec, len(replacements))
        placed = store.view(
            lambda tx: [tx.get(Task, r.id) for r in replacements])
        assert all(t is not None and t.node_id and t.node_id not in drained
                   for t in placed), "replacements must avoid drained nodes"
        return dt, n_dec, len(replacements), planner, sched, \
            _planner_counter_delta(snap)

    results, retries = run_with_variance_guard(trial, name="storm")
    dts = [r[0] for r in results]
    dt, n_dec, n_repl, planner, sched, routed = min(results,
                                                    key=lambda r: r[0])
    out = {
        "nodes": n_nodes, "tasks": n_tasks,
        "drained_nodes": n_drained,
        "replacements": n_repl,
        "decisions_per_sec": round(n_dec / dt, 1),
        "plan_s": round(planner.stats["plan_seconds"], 3),
        "commit_s": round(sched.stats["commit_seconds"], 3),
        "fallback_groups": routed["groups_fallback"],
        "variance_reruns": retries,
        "compiles": _compile_delta({}),
    }
    if "storm" in _flightrec_dumps:
        out["flightrec_dump"] = _flightrec_dumps["storm"]
    out.update(_spread_stats(dts))
    return out


def run_live_manager(planner_factory, external_firehose=False,
                     n_services=None, n_nodes=None, total_tasks=None):
    """Config 6/7: config-4's scale in PRODUCTION shape — a real
    single-voter raft proposer (on-disk WAL, consensus apply path)
    attached to the store, plus the control plane's subscriber mix
    (dispatcher sessions, orchestrator/reaper loops, metrics collector —
    all in their real block-aware subscription shapes, with live
    consumer threads).  Blocks ride one compact TaskBlockAction per
    chunk through raft and publish one coalesced EventTaskBlock.

    ``n_services`` (default 2, env BENCH_CFG6_SERVICES) services
    splitting ``total_tasks`` (default N_TASKS each) schedule in ONE
    tick — the multi-group shape a live manager actually carries.  Runs
    of fusable groups densify into ONE scan-over-groups program per
    chunk (ops/fusedbatch.py), so the tick pays one device round-trip
    ladder regardless of service count; chunk i+1 computes while group
    i's chunks ride raft (``plan_hidden_frac`` is the overlap
    evidence).  Config 7 reuses this harness at 10 services
    (BENCH_CFG7_* env knobs scale it toward the 1M-task x 50k-node
    target shape on hosts that hold it).

    ``external_firehose`` adds a watch-API-style client consuming EVERY
    task as a synthesized per-task event.  Synthesis runs on the
    consumer's own thread (never the commit path), but this benchmark
    host has ONE core, so the firehose's GIL time lands in the tick
    wall-clock anyway; it is off by default because a real manager has
    no all-task external watcher — the cost scales with what external
    clients actually subscribe to."""
    _trim_heap()
    import shutil
    import tempfile
    import threading

    from swarmkit_tpu.models import Task as _Task, TaskState
    from swarmkit_tpu.state import match
    from swarmkit_tpu.state.raft import LocalNetwork, RaftLogger, RaftNode

    if n_services is None:
        n_services = int(os.environ.get("BENCH_CFG6_SERVICES", 2))
    if n_nodes is None:
        n_nodes = N_NODES
    if total_tasks is None:
        total_tasks = N_TASKS * n_services

    # warm-up at this config's exact fused jit signatures: same node
    # bucket, same service count (group-slot/service-slot buckets), tiny
    # task counts — compiles must never land in the timed tick (tracer
    # off so the compile spans stay out of this config's phase window)
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        warm_store, *_ = build_cluster(n_nodes, 16 * n_services,
                                       n_services=n_services)
        warm_planner = planner_factory()
        warm_planner.enable_small_group_routing = False
        one_tick(warm_store, warm_planner)
        del warm_store, warm_planner
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    store, svc, nodes, tasks = build_cluster(n_nodes, total_tasks,
                                             n_services=n_services)
    tmp = tempfile.mkdtemp(prefix="bench-raft-")
    rn = RaftNode("b0", ["b0"], store,
                  RaftLogger(os.path.join(tmp, "b0")), LocalNetwork())
    store._proposer = rn
    rn.start()
    deadline = time.time() + 15
    while not (rn.is_leader and rn.core.leader_ready):
        if time.time() > deadline:
            raise RuntimeError("bench raft leader not ready")
        time.sleep(0.01)

    from swarmkit_tpu.state.events import EventTaskBlock

    counts = {}
    # the subscriber mix a live manager carries, in each component's real
    # subscription shape: block-aware control loops (orchestrators,
    # reaper, restart — they skip assignment blocks by contract),
    # block-aware dispatcher sessions (per_node membership probes), the
    # metrics collector (cheap per-item histogram shift), and one
    # EXTERNAL watch client in the legacy per-event shape — it pays the
    # per-task synthesis, on its own thread, never the commit path
    subs = {
        # real orchestrator/reaper loops subscribe unfiltered and skip
        # blocks by contract (state<=RUNNING); model that exactly
        "orchestrator": store.queue.subscribe(accepts_blocks=True),
        "reaper": store.queue.subscribe(accepts_blocks=True),
    }
    if external_firehose:
        subs["external_watch"] = store.queue.subscribe(
            match(_Task, actions=("update",)))
    session_nodes = [n.id for n in nodes[:8]]
    for i, nid in enumerate(session_nodes):
        def pred(ev, nid=nid):
            if isinstance(ev, EventTaskBlock):
                return True   # per-node probe runs on the consumer side
            return getattr(getattr(ev, "obj", None), "node_id",
                           None) == nid
        subs[f"session{i}"] = store.queue.subscribe(
            pred, accepts_blocks=True)
    hist = {}
    metrics_sub = store.queue.subscribe(accepts_blocks=True)
    stop = threading.Event()

    # consumers BLOCK on the subscription like the real components do
    # (orchestrator/dispatcher loops wait in Subscription.get, they do
    # not poll) — sleep-polling here both mismodels the components and
    # taxes the tick with periodic GIL wakeups on this 1-core host

    def _blocking_items(sub):
        try:
            head = sub.get(timeout=0.1)
        except TimeoutError:
            return []
        return [head] + sub.drain()

    def consume(name, sub):
        got = 0
        while not stop.is_set():
            for it in _blocking_items(sub):
                if isinstance(it, EventTaskBlock):
                    if name.startswith("session"):
                        nid = session_nodes[int(name[7:])]
                        got += len(it.per_node().get(nid, ()))
                    else:
                        got += len(it)   # control loop: O(1) skip
                else:
                    got += 1
        for it in sub.drain():
            got += len(it) if isinstance(it, EventTaskBlock) else 1
        counts[name] = got

    def consume_metrics(sub):
        got = 0

        def absorb(items):
            nonlocal got
            for it in items:
                if isinstance(it, EventTaskBlock):
                    for old in it.olds:
                        k = int(old.status.state)
                        hist[k] = hist.get(k, 0) - 1
                    hist[it.state] = hist.get(it.state, 0) + len(it)
                    got += len(it)
                else:
                    got += 1

        while not stop.is_set():
            absorb(_blocking_items(sub))
        absorb(sub.drain())   # post-stop tail, like consume()
        counts["metrics"] = got

    threads = [threading.Thread(target=consume, args=(k, s), daemon=True)
               for k, s in subs.items()]
    threads.append(threading.Thread(target=consume_metrics,
                                    args=(metrics_sub,), daemon=True))
    for t in threads:
        t.start()

    try:
        from swarmkit_tpu import native as _native
        from swarmkit_tpu.utils.metrics import registry as _registry
        planner = planner_factory()
        snap = _planner_counter_snapshot()
        fanout_timer = _registry.timer("swarm_watch_fanout_latency")
        fanout0 = fanout_timer.total
        fallbacks0 = _registry.get_counter("swarm_native_commit_fallbacks")
        sched, n_dec, dt = one_tick(store, planner)
        routed = _planner_counter_delta(snap)
        time.sleep(0.2)   # let consumers drain the tail
        stop.set()
        for t in threads:
            t.join(timeout=5)
        n_assigned = sum(
            1 for t in store.view(lambda tx: tx.find(_Task))
            if t.status.state >= TaskState.ASSIGNED and t.node_id)
        assert n_assigned >= total_tasks, \
            f"live-manager: only {n_assigned}/{total_tasks} ASSIGNED"
        # the metrics histogram must balance, and when the firehose
        # client is attached every decision must reach it as a per-task
        # synthesized event
        assert counts["metrics"] >= n_dec, counts
        assert hist.get(int(TaskState.ASSIGNED), 0) >= n_dec, hist
        if external_firehose:
            assert counts["external_watch"] >= n_dec, counts
        return {
            "nodes": n_nodes, "tasks": total_tasks,
            "services": n_services,
            "pipeline_depth": sched.pipeline_depth,
            "decisions": n_dec,
            "decisions_per_sec": round(n_dec / dt, 1),
            "tick_s": round(dt, 3),
            "plan_s": round(planner.stats["plan_seconds"], 3),
            "commit_s": round(sched.stats["commit_seconds"], 3),
            # commit-plane headline fields (ISSUE 13): the commit phase
            # wall, the watch fan-out synthesis cost (consumer side,
            # includes the drain tail), and whether the native commit
            # plane held (a fallback tick inside the timed window means
            # it silently ran Python — bench_compare gates on it)
            "commit_phase_s": round(sched.stats["commit_seconds"], 3),
            "fanout_s": round(fanout_timer.total - fanout0, 3),
            "native_commit": {
                # enabled = the escape hatch (SWARM_NATIVE_COMMIT) was
                # not pulled; active = the C module actually loaded.
                # enabled-but-inactive or any fallback tick inside the
                # timed window fails bench_compare's native-commit gate.
                "enabled": _native.commit_enabled(),
                "active": _native.get() is not None,
                "fallbacks": int(_registry.get_counter(
                    "swarm_native_commit_fallbacks") - fallbacks0),
            },
            "fallback_groups": routed["groups_fallback"],
            "groups_fused": routed["groups_fused"],
            "mesh_devices": (planner.mesh.shape["nodes"]
                             if getattr(planner, "mesh", None) is not None
                             else 1),
            "raft_entries_applied": rn.stats["applied"],
            "events_delivered": dict(counts),
            "path": "device+raft+watchers",
            "compiles": _compile_delta(snap),
        }
    finally:
        stop.set()
        rn.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_priority_jobs(planner_factory):
    """Config 8: services + jobs + 3 priority bands on a FULL cluster —
    the priority & preemption subsystem's production shape.  512 nodes
    (8 cpu each, 4 slots at the 2-cpu reservation) run 1800 priority-0
    tasks; a 400-task priority-2 band, a 120-task priority-1 band and a
    64-completion replicated job (priority 1) then arrive in ONE tick.
    Free capacity covers less than half of them, so the tick's
    preemption pass (device victim kernel, ops/preempt.py) must evict
    ~336 low-band tasks to place every arrival — the bench asserts all
    arrivals ASSIGNED and reports the ``swarm_preemptions`` delta,
    which scripts/bench_compare.py gates on appearing with ZERO
    planner-compile growth in the timed window (the warm-up pass below
    covers every (NB, V, PB) victim-kernel signature)."""
    _trim_heap()
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState,
        NodeStatus, ReplicatedService, Resources, ResourceRequirements,
        Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.models.specs import ReplicatedJob
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id
    from swarmkit_tpu.utils.metrics import registry as _reg

    N_N = int(os.environ.get("BENCH_CFG8_NODES", 512))
    CPU = 2 * 10 ** 9
    MEM = 1 << 30
    N_LO, N_HI, N_MID, N_JOB = 1800, 400, 120, 64

    def build():
        store = MemoryStore()
        nodes = []
        for i in range(N_N):
            nodes.append(Node(
                id=new_id(),
                spec=NodeSpec(annotations=Annotations(name=f"p{i:04d}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"p{i:04d}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 << 30))))
        res = ResourceRequirements(
            reservations=Resources(nano_cpus=CPU, memory_bytes=MEM))
        bands = {"lo": (0, N_LO), "hi": (2, N_HI), "mid": (1, N_MID)}
        specs = {name: TaskSpec(resources=res, priority=prio)
                 for name, (prio, _n) in bands.items()}
        tasks = []
        svcs = []
        for name, (prio, count) in bands.items():
            svc = Service(
                id=new_id(),
                spec=ServiceSpec(
                    annotations=Annotations(name=f"band-{name}"),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=count),
                    task=specs[name]),
                spec_version=Version(index=1))
            svcs.append(svc)
            for s in range(count):
                t = Task(id=new_id(), service_id=svc.id, slot=s + 1,
                         desired_state=TaskState.RUNNING,
                         spec=specs[name], spec_version=Version(index=1),
                         status=TaskStatus(state=TaskState.PENDING))
                if name == "lo":   # the resident band: already RUNNING
                    t.node_id = nodes[s % N_N].id
                    t.status = TaskStatus(state=TaskState.RUNNING)
                tasks.append(t)
        job_spec = TaskSpec(resources=res, priority=1)
        job = Service(
            id=new_id(),
            spec=ServiceSpec(
                annotations=Annotations(name="band-job"),
                mode=ServiceMode.REPLICATED_JOB,
                replicated_job=ReplicatedJob(total_completions=N_JOB),
                task=job_spec),
            spec_version=Version(index=1))
        svcs.append(job)
        for s in range(N_JOB):
            tasks.append(Task(
                id=new_id(), service_id=job.id, slot=s,
                desired_state=TaskState.COMPLETE, spec=job_spec,
                spec_version=Version(index=1),
                job_iteration=Version(index=0),
                status=TaskStatus(state=TaskState.PENDING)))

        def mk(tx):
            for n in nodes:
                tx.create(n)
            for s in svcs:
                tx.create(s)
        store.update(mk)
        store.update(lambda tx: (
            [tx.create(t) for t in tasks] and None))
        return store

    def one_pass(store):
        planner = planner_factory()
        sched = Scheduler(store, batch_planner=planner,
                          preempt_budget=512)
        store.view(sched._setup_tasks_list)
        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        n_dec = sched.tick()
        dt = time.perf_counter() - t0
        gc.unfreeze()
        return sched, planner, n_dec, dt

    # warm-up: the identical workload once, tracer off — covers every
    # planner AND victim-kernel jit signature this config touches
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        one_pass(build())
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    store = build()
    snap = _planner_counter_snapshot()
    pre0 = _reg.get_counter('swarm_preemptions{reason="priority"}')
    sched, planner, n_dec, dt = one_pass(store)
    preemptions = int(
        _reg.get_counter('swarm_preemptions{reason="priority"}') - pre0)
    routed = _planner_counter_delta(snap)

    pending_bands = N_HI + N_MID + N_JOB
    placed = sum(
        1 for t in store.view(lambda tx: tx.find(Task))
        if t.node_id and t.status.state >= TaskState.ASSIGNED
        and t.desired_state <= TaskState.COMPLETE)
    assert placed >= N_LO - preemptions + pending_bands, \
        f"cfg8: only {placed} live placed (preemptions={preemptions})"
    assert preemptions > 0, "cfg8 ran without a single preemption"
    return {
        "nodes": N_N, "tasks": N_LO + pending_bands,
        "pending_arrivals": pending_bands,
        "priority_bands": 3,
        "decisions": n_dec,
        "decisions_per_sec": round(n_dec / dt, 1),
        "tick_s": round(dt, 3),
        "plan_s": round(planner.stats["plan_seconds"], 3),
        "commit_s": round(sched.stats["commit_seconds"], 3),
        "preemptions": preemptions,
        "fallback_groups": routed["groups_fallback"],
        "path": "device+preempt",
        "shape_cost_x": 1.0,
        "compiles": _compile_delta(snap),
    }


def run_autoscale_tenant_storm(planner_factory):
    """Config 9: autoscaler + tenant QoS under a burst (ISSUE 12).  256
    nodes run a high-band tenant (400 tasks, must all place) while a
    quota'd low-band tenant bursts: one service asks 500 tasks against
    a 300-task quota (admission clamps the overflow), a second same-
    tenant service's whole group arrives with the tenant exhausted —
    the DEVICE quota-mask column rejects it end to end.  The timed
    window covers one autoscaler drive (the supervisor's decision
    write) plus the storm tick; scripts/bench_compare.py gates on
    ``quota_clamps`` > 0 with ZERO XLA compiles inside the window (the
    warm-up pass below covers the quota-mask signatures)."""
    _trim_heap()
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState,
        NodeStatus, ReplicatedService, Resources, ResourceRequirements,
        Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.models.specs import AutoscaleConfig
    from swarmkit_tpu.models.objects import Cluster
    from swarmkit_tpu.models.specs import ClusterSpec
    from swarmkit_tpu.models.types import TenantQuota
    from swarmkit_tpu.orchestrator.autoscaler import (
        Supervisor as AutoscaleSupervisor,
    )
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.scheduler.quota import TENANT_LABEL
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id

    N_N = int(os.environ.get("BENCH_CFG9_NODES", 256))
    CPU = 2 * 10 ** 9
    # band sizes derive from capacity (4 slots per 8-cpu node) so the
    # config scales with BENCH_CFG9_NODES: the high band + the burst
    # tenant's quota together stay ~70% of the cluster — the blocked
    # service must fail on QUOTA, not on capacity
    slots = N_N * 4
    N_HI = slots * 2 // 5
    QUOTA_TASKS = slots * 3 // 10
    N_BURST = QUOTA_TASKS + max(slots // 5, 50)
    N_BLOCKED = max(slots // 8, 16)

    def build():
        store = MemoryStore()
        store.update(lambda tx: tx.create(Cluster(
            id=new_id(),
            spec=ClusterSpec(
                annotations=Annotations(name="default"),
                tenants={
                    "burst": TenantQuota(nano_cpus=QUOTA_TASKS * CPU),
                    "hi": TenantQuota(nano_cpus=1000 * CPU)}))))

        def mk_nodes(tx):
            for i in range(N_N):
                tx.create(Node(
                    id=new_id(),
                    spec=NodeSpec(
                        annotations=Annotations(name=f"q{i:04d}")),
                    status=NodeStatus(state=NodeState.READY),
                    description=NodeDescription(
                        hostname=f"q{i:04d}",
                        resources=Resources(nano_cpus=8 * 10 ** 9,
                                            memory_bytes=32 << 30))))
        store.update(mk_nodes)
        res = ResourceRequirements(
            reservations=Resources(nano_cpus=CPU, memory_bytes=1 << 30))
        plan = (("hi", "hi", 2, N_HI, None),
                ("burst", "burst", 0, N_BURST,
                 AutoscaleConfig(min_replicas=2, max_replicas=N_BURST,
                                 target_utilization=1.0,
                                 stabilization_window=0.0)),
                ("blocked", "burst", 0, N_BLOCKED, None))
        svcs = {}

        def mk_svcs(tx):
            for name, tenant, prio, count, autoscale in plan:
                spec = TaskSpec(resources=res, priority=prio)
                svc = Service(
                    id=new_id(),
                    spec=ServiceSpec(
                        annotations=Annotations(
                            name=f"t-{name}",
                            labels={TENANT_LABEL: tenant}),
                        mode=ServiceMode.REPLICATED,
                        # the burst service starts small so the timed
                        # autoscaler drive commits a real scale-up
                        # decision against the sampled load
                        replicated=ReplicatedService(
                            replicas=2 if autoscale else count),
                        task=spec,
                        autoscale=autoscale),
                    spec_version=Version(index=1))
                svcs[name] = svc
                tx.create(svc)
        store.update(mk_svcs)

        def mk_tasks(tx):
            for name, _tenant, prio, count, _a in plan:
                svc = svcs[name]
                for s in range(count):
                    tx.create(Task(
                        id=new_id(), service_id=svc.id, slot=s + 1,
                        desired_state=TaskState.RUNNING,
                        spec=svc.spec.task,
                        spec_version=Version(index=1),
                        service_annotations=svc.spec.annotations,
                        status=TaskStatus(state=TaskState.PENDING)))
        store.update(mk_tasks)
        return store, svcs

    def one_pass(store, svcs):
        planner = planner_factory()
        sched = Scheduler(store, batch_planner=planner)
        store.view(sched._setup_tasks_list)
        scaler = AutoscaleSupervisor(
            store,
            sampler=lambda sid: {"load": float(N_BURST)}
            if sid == svcs["burst"].id else None,
            start_worker=False)
        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        scaler.drive()
        n_dec = sched.tick()
        dt = time.perf_counter() - t0
        gc.unfreeze()
        return sched, planner, scaler, n_dec, dt

    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        one_pass(*build())   # warm-up: every jit signature incl. quota
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    store, svcs = build()
    snap = _planner_counter_snapshot()
    sched, planner, scaler, n_dec, dt = one_pass(store, svcs)
    routed = _planner_counter_delta(snap)
    clamps = sched.stats.get("quota_clamps", 0)
    assert scaler.stats["decisions"] > 0, \
        "cfg9 autoscaler made no decision in the timed window"

    tasks = store.view(lambda tx: tx.find(Task))
    by_svc = {}
    for t in tasks:
        if t.node_id and t.status.state >= TaskState.ASSIGNED:
            by_svc[t.service_id] = by_svc.get(t.service_id, 0) + 1
    placed_hi = by_svc.get(svcs["hi"].id, 0)
    placed_burst = by_svc.get(svcs["burst"].id, 0)
    placed_blocked = by_svc.get(svcs["blocked"].id, 0)
    assert placed_hi == N_HI, \
        f"cfg9: high band placed {placed_hi}/{N_HI}"
    assert placed_burst <= QUOTA_TASKS, \
        f"cfg9: burst tenant exceeded its quota ({placed_burst})"
    assert clamps > 0, "cfg9 ran without a single quota clamp"
    assert placed_blocked == 0, \
        f"cfg9: exhausted tenant still placed {placed_blocked}"
    blocked_err = next(
        (t.status.err for t in tasks
         if t.service_id == svcs["blocked"].id), "")
    assert "over tenant quota" in (blocked_err or ""), blocked_err
    return {
        "nodes": N_N, "tasks": N_HI + N_BURST + N_BLOCKED,
        "tenants": 2,
        "decisions": n_dec,
        "decisions_per_sec": round(n_dec / dt, 1),
        "tick_s": round(dt, 3),
        "plan_s": round(planner.stats["plan_seconds"], 3),
        "commit_s": round(sched.stats["commit_seconds"], 3),
        "quota_clamps": clamps,
        "autoscale_decisions": scaler.stats["decisions"],
        "fallback_groups": routed["groups_fallback"],
        "path": "device+quota-mask",
        "shape_cost_x": 1.0,
        "compiles": _compile_delta(snap),
    }


def run_steady_state_churn(planner_factory):
    """Config 10: SUSTAINED decisions/sec under Poisson churn — the
    streaming scheduler's production shape (ISSUE 14).  A big cluster
    sits in steady state (base tasks RUNNING everywhere) while every
    window brings small Poisson batches of arrivals and exits; each
    window ends in one scheduler tick driven through the real store
    watch feed (the streaming delta source).  The SAME seeded workload
    runs twice: once with the streaming plane on (device-resident node
    state, dirty-row refresh) and once forced to full replans
    (``SWARM_STREAMING_PLANNER=0`` posture) — the headline is the
    sustained-rate ratio, and placements must be byte-identical
    between the two passes.  scripts/bench_compare.py gates on the
    streaming plane being ACTIVE (incremental ticks > 0), zero XLA
    compiles inside the timed windows, and the pending->assigned p99
    not regressing >20% run-over-run (the obs lifecycle timer,
    measured per window from the same watch feed)."""
    _trim_heap()
    import random as _random
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState,
        NodeStatus, ReplicatedService, Resources, ResourceRequirements,
        Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.models.types import now
    from swarmkit_tpu.obs import devicetelemetry as _devtel
    from swarmkit_tpu.obs.lifecycle import LifecycleTracker
    from swarmkit_tpu.utils.sampling import poisson as _poisson
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.state.events import Event, EventSnapshotRestore
    from swarmkit_tpu.utils.metrics import Registry

    from swarmkit_tpu.models import Placement, PlacementPreference, \
        Platform, SpreadOver

    N_N = int(os.environ.get("BENCH_CFG10_NODES", 8192))
    N_BASE = int(os.environ.get("BENCH_CFG10_BASE_TASKS", 12_000))
    WINDOWS = int(os.environ.get("BENCH_CFG10_WINDOWS", 12))
    SEED = int(os.environ.get("BENCH_CFG10_SEED", 1))
    CPU = 10 ** 8
    MEM = 64 << 20
    SVCS = ("ca", "cb", "cc", "cd", "ce", "cf")
    LAM_ARRIVE = 40.0      # for the window's (rotating) arrival service
    LAM_EXIT = 18.0        # per window

    # production spec shapes: constraints, platform requirements and a
    # spread preference — the per-group column builders these demand
    # (constraint/platform hash columns, spread leaves) are exactly the
    # feasibility-mask precursors the resident state keeps, so the
    # full-replan side pays their O(cluster) Python densification per
    # tick while the streaming side refreshes dirty rows
    res = ResourceRequirements(
        reservations=Resources(nano_cpus=CPU, memory_bytes=MEM))
    specs = {
        "ca": TaskSpec(resources=res),
        "cb": TaskSpec(resources=res, placement=Placement(
            constraints=["node.labels.tier==web"],
            platforms=[Platform(os="linux", architecture="amd64")])),
        "cc": TaskSpec(resources=res, placement=Placement(
            preferences=[PlacementPreference(spread=SpreadOver(
                spread_descriptor="node.labels.rack"))])),
        "cd": TaskSpec(resources=res, placement=Placement(
            constraints=["node.labels.rack!=r03"],
            platforms=[Platform(os="linux", architecture="amd64")])),
        "ce": TaskSpec(resources=res, placement=Placement(
            constraints=["node.hostname!=c99999"],
            platforms=[Platform(os="linux", architecture="amd64")],
            preferences=[PlacementPreference(spread=SpreadOver(
                spread_descriptor="node.labels.rack"))])),
        "cf": TaskSpec(resources=res, placement=Placement(
            constraints=["node.labels.rack!=r07"],
            platforms=[Platform(os="linux", architecture="amd64")])),
    }
    # arrivals rotate over the production-shaped services; the plain
    # service stays as base load
    ARRIVE_SVCS = ("cb", "cc", "cd", "ce", "cf")

    def workload_script(windows):
        """Precompute the whole churn (seeded) so both passes replay
        byte-identical arrivals/exits.  Each window's arrivals hit ONE
        (rotating) service — the steady-state shape: small bursts, not
        every service at once, so the full-replan side re-densifies the
        whole cluster for a single group's worth of decisions."""
        rng = _random.Random(SEED)
        script = []
        for w in range(windows):
            sid = ARRIVE_SVCS[w % len(ARRIVE_SVCS)]
            arrivals = {sid: max(1, _poisson(rng, LAM_ARRIVE))}
            script.append((arrivals, _poisson(rng, LAM_EXIT)))
        return script

    def build():
        store = MemoryStore()
        nodes = [Node(
            id=f"c{i:05d}",
            spec=NodeSpec(annotations=Annotations(
                name=f"c{i:05d}",
                labels={"tier": "web" if i % 2 else "db",
                        "rack": f"r{i % 16:02d}"})),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname=f"c{i:05d}",
                platform=Platform(os="linux", architecture="amd64"),
                resources=Resources(nano_cpus=8 * 10 ** 9,
                                    memory_bytes=32 << 30)))
            for i in range(N_N)]
        store.update(lambda tx: [tx.create(n) for n in nodes])

        def mk_svcs(tx):
            for sid in SVCS:
                tx.create(Service(
                    id=sid,
                    spec=ServiceSpec(
                        annotations=Annotations(name=sid),
                        mode=ServiceMode.REPLICATED,
                        replicated=ReplicatedService(replicas=0),
                        task=specs[sid]),
                    spec_version=Version(index=1)))
        store.update(mk_svcs)

        def mk_base(tx):
            for k in range(N_BASE):
                sid = SVCS[k % len(SVCS)]
                tx.create(Task(
                    id=f"{sid}-base{k:06d}", service_id=sid,
                    slot=k + 1, desired_state=TaskState.RUNNING,
                    spec=specs[sid], spec_version=Version(index=1),
                    node_id=nodes[k % N_N].id,
                    status=TaskStatus(state=TaskState.RUNNING)))
        store.update(mk_base)
        return store

    def one_pass(streaming, windows):
        store = build()
        planner = planner_factory()
        planner.enable_small_group_routing = False
        planner.streaming_enabled = streaming
        sched = Scheduler(store, batch_planner=planner,
                          pipeline_depth=1)
        _, sub = store.view_and_watch(
            lambda tx: sched._setup_tasks_list(tx), accepts_blocks=True)
        lreg = Registry()
        lt = LifecycleTracker(registry=lreg)
        seqs = {sid: 0 for sid in SVCS}
        script = workload_script(windows)

        def pump():
            while True:
                ev = sub.poll()
                if ev is None:
                    return
                lt.handle_event(ev)
                if isinstance(ev, EventSnapshotRestore):
                    sched._resync()
                elif isinstance(ev, Event):
                    sched._handle_event(ev)

        def add(sid, n):
            spec = specs[sid]
            base = seqs[sid]

            def cb(tx):
                ts = now()
                for k in range(n):
                    tx.create(Task(
                        id=f"{sid}-a{base + k:06d}", service_id=sid,
                        slot=N_BASE + base + k + 1,
                        desired_state=TaskState.RUNNING, spec=spec,
                        spec_version=Version(index=1),
                        status=TaskStatus(state=TaskState.PENDING,
                                          timestamp=ts)))
            store.update(cb)
            seqs[sid] = base + n

        exited = {"n": 0}

        def exit_some(k):
            # deterministic victims: oldest base tasks first — the
            # same ids in both passes
            start = exited["n"]
            victims = [f"{SVCS[j % len(SVCS)]}-base{j:06d}"
                       for j in range(start, min(start + k, N_BASE))]
            exited["n"] = start + len(victims)

            def cb(tx):
                ts = now()
                for tid in victims:
                    cur = tx.get(Task, tid)
                    if cur is None:
                        continue
                    cur = cur.copy()
                    cur.status = TaskStatus(state=TaskState.COMPLETE,
                                            timestamp=ts,
                                            message="churn exit")
                    tx.update(cur)
            store.update(cb)

        sched.tick()   # cold tick outside the timed window
        gc.collect()
        gc.freeze()
        decisions = 0
        # per-reason transfer ledger around the steady-state windows
        # only: the cold tick's full upload stays out, so the delta IS
        # the steady-state churn cost the transfer-regression gate reads
        xfer_before = _devtel.snapshot()["transfers"]
        t0 = time.perf_counter()
        for arrivals, exits in script:
            for sid, n in arrivals.items():
                if n:
                    add(sid, n)
            if exits:
                exit_some(exits)
            pump()
            decisions += sched.tick()
        dt = time.perf_counter() - t0
        xfer_after = _devtel.snapshot()["transfers"]
        xfer = {
            d: {r: {k: row[k] - xfer_before.get(d, {}).get(r, {})
                    .get(k, 0) for k in row}
                for r, row in tbl.items()}
            for d, tbl in xfer_after.items()}
        gc.unfreeze()
        pump()
        store.queue.unsubscribe(sub)
        placements = sorted(
            (t.id, t.node_id) for t in store.view(
                lambda tx: tx.find(Task)))
        import hashlib
        digest = hashlib.sha256(
            repr(placements).encode()).hexdigest()
        edge = lt.summary().get("pending->assigned", {})
        return (sched, planner, decisions, dt, digest,
                edge.get("p99"), xfer)

    # warm-up: both postures once, tracer off — covers every planner
    # jit signature (incl. the streaming scatter buckets) this config
    # touches
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        one_pass(True, 3)
        one_pass(False, 2)
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    snap = _planner_counter_snapshot()
    (sched_s, planner_s, dec_s, dt_s, digest_s,
     p99_s, xfer_s) = one_pass(True, WINDOWS)
    (_sched_f, planner_f, dec_f, dt_f, digest_f,
     _p99_f, _xfer_f) = one_pass(False, WINDOWS)
    routed = _planner_counter_delta(snap)
    compiles = _compile_delta(snap)

    assert dec_s == dec_f, (dec_s, dec_f)
    assert digest_s == digest_f, \
        "cfg10: streaming placements diverged from full-replan"
    st = planner_s.streaming_snapshot()
    assert st["enabled"] and st["incremental_ticks"] > 0, st
    assert not planner_f.streaming_snapshot()["enabled"]
    dps_s = dec_s / dt_s if dt_s else 0.0
    dps_f = dec_f / dt_f if dt_f else 0.0
    return {
        "nodes": N_N, "base_tasks": N_BASE, "windows": WINDOWS,
        "decisions": dec_s,
        "decisions_per_sec": round(dps_s, 1),
        "full_replan_decisions_per_sec": round(dps_f, 1),
        "streaming_speedup": round(dps_s / dps_f, 2) if dps_f else None,
        "tick_s": round(dt_s, 3),
        "plan_s": round(planner_s.stats["plan_seconds"], 3),
        "commit_s": round(sched_s.stats["commit_seconds"], 3),
        "pending_assigned_p99_s": round(p99_s, 4)
        if p99_s is not None else None,
        "placements_identical": digest_s == digest_f,
        "streaming": st,
        "device_transfers": xfer_s,
        "h2d_bytes_per_tick": round(
            sum(r["bytes"] for r in xfer_s.get("h2d", {}).values())
            / float(WINDOWS), 1),
        # the resident-tier slice of that ledger: dirty-row scatters
        # (single-device and sharded) plus wide re-uploads.  Under a
        # planner mesh this is what the mesh-resident-transfer gate
        # pins at ~0 — churn must ride per-shard donated scatters,
        # not re-uploads
        "planner_mesh": _mesh_devices(),
        "resident_h2d_bytes_per_tick": round(
            sum(r["bytes"] for name, r in xfer_s.get("h2d", {}).items()
                if name in ("dirty_scatter", "shard_scatter",
                            "wide_reupload")) / float(WINDOWS), 1),
        "strategy_host_groups": int(
            planner_s.stats.get("groups_strategy_host", 0)
            + planner_f.stats.get("groups_strategy_host", 0)),
        "fallback_groups": routed["groups_fallback"],
        "path": "device+streaming",
        "shape_cost_x": 1.0,
        "compiles": compiles,
    }


def run_fragmentation(planner_factory):
    """Config 11: placement-strategy fragmentation (ISSUE 15).  400
    uniform nodes (16 cpu) receive mixed-size replicas — 800 small
    (1 cpu), 300 medium (4 cpu), 200 large (8 cpu) plus a 100-task
    node.ip-CIDR-constrained service (the closed device-path waiver:
    ``fallback_groups`` must stay 0) — in ONE tick, twice: every
    service under the ``spread`` strategy, then the identical workload
    under ``binpack``.  Reported per pass: decisions/sec (the spread
    pass is "spread through the strategy seam" — bench_compare gates
    its regression at 10%) and the STRANDED-CAPACITY fraction: the
    share of free cpu sitting on partially-loaded nodes in slices too
    small to hold one more large replica.  bench_compare gates
    binpack < spread on that fraction, zero strategy fallbacks, and
    compile-flat timed windows (the warm-up pass covers the strategy
    kernels' signatures)."""
    _trim_heap()
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState,
        NodeStatus, Placement, ReplicatedService, Resources,
        ResourceRequirements, Service, ServiceMode, ServiceSpec, Task,
        TaskSpec, TaskState, TaskStatus, Version,
    )
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id
    from swarmkit_tpu.utils.metrics import registry as _reg

    N_N = int(os.environ.get("BENCH_CFG11_NODES", 400))
    CPU_UNIT = 10 ** 9
    NODE_CPU = 16 * CPU_UNIT
    LARGE_D = 8 * CPU_UNIT
    MIXES = (("small", 1, 800), ("medium", 4, 300), ("large", 8, 200))
    N_IP = 100

    def build(strategy):
        store = MemoryStore()
        nodes = []
        for i in range(N_N):
            # two /16s: the CIDR-constrained service may only use 10.0/16
            addr = f"10.{i % 2}.{(i // 2) // 250}.{(i // 2) % 250 + 1}"
            nodes.append(Node(
                id=new_id(),
                spec=NodeSpec(annotations=Annotations(name=f"f{i:04d}")),
                status=NodeStatus(state=NodeState.READY, addr=addr),
                description=NodeDescription(
                    hostname=f"f{i:04d}",
                    resources=Resources(nano_cpus=NODE_CPU,
                                        memory_bytes=64 << 30))))
        svcs, tasks = [], []

        def add_service(name, cpus, count, constraints=None):
            spec = TaskSpec(
                resources=ResourceRequirements(reservations=Resources(
                    nano_cpus=cpus * CPU_UNIT,
                    memory_bytes=(cpus << 30) // 4)),
                placement=Placement(constraints=constraints or [],
                                    strategy=strategy))
            svc = Service(
                id=new_id(),
                spec=ServiceSpec(annotations=Annotations(name=name),
                                 mode=ServiceMode.REPLICATED,
                                 replicated=ReplicatedService(
                                     replicas=count),
                                 task=spec),
                spec_version=Version(index=1))
            svcs.append(svc)
            for s in range(count):
                tasks.append(Task(
                    id=new_id(), service_id=svc.id, slot=s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING)))

        for name, cpus, count in MIXES:
            add_service(f"frag-{name}", cpus, count)
        add_service("frag-ip", 1, N_IP,
                    constraints=["node.ip==10.0.0.0/16"])

        def mk(tx):
            for n in nodes:
                tx.create(n)
            for s in svcs:
                tx.create(s)
        store.update(mk)
        store.update(lambda tx: (
            [tx.create(t) for t in tasks] and None))
        n_tasks = sum(c for _, _, c in MIXES) + N_IP
        return store, n_tasks

    def one_pass(strategy):
        store, n_tasks = build(strategy)
        planner = planner_factory()
        sched = Scheduler(store, batch_planner=planner)
        store.view(sched._setup_tasks_list)
        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        n_dec = sched.tick()
        dt = time.perf_counter() - t0
        gc.unfreeze()
        placed = sum(
            1 for t in store.view(lambda tx: tx.find(Task))
            if t.node_id and t.status.state >= TaskState.ASSIGNED)
        assert placed == n_tasks, \
            f"cfg11/{strategy}: {placed}/{n_tasks} placed"
        # stranded capacity: free cpu on PARTIALLY loaded nodes in
        # slices too small for one more large replica, as a fraction
        # of all free cpu
        free = [info.available_resources.nano_cpus
                for info in sched.node_set.nodes.values()]
        total_free = sum(free)
        stranded = sum(f for f in free if 0 < f < LARGE_D)
        frac = stranded / total_free if total_free else 0.0
        ip_nodes = {t.node_id for t in store.view(
            lambda tx: tx.find(Task))
            if t.node_id and t.spec.placement
            and t.spec.placement.constraints}
        addr_of = {n.id: n.status.addr for n in store.view(
            lambda tx: tx.find(Node))}
        assert all(addr_of[nid].startswith("10.0.")
                   for nid in ip_nodes), "cfg11: CIDR constraint leaked"
        return planner, sched, n_dec, dt, frac

    # warm-up: both strategies once, tracer off — covers the spread
    # AND strategy-kernel jit signatures this config touches
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        one_pass("spread")
        one_pass("binpack")
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    snap = _planner_counter_snapshot()
    fb0 = sum(_reg.get_counter(
        f'swarm_strategy_fallbacks{{strategy="{s}"}}')
        for s in ("spread", "binpack"))
    dev0 = _reg.get_counter(
        'swarm_strategy_groups{route="device",strategy="binpack"}')
    _, _, dec_sp, dt_sp, frac_sp = one_pass("spread")
    planner_bp, _, dec_bp, dt_bp, frac_bp = one_pass("binpack")
    routed = _planner_counter_delta(snap)
    fallbacks = int(sum(_reg.get_counter(
        f'swarm_strategy_fallbacks{{strategy="{s}"}}')
        for s in ("spread", "binpack")) - fb0)
    binpack_device_groups = int(_reg.get_counter(
        'swarm_strategy_groups{route="device",strategy="binpack"}')
        - dev0)
    return {
        "nodes": N_N,
        "tasks": sum(c for _, _, c in MIXES) + N_IP,
        "decisions": dec_sp,
        "decisions_per_sec": round(dec_sp / dt_sp, 1),
        "spread_decisions_per_sec": round(dec_sp / dt_sp, 1),
        "binpack_decisions_per_sec": round(dec_bp / dt_bp, 1),
        "stranded_frac_spread": round(frac_sp, 4),
        "stranded_frac_binpack": round(frac_bp, 4),
        "stranded_improvement_x": round(frac_sp / frac_bp, 2)
        if frac_bp else None,
        "strategy_fallbacks": fallbacks,
        "binpack_device_groups": binpack_device_groups,
        "tick_s": round(dt_sp, 3),
        "fallback_groups": routed["groups_fallback"],
        "path": "device+strategy",
        "shape_cost_x": 1.0,
        "compiles": _compile_delta(snap),
    }


def run_gang_pipeline(planner_factory):
    """Config 12: gang scheduling & pipeline workflows (ISSUE 16).
    400 uniform nodes (16 cpu) receive a mixed gang fleet — 24
    single-service gangs of 8 (4-cpu members), 40 of 4 (2-cpu), and
    8 cross-service gangs of 8 stitched by ``gang_id`` (the fused
    ``gang_fit`` route) — plus a 3-stage pipeline a -> b -> c (120
    replicas each).  Tick 1 admits every gang atomically and places
    stage a while b and c hold at the DAG gate; releasing b then c
    drains the pipeline over two more ticks.  The identical workload
    with gang/pipeline fields stripped runs the plain path in one
    tick for comparison.  bench_compare gates: zero partially-placed
    gangs, zero gang deferrals, the gate actually held (gated
    deferrals > 0) then drained, device gang route (0 host-oracle
    verdicts), compile-flat timed windows, and the gang tick's dec/s
    within 4x of the plain tick's."""
    _trim_heap()
    from swarmkit_tpu.models import (
        Annotations, GangConfig, Node, NodeDescription, NodeSpec,
        NodeState, NodeStatus, PipelineStatus, Placement,
        ReplicatedService, Resources, ResourceRequirements, Service,
        ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.scheduler import Scheduler
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id
    from swarmkit_tpu.utils.metrics import registry as _reg

    N_N = int(os.environ.get("BENCH_CFG12_NODES", 400))
    CPU_UNIT = 10 ** 9
    NODE_CPU = 16 * CPU_UNIT
    GANGS = (("gang8", 24, 8, 4), ("gang4", 40, 4, 2))  # name,n,size,cpu
    N_XGANG = 8          # cross-service gangs: 2 services x 4 members
    N_STAGE = 120        # replicas per pipeline stage

    def build(gang):
        store = MemoryStore()
        nodes = [Node(
            id=new_id(),
            spec=NodeSpec(annotations=Annotations(name=f"g{i:04d}")),
            status=NodeStatus(state=NodeState.READY,
                              addr=f"10.{i // 250}.0.{i % 250 + 1}"),
            description=NodeDescription(
                hostname=f"g{i:04d}",
                resources=Resources(nano_cpus=NODE_CPU,
                                    memory_bytes=64 << 30)))
            for i in range(N_N)]
        svcs, tasks = [], []

        def add_service(name, cpus, count, min_size=0, gang_id="",
                        depends_on=()):
            placement = (Placement(gang=GangConfig(min_size=min_size))
                         if gang and min_size else Placement())
            spec = TaskSpec(
                resources=ResourceRequirements(reservations=Resources(
                    nano_cpus=cpus * CPU_UNIT,
                    memory_bytes=(cpus << 30) // 4)),
                placement=placement,
                gang_id=gang_id if gang else "")
            svc = Service(
                id=new_id(),
                spec=ServiceSpec(
                    annotations=Annotations(name=name),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=count),
                    task=spec,
                    depends_on=list(depends_on) if gang else []),
                spec_version=Version(index=1))
            svcs.append(svc)
            for s in range(count):
                tasks.append(Task(
                    id=new_id(), service_id=svc.id, slot=s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING)))

        for prefix, n_gangs, size, cpus in GANGS:
            for g in range(n_gangs):
                add_service(f"{prefix}-{g:02d}", cpus, size,
                            min_size=size)
        for g in range(N_XGANG):
            for half in "ab":
                add_service(f"xgang-{g}{half}", 2, 4, min_size=8,
                            gang_id=f"xg{g}")
        add_service("stage-a", 1, N_STAGE)
        add_service("stage-b", 1, N_STAGE, depends_on=("stage-a",))
        add_service("stage-c", 1, N_STAGE, depends_on=("stage-b",))

        def mk(tx):
            for n in nodes:
                tx.create(n)
            for s in svcs:
                tx.create(s)
        store.update(mk)
        store.update(lambda tx: (
            [tx.create(t) for t in tasks] and None))
        return store, svcs, len(tasks)

    def release(store, svcs, name):
        sid = next(s.id for s in svcs
                   if s.spec.annotations.name == name)

        def cb(tx):
            cur = tx.get(Service, sid).copy()
            cur.pipeline_status = PipelineStatus(state="released")
            tx.update(cur)
        store.update(cb)

    def placed_ids(store):
        return {t.id for t in store.view(lambda tx: tx.find(Task))
                if t.node_id and t.status.state >= TaskState.ASSIGNED}

    def one_pass(gang):
        store, svcs, n_tasks = build(gang)
        planner = planner_factory()
        sched = Scheduler(store, batch_planner=planner)
        store.view(sched._setup_tasks_list)
        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        dec1 = sched.tick()
        dt1 = time.perf_counter() - t0
        gc.unfreeze()
        placed1 = placed_ids(store)
        gated = 0
        if gang:
            # gate evidence: b and c held at tick 1, then drain after
            # their releases — the DAG-gated rollout end to end
            by_svc = {s.id: s.spec.annotations.name for s in svcs}
            gated = sum(
                1 for t in store.view(lambda tx: tx.find(Task))
                if t.id not in placed1
                and by_svc[t.service_id] in ("stage-b", "stage-c"))
            release(store, svcs, "stage-b")
            sched.tick()
            release(store, svcs, "stage-c")
            sched.tick()
        n_placed = len(placed_ids(store))
        assert n_placed == n_tasks, \
            f"cfg12/gang={gang}: {n_placed}/{n_tasks} placed"
        # atomicity evidence: every gang unit fully placed or fully
        # pending after tick 1 — a strict subset is a violation
        partial = 0
        if gang:
            from swarmkit_tpu.scheduler.gang import gang_unit, is_gang
            units = {}
            for t in store.view(lambda tx: tx.find(Task)):
                if is_gang(t):
                    units.setdefault(gang_unit(t), []).append(
                        t.id in placed1)
            partial = sum(1 for flags in units.values()
                          if any(flags) and not all(flags))
        return dec1, dt1, gated, partial

    # warm-up: both shapes once, tracer off — covers the gang_fit
    # (_gf/_gfF) and plain-path jit signatures this config touches
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        one_pass(True)
        one_pass(False)
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    snap = _planner_counter_snapshot()
    base = {k: _reg.get_counter(k) for k in (
        "swarm_gang_admitted", "swarm_gang_deferred",
        "swarm_planner_gang_fit_host",
        "swarm_planner_gang_fit_device",
        "swarm_planner_gang_fit_fused")}
    dec_g, dt_g, gated, partial = one_pass(True)
    dec_p, dt_p, _, _ = one_pass(False)
    delta = {k: int(_reg.get_counter(k) - v) for k, v in base.items()}
    n_gangs = sum(n for _, n, _, _ in GANGS) + N_XGANG
    return {
        "nodes": N_N,
        "tasks": dec_p,
        "decisions": dec_g,
        "decisions_per_sec": round(dec_g / dt_g, 1),
        "gang_decisions_per_sec": round(dec_g / dt_g, 1),
        "plain_decisions_per_sec": round(dec_p / dt_p, 1),
        "gang_vs_plain_x": round((dec_p / dt_p) / (dec_g / dt_g), 2)
        if dec_g else None,
        "gangs": n_gangs,
        "gangs_admitted": delta["swarm_gang_admitted"],
        "gang_deferred": delta["swarm_gang_deferred"],
        "gang_atomicity_violations": partial,
        "gang_fit_host_verdicts": delta["swarm_planner_gang_fit_host"],
        "gang_fit_device_verdicts":
            delta["swarm_planner_gang_fit_device"]
            + delta["swarm_planner_gang_fit_fused"],
        "pipeline_gated_deferrals": gated,
        "pipeline_stages": 3,
        "tick_s": round(dt_g, 3),
        "path": "device+gang",
        "shape_cost_x": 1.0,
        "compiles": _compile_delta(snap),
    }


def run_e2e(n_agents=5,
            n_replicas=int(os.environ.get("BENCH_E2E_REPLICAS", 500))):
    """swarm-bench equivalent: create an N-replica service and measure
    per-task time from service creation to RUNNING status committed
    (reference: cmd/swarm-bench collector.go percentiles)."""
    _trim_heap()
    import time as time_mod

    from swarmkit_tpu.agent import Agent
    from swarmkit_tpu.agent.testutils import TestExecutor
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.manager.dispatcher import Config_
    from swarmkit_tpu.models import TaskState

    # a fresh journey ledger for the e2e window: the headline trials
    # above already filled the cap with their (created-less) tasks,
    # which would refuse every e2e task and starve the attribution
    from swarmkit_tpu.obs.journey import journeys
    journeys.reset(sample_rate=1.0)
    try:
        mgr = Manager(dispatcher_config=Config_(
            heartbeat_period=2.0, process_updates_interval=0.05,
            assignment_batching_wait=0.05))
    except ImportError as e:
        # image without the `cryptography` package (ROADMAP env note):
        # the manager's CA bootstrap is unavailable — report instead of
        # failing the whole bench artifact
        return {"error": f"skipped: {e}"}
    mgr.run()
    agents = []
    try:
        from swarmkit_tpu.models import (
            Annotations, Node, NodeDescription, NodeSpec, NodeState,
            NodeStatus, Resources,
        )
        from swarmkit_tpu.utils import new_id
        for i in range(n_agents):
            node = Node(
                id=new_id(),
                spec=NodeSpec(annotations=Annotations(name=f"bench-w{i}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"bench-w{i}",
                    resources=Resources(nano_cpus=64 * 10**9,
                                        memory_bytes=256 << 30)))
            mgr.store.update(lambda tx, node=node: tx.create(node))
            a = Agent(node.id, TestExecutor(hostname=f"bench-w{i}"),
                      mgr.dispatcher)
            a.start()
            agents.append(a)

        from swarmkit_tpu.models import (
            ReplicatedService, ServiceMode, ServiceSpec, TaskSpec,
        )
        from swarmkit_tpu.models.specs import ContainerSpec

        spec = ServiceSpec(
            annotations=Annotations(name="e2e-bench"),
            task=TaskSpec(container=ContainerSpec(image="bench")),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=n_replicas))
        t_create = time_mod.time()
        svc = mgr.control_api.create_service(spec)

        deadline = time_mod.time() + 120
        latencies = []
        while time_mod.time() < deadline:
            tasks = mgr.control_api.list_tasks(service_id=svc.id)
            done = [t for t in tasks
                    if t.status.state == TaskState.RUNNING
                    and t.desired_state == TaskState.RUNNING]
            if len(done) >= n_replicas:
                # applied_at is stamped by the dispatcher on status commit
                latencies = sorted(
                    (t.status.applied_at or t.status.timestamp) - t_create
                    for t in done)
                break
            time_mod.sleep(0.1)
        if not latencies:
            return {"error": "did not converge"}

        def pct(p):
            return round(latencies[min(len(latencies) - 1,
                                       int(p * len(latencies)))], 3)
        # fold any still-buffered store events, then join journeys into
        # the per-plane attribution of time-to-running p99 (ISSUE 17):
        # which plane the slow cohort's wall time actually sat in
        from swarmkit_tpu.obs import flightrec as _fr
        _fr.poll_store()
        return {
            "agents": n_agents, "replicas": n_replicas,
            "p50_s": pct(0.50), "p90_s": pct(0.90), "p99_s": pct(0.99),
            "max_s": round(latencies[-1], 3),
            "journey_attribution": journeys.critical_path(0.99),
            "journey_summary": journeys.summary(),
        }
    finally:
        for a in agents:
            a.stop()
        mgr.stop()


def run_million_swarm(planner_factory):
    """Config 13: overload-safe serving at fleet scale — >=1k REAL
    dispatcher sessions over ONE threadless dispatcher (batched
    assignment fan-out, bounded session/update/assignment bookkeeping)
    carrying a ~1M-replica fan-out end to end.  Phases: register the
    fleet (heartbeat stretch engages as the session count passes the
    threshold), open every assignment stream, schedule the full replica
    set in one timed tick (compiles must be zero — same warm-up
    discipline as cfg6/7), deliver assignments through the batched
    fan-out, then absorb the status-writeback storm at the bounded
    admission edge: batches that would overflow the buffer are shed
    WHOLE with ErrOverloaded, counted on both sides of the RPC, and
    re-sent by the client next round until every replica is RUNNING —
    degraded, never silently lossy.  Records time-to-running
    percentiles (tick start -> RUNNING committed), the exact
    shed/recovery ledger, heartbeat-stretch evidence, fan-out traffic,
    and the dispatcher/scheduler plane saturation snapshot.
    BENCH_CFG13_* env knobs scale it; defaults hit the 1k-session x
    1M-replica target shape."""
    _trim_heap()
    import time as time_mod

    from swarmkit_tpu.manager.dispatcher import (
        Config_ as _DCfg, Dispatcher, ErrOverloaded,
    )
    from swarmkit_tpu.models import (
        Resources, Task as _Task, TaskState, TaskStatus,
    )
    from swarmkit_tpu.obs.planes import plane as _plane

    n_agents = int(os.environ.get("BENCH_CFG13_AGENTS", 1000))
    n_replicas = int(os.environ.get("BENCH_CFG13_REPLICAS", 1_000_000))
    n_services = int(os.environ.get("BENCH_CFG13_SERVICES", 10))
    pending_cap = int(os.environ.get("BENCH_CFG13_PENDING_CAP", 65_536))
    report_batch = int(os.environ.get("BENCH_CFG13_REPORT_BATCH", 1024))

    # the default bench reservation (0.1 CPU) caps a 64-CPU node at 640
    # tasks — 1000 nodes would top out at 640k replicas.  This config
    # models the 1000x-agent serving shape: light replicas, ~3200/node
    # CPU headroom so the full 1M fan-out fits with imbalance slack
    _rsv = Resources(nano_cpus=2 * 10**7, memory_bytes=16 << 20)

    # warm-up at this config's exact fused jit signatures (same node
    # bucket, same service count) so no compile lands in the timed tick
    from swarmkit_tpu.obs import tracer as _tracer
    was_tracing = _tracer.enabled
    _tracer.disable()
    try:
        warm_store, *_ = build_cluster(n_agents, 16 * n_services,
                                       reservations=_rsv,
                                       n_services=n_services)
        warm_planner = planner_factory()
        warm_planner.enable_small_group_routing = False
        one_tick(warm_store, warm_planner)
        del warm_store, warm_planner
        # second pass with default routing: small/remainder groups may
        # take the single-group kernel at this shape — warm it too
        warm_store, *_ = build_cluster(n_agents, 16 * n_services,
                                       reservations=_rsv,
                                       n_services=n_services)
        one_tick(warm_store, planner_factory())
        del warm_store
        _trim_heap()
    finally:
        _tracer.enabled = was_tracing

    store, svc, nodes, tasks = build_cluster(n_agents, n_replicas,
                                             reservations=_rsv,
                                             n_services=n_services)
    # overload bounds live: session cap just above the fleet (steady
    # registration stays admitted), stretch threshold well under it
    # (the period MUST stretch), update buffer far under the storm
    # (the writeback MUST shed).  max_batch_items sits above the
    # admission bound so the buffer drains on this harness's explicit
    # flush turns, not behind an implicit mid-round flush.
    d = Dispatcher(store, _DCfg(
        heartbeat_period=30.0,
        max_batch_items=pending_cap * 2,
        max_sessions=n_agents + 64,
        hb_stretch_start=max(8, n_agents // 16),
        hb_stretch_max=4.0,
        max_pending_updates=pending_cap,
        max_terminal_tasks=max(1024, n_replicas // 64)))
    d.run(start_worker=False)   # threadless: this harness is the clock
    fan = d.enable_batched_fanout()
    try:
        t_reg0 = time_mod.perf_counter()
        sessions = {}
        for n in nodes:
            sessions[n.id] = d.register(n.id)[0]
        register_s = time_mod.perf_counter() - t_reg0
        stretch = d._stretch_factor()

        t_open0 = time_mod.perf_counter()
        streams = {n.id: fan.open(n.id, sessions[n.id]) for n in nodes}
        open_s = time_mod.perf_counter() - t_open0

        def drain_streams():
            msgs = changes = 0
            for s in streams.values():
                while True:
                    try:
                        m = s.get(timeout=0)
                    except Exception:   # TimeoutError / Closed: drained
                        break
                    msgs += 1
                    changes += len(m.changes)
            return msgs, changes

        # ---- timed scheduling window (compiles gated to zero)
        planner = planner_factory()
        snap = _planner_counter_snapshot()
        _plane("scheduler").roll()    # open the tick occupancy window
        t_create = time_mod.time()
        sched, n_dec, dt = one_tick(store, planner)
        _plane("scheduler").note_busy(dt)
        compiles = _compile_delta(snap)

        # ---- assignment fan-out: one subscription drains into 1k
        # bounded per-node sets; flush sends the incremental batches
        t_fan0 = time_mod.perf_counter()
        fan_msgs = fan_changes = 0
        while True:
            fan.flush()
            m, c = drain_streams()
            fan_msgs += m
            fan_changes += c
            if not m:
                break
        fanout_s = time_mod.perf_counter() - t_fan0

        # ---- status-writeback storm against the bounded admission
        # edge: every shed is counted on both sides and the batch is
        # re-queued for the next round (recovery is total by exit)
        backlog = {}
        for t in store.view(lambda tx: tx.find(_Task)):
            if t.node_id:
                backlog.setdefault(t.node_id, []).append(t.id)
        node_ids = [n.id for n in nodes]
        client = {"shed_batches": 0, "shed_updates": 0, "rounds": 0,
                  "heartbeats": 0}
        sheds0 = d.stats["sheds"]
        _plane("dispatcher").roll()   # open the writeback window
        peak_depth = 0
        t_wb0 = time_mod.perf_counter()
        rr = 0
        while backlog:
            client["rounds"] += 1
            for nid in node_ids:   # keep the 1k-session TTL wheel hot
                d.heartbeat(nid, sessions[nid])
                client["heartbeats"] += 1
            shed_this_round = 0
            for _ in range(len(node_ids)):
                nid = node_ids[rr % len(node_ids)]
                rr += 1
                ids = backlog.get(nid)
                if not ids:
                    continue
                chunk = ids[:report_batch]
                ts = time_mod.time()
                ups = [(tid, TaskStatus(state=TaskState.RUNNING,
                                        message="started",
                                        timestamp=ts))
                       for tid in chunk]
                try:
                    d.update_task_status(nid, sessions[nid], ups)
                except ErrOverloaded:
                    client["shed_batches"] += 1
                    client["shed_updates"] += len(ups)
                    shed_this_round += 1
                    if shed_this_round >= 4:
                        break   # edge saturated: drain before resending
                    continue
                del ids[:len(chunk)]
                if not ids:
                    del backlog[nid]
            peak_depth = max(peak_depth, len(d._task_updates))
            _plane("dispatcher").set_depth(peak_depth)
            with _plane("dispatcher").busy():
                d._flush_updates()      # the worker's process turn
                d.process_deadlines()   # TTL wheel + fan-out flush
            m, c = drain_streams()
            fan_msgs += m
            fan_changes += c
        writeback_s = time_mod.perf_counter() - t_wb0
        shed_count = d.stats["sheds"] - sheds0

        # the shed ledger must reconcile EXACTLY: every shed the
        # dispatcher counted is one a client observed (and re-sent)
        assert shed_count == client["shed_updates"], \
            (shed_count, client)
        assert d.stats["premature_expirations"] == 0, d.stats

        lat = sorted(
            (t.status.applied_at or t.status.timestamp) - t_create
            for t in store.view(lambda tx: tx.find(_Task))
            if t.status.state == TaskState.RUNNING)
        assert len(lat) >= n_replicas, \
            f"cfg13: only {len(lat)}/{n_replicas} RUNNING"

        def pct(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3)
        _plane("dispatcher").roll()
        _plane("scheduler").roll()
        return {
            "agents": n_agents, "replicas": n_replicas,
            "services": n_services, "sessions": len(sessions),
            "decisions": n_dec,
            "decisions_per_sec": round(n_dec / dt, 1),
            "tick_s": round(dt, 3),
            "register_s": round(register_s, 3),
            "stream_open_s": round(open_s, 3),
            "fanout_s": round(fanout_s, 3),
            "fanout_messages": fan_msgs,
            "fanout_changes": fan_changes,
            "fanout_compactions": fan.stats["compactions"],
            "writeback_s": round(writeback_s, 3),
            "writeback_rounds": client["rounds"],
            "peak_update_depth": peak_depth,
            "heartbeats": client["heartbeats"],
            "hb_stretch_factor": round(stretch, 3),
            "hb_stretches": d.stats["hb_stretches"],
            "premature_expirations": d.stats["premature_expirations"],
            "expirations": d.stats["expirations"],
            "sheds": {
                "dispatcher": shed_count,
                "client_observed": client["shed_updates"],
                "shed_batches": client["shed_batches"],
                "uncounted": shed_count - client["shed_updates"],
                "unrecovered": n_replicas - len(lat)},
            "time_to_running": {
                "p50_s": pct(0.50), "p90_s": pct(0.90),
                "p99_s": pct(0.99), "max_s": round(lat[-1], 3),
                "running": len(lat)},
            "planes": {"dispatcher": _plane("dispatcher").report(),
                       "scheduler": _plane("scheduler").report()},
            "path": "dispatcher+fanout+writeback",
            "compiles": compiles,
        }
    finally:
        d.stop()
        _trim_heap()


def main():
    from swarmkit_tpu.models import Platform, PlacementPreference, Resources, SpreadOver
    from swarmkit_tpu.obs import tracer
    from swarmkit_tpu.obs.report import phase_table
    from swarmkit_tpu.ops import TPUPlanner

    tpu = TPUPlanner

    # warm the kernel compile cache for each (node-bucket, spread-level)
    # jit signature used below, outside the timed regions
    rack_pref = [PlacementPreference(
        spread=SpreadOver(spread_descriptor="node.labels.rack"))]
    warm = [(N_NODES, None)]
    if _cfg_enabled(1):
        warm += [(100, None)]
    if _cfg_enabled(3):
        warm += [(5_000, None)]
    if _cfg_enabled(4):
        warm += [(N_NODES, rack_pref)]
    for n_nodes, prefs in warm:
        store, svc, nodes, tasks = build_cluster(
            n_nodes, 64, prefs=prefs)
        warm_planner = TPUPlanner()
        warm_planner.enable_small_group_routing = False  # compile shapes
        one_tick(store, warm_planner)
    # the adaptive router's launch-overhead probe compiles its own tiny
    # shape on first use — warm it here or the FIRST headline trial pays
    # a ~1s jit compile and p99 reports compile time, not scheduling
    TPUPlanner()._measure_launch_overhead()
    if _cfg_enabled(4):
        # warm the preassigned-validation kernel (global-service share of
        # config 4) at its node-bucket shape
        store, svc, nodes, tasks = build_cluster(
            N_NODES, 64, prefs=rack_pref, global_share=1.0)
        warm_planner = TPUPlanner()
        warm_planner.enable_small_group_routing = False
        one_tick(store, warm_planner, preassigned=True)

    # spans recorded from here on; the warm-up compiles above stay out
    tracer.reset()
    tracer.enable()
    # black box on: recent spans + registry samples stay dumpable when
    # a variance guard trips (run_with_variance_guard)
    from swarmkit_tpu.obs import flightrec
    flightrec.reset()
    flightrec.enabled = True
    # journeys + plane windows on from here (the shipped posture): the
    # ledger rides the recorder's store taps; plane occupancy windows
    # roll at artifact-assembly time below
    from swarmkit_tpu.obs import planes as planes_mod
    from swarmkit_tpu.obs.journey import journeys
    planes_mod.reset()
    # pre-create the taxonomy and open every occupancy window at the
    # bench epoch (windows open lazily at first roll; without this the
    # single artifact-assembly roll below would read a zero-width
    # window and report occupancy 0 for every plane)
    for _pl in planes_mod.ALL_PLANES:
        planes_mod.plane(_pl)
    planes_mod.roll_all()
    journeys.reset(sample_rate=1.0)
    journeys.enabled = True
    flightrec.journey_sink = journeys.handle_event
    # device-plane ledger on from here (the shipped posture): kernel
    # rows, per-reason transfer bytes, the compile-cache ledger the
    # window sentinel below audits
    from swarmkit_tpu.obs import devicetelemetry
    devicetelemetry.reset()
    devicetelemetry.set_enabled(True)

    # ---- headline: config 4 scale, median of TRIALS (variance-guarded)
    def headline_trial(obs_tap=False):
        store, svc, nodes, tasks = build_cluster(N_NODES, N_TASKS)
        planner = TPUPlanner()
        # obs_tap = the journeys-enabled posture: the store is tapped
        # like a live manager's, so commits pay the real subscription
        # fan-out; the fold itself (poll_store) runs off the timed
        # window, where the production sampler thread runs it
        if obs_tap:
            flightrec.watch_store(store)
        sched, n_dec, dt = one_tick(store, planner)
        if obs_tap:
            flightrec.poll_store()
            flightrec.unwatch_store(store)
        assert n_dec == N_TASKS
        assert planner.stats["tasks_planned"] == N_TASKS, planner.stats
        out = (dt, planner.stats["plan_seconds"],
               sched.stats["commit_seconds"])
        del store, svc, nodes, tasks, planner, sched
        gc.collect()
        return out

    headline_compile_snap = _planner_counter_snapshot()
    with tracer.span("bench.config", "bench", cfg="headline"):
        trials, headline_reruns = run_with_variance_guard(
            headline_trial, n_trials=TRIALS, name="headline")
    # per-bucket compile counts inside the timed headline region — the
    # warm-up above compiled every signature, so nonzero means a compile
    # landed in a timed trial and the numbers carry its cost
    headline_compiles = _compile_delta(headline_compile_snap)
    ticks = sorted(t[0] for t in trials)
    med = statistics.median(ticks)
    rep = min(trials, key=lambda t: abs(t[0] - med))
    tpu_dps = N_TASKS / med

    # ---- tracing overhead: ALTERNATING tracer-off / tracer-on trials
    # of the same headline workload, so machine-state drift (allocator
    # caches, GC) lands evenly in both halves instead of biasing
    # whichever ran later; medians of each half are the pair the ≤3%
    # acceptance bound is judged on.  Registry counters/timers stay on
    # in BOTH halves by design, like the reference's go-metrics — this
    # measures the optional span layer.  The headline number above is
    # the obs-enabled (shipped) posture.
    if SKIP_OBS:
        obs_stats = None
    else:
        # the "on" half is the full shipped posture: spans AND the
        # journey ledger riding a live store tap; "off" is both dark.
        # The ≤3% acceptance bound (bench_compare obs-overhead gate) is
        # judged on these medians, and the window must be compile-free
        # or the number carries XLA cost instead of obs cost.
        obs_compile_snap = _planner_counter_snapshot()
        # compile-cache window sentinel: signatures already compiled
        # before the timed window — a later miss on any of these is a
        # cache-ledger regression (bench_compare compile-cache-hit gate)
        devtel_seen = {
            b: r["compiles"] for b, r
            in devicetelemetry.compile_cache_snapshot().items()
            if r["compiles"] > 0}
        on_ts, off_ts = [], []
        for _ in range(max(1, TRIALS)):
            tracer.disable()
            journeys.enabled = False
            devicetelemetry.set_enabled(False)
            off_ts.append(headline_trial()[0])
            tracer.enable()
            journeys.enabled = True
            devicetelemetry.set_enabled(True)
            on_ts.append(headline_trial(obs_tap=True)[0])
        med_on = statistics.median(on_ts)
        med_off = statistics.median(off_ts)
        devtel_after = devicetelemetry.compile_cache_snapshot()
        window_repeat_misses = sorted(
            b for b, n in devtel_seen.items()
            if devtel_after.get(b, {}).get("compiles", 0) > n)
        obs_stats = {
            "enabled_decisions_per_sec": round(N_TASKS / med_on, 1),
            "disabled_decisions_per_sec": round(N_TASKS / med_off, 1),
            "overhead_pct": round((med_on - med_off) / med_off * 100.0,
                                  2),
            "window_compiles": sum(
                _compile_delta(obs_compile_snap).values()),
            "window_repeat_misses": window_repeat_misses,
            "journey_sampled_tasks": journeys.summary()["sampled_tasks"],
        }

    if SKIP_HOST:
        host_dps, vs = None, 0.0
    else:
        host_ticks = []
        for _ in range(TRIALS):
            store, svc, nodes, tasks = build_cluster(N_NODES, BASELINE_TASKS)
            _, n_dec, dt = one_tick(store, None)
            host_ticks.append(dt)
        host_dps = BASELINE_TASKS / statistics.median(host_ticks)
        vs = tpu_dps / host_dps

    configs = {}
    if _cfg_enabled(1):
        with tracer.span("bench.config", "bench", cfg="cfg1"):
            configs["1_spread_1k_x_100"] = run_config(
                "cfg1", 100, 1_000, tpu,
                reservations=Resources())
    if _cfg_enabled(2):
        with tracer.span("bench.config", "bench", cfg="cfg2"):
            configs["2_binpack_10k_x_1k"] = run_config(
                "cfg2", 1_000, 10_000, tpu,
                reservations=Resources(nano_cpus=2 * 10**9,
                                       memory_bytes=2 << 30))
    if _cfg_enabled(3):
        with tracer.span("bench.config", "bench", cfg="cfg3"):
            configs["3_constraints_50k_x_5k"] = run_config(
                "cfg3", 5_000, 50_000, tpu,
                node_labels=lambda i: {"tier": "web" if i % 2 else "db",
                                       "rack": f"r{i % 40}"},
                node_platform=lambda i: {"os": "linux" if i % 10
                                         else "windows",
                                         "architecture": "amd64"},
                constraints=["node.labels.tier==web"],
                platforms=[Platform(os="linux", architecture="amd64")],
                expect=50_000)
    if _cfg_enabled(4):
        with tracer.span("bench.config", "bench", cfg="cfg4"):
            configs["4_mixed_100k_x_10k"] = run_config(
                "cfg4", N_NODES, N_TASKS, tpu,
                prefs=[PlacementPreference(
                    spread=SpreadOver(
                        spread_descriptor="node.labels.rack"))],
                global_share=0.2)
    if _cfg_enabled(5):
        with tracer.span("bench.config", "bench", cfg="cfg5"):
            configs["5_reschedule_storm"] = run_storm(tpu)
    # shape_cost_x = per-decision cost of a config relative to the
    # lab-shape headline (tpu_dps).  Configs 1-5 run the very harness
    # the headline runs (no proposer, no watchers) — they ARE the lab
    # shape, so their production-shape cost factor is 1.0 by
    # construction; recording it (instead of the old None) keeps the
    # history ledger's per-config shape_cost_x column well-defined.
    for cfg in configs.values():
        cfg.setdefault("shape_cost_x", 1.0)
    if _cfg_enabled(6):
        with tracer.span("bench.config", "bench", cfg="cfg6"):
            configs["6_live_manager_2x100k_x_10k"] = run_live_manager(tpu)
        live = configs["6_live_manager_2x100k_x_10k"]["decisions_per_sec"]
        # production-shape cost factor: per-decision rate of the live
        # multi-service tick vs the lab-shape headline (no
        # proposer/watchers); target <1.5x
        configs["6_live_manager_2x100k_x_10k"]["shape_cost_x"] = round(
            tpu_dps / live, 2) if live else None
    if _cfg_enabled(7):
        # many-service scale-out: 10 services fused into one program
        # ladder per tick.  Defaults fit the dev container; the env
        # knobs scale toward the 1M-task x 50k-node target shape on
        # hosts that hold it (BENCH_CFG7_NODES=50000
        # BENCH_CFG7_TASKS=1000000).
        cfg7_services = int(os.environ.get("BENCH_CFG7_SERVICES", 10))
        cfg7_nodes = int(os.environ.get("BENCH_CFG7_NODES", N_NODES))
        cfg7_tasks = int(os.environ.get("BENCH_CFG7_TASKS", 500_000))
        with tracer.span("bench.config", "bench", cfg="cfg7"):
            configs["7_many_service_10x"] = run_live_manager(
                tpu, n_services=cfg7_services, n_nodes=cfg7_nodes,
                total_tasks=cfg7_tasks)
        live7 = configs["7_many_service_10x"]["decisions_per_sec"]
        configs["7_many_service_10x"]["shape_cost_x"] = round(
            tpu_dps / live7, 2) if live7 else None
    if _cfg_enabled(8):
        # services + jobs + 3 priority bands: the preemption subsystem
        # under load (victim kernel signatures warmed inside the config)
        with tracer.span("bench.config", "bench", cfg="cfg8"):
            configs["8_mixed_priority_jobs"] = run_priority_jobs(tpu)
    if _cfg_enabled(9):
        # autoscaler decision + quota-clamped tenant burst through the
        # device quota-mask column (bench_compare gates clamps > 0 with
        # compile-flat timed windows)
        with tracer.span("bench.config", "bench", cfg="cfg9"):
            configs["9_autoscale_tenant_storm"] = \
                run_autoscale_tenant_storm(tpu)
    if _cfg_enabled(10):
        # sustained decisions/sec under Poisson churn: the streaming
        # scheduler's incremental ticks vs forced full replans, same
        # seeded workload, placements byte-identical (bench_compare
        # gates the plane being active + compile-flat windows + the
        # pending->assigned p99 regression bound)
        with tracer.span("bench.config", "bench", cfg="cfg10"):
            configs["10_steady_state_churn"] = \
                run_steady_state_churn(tpu)
    if _cfg_enabled(11):
        # mixed-size replicas under spread vs binpack through the
        # strategy seam: stranded-capacity fraction + the node.ip-CIDR
        # device column (bench_compare gates binpack < spread, zero
        # strategy fallbacks, fallback_groups 0, compile-flat windows,
        # and spread dec/s regression <= 10%)
        with tracer.span("bench.config", "bench", cfg="cfg11"):
            configs["11_fragmentation_strategies"] = \
                run_fragmentation(tpu)
    if _cfg_enabled(12):
        # mixed gang fleet + 3-stage DAG-gated pipeline through the
        # atomic-admission path (bench_compare gates zero partial
        # gangs, zero gang deferrals, the gate holding then draining,
        # device gang route, compile-flat windows, and the gang
        # tick's dec/s within 4x of the plain tick)
        with tracer.span("bench.config", "bench", cfg="cfg12"):
            configs["12_gang_pipeline"] = run_gang_pipeline(tpu)
    if _cfg_enabled(13):
        # overload-safe serving at fleet scale: >=1k real dispatcher
        # sessions + ~1M-replica fan-out through the batched assignment
        # plane with the admission bounds LIVE (bench_compare gates the
        # time-to-running p99 regression, ledger-exact shed counting
        # with zero unrecovered, and zero timed-window compiles)
        with tracer.span("bench.config", "bench", cfg="cfg13"):
            configs["13_million_swarm"] = run_million_swarm(tpu)
    if SKIP_E2E:
        e2e = None
    else:
        with tracer.span("bench.config", "bench", cfg="e2e"):
            e2e = run_e2e()

    # ---- trace export + phase tables (from the SAME document, so the
    # artifact's table and the loadable trace can never diverge)
    tracer.disable()
    doc = tracer.to_chrome()
    trace_file = None
    try:
        with open(TRACE_OUT, "w") as f:
            json.dump(doc, f)
        trace_file = TRACE_OUT
    except OSError:
        pass
    from swarmkit_tpu.obs.report import config_windows
    tables = {cfg: phase_table(doc, window=w)
              for cfg, w in config_windows(doc)}

    # headline overlap evidence (ROADMAP item 1), promoted from the
    # per-config phase_table: cfg6 — the production-shape pipelined
    # tick — when it ran, else the headline window.  bench_compare
    # fails a run whose overlap regressed to 0 with the pipeline on.
    from swarmkit_tpu.utils.pipeline import default_pipeline_depth
    overlap_src = next((c for c in ("cfg6", "cfg7") if c in tables),
                       "headline")
    overlap_tbl = tables.get(overlap_src, {})

    # close the plane occupancy windows so the saturation gauges (and
    # the health checks reading them) reflect the finished run
    planes_mod.roll_all()
    planes_report = planes_mod.report_all()

    # health plane verdict over the finished run's registry: all-pass is
    # the clean-run baseline the acceptance criteria pin
    from swarmkit_tpu.obs.health import HealthEvaluator
    health_eval = HealthEvaluator()
    health_checks = health_eval.evaluate()
    health = {"status": health_eval.status(), "checks": health_checks}

    artifact = {
        "metric": f"scheduling decisions/sec, {N_TASKS // 1000}k tasks x "
                  f"{N_NODES // 1000}k nodes (single tick, store-committed)",
        "value": round(tpu_dps, 1),
        "unit": "decisions/sec",
        "vs_baseline": round(vs, 2),
        "tick_p50_s": round(med, 3),
        "tick_p99_s": round(ticks[-1], 3),
        "tick_min_s": round(ticks[0], 3),
        "tick_stdev_s": round(statistics.stdev(ticks), 4)
        if len(ticks) > 1 else 0.0,
        "headline_variance_x": round(ticks[-1] / ticks[0], 2),
        "headline_variance_reruns": headline_reruns,
        "plan_phase_s": round(rep[1], 3),
        "commit_phase_s": round(rep[2], 3),
        "plan_phase_decisions_per_sec": round(N_TASKS / rep[1], 1)
        if rep[1] else None,
        "trials": len(trials),
        "baseline": "host-oracle path, same store+commit framework "
                    "(Go toolchain unavailable; see BASELINE.md)",
        "baseline_decisions_per_sec": round(host_dps, 1) if host_dps
        else None,
        "obs": obs_stats,
        "trace_file": trace_file,
        # per-bucket XLA compiles inside the timed headline region
        "planner_compiles": headline_compiles,
        # plan/commit software pipeline: configured depth + the overlap
        # the trace actually measured (see overlap_src above)
        "pipeline_depth": default_pipeline_depth(),
        # planner mesh size (SWARM_PLANNER_MESH; 1 = single device)
        "planner_mesh_devices": _mesh_devices(),
        # N∈{1,2,4,8} fused-chunk crossover curve, when measured
        # (scripts/mesh_crossover.py writes the artifact it embeds)
        "mesh_crossover": _mesh_crossover(),
        "plan_commit_overlap_s": overlap_tbl.get(
            "plan_commit_overlap_s", 0.0),
        "plan_hidden_frac": overlap_tbl.get("plan_hidden_frac", 0.0),
        "plan_overlap_source": overlap_src,
        # commit-plane headline (ISSUE 13): fraction of the commit wall
        # hidden behind the plan, fan-out synthesis cost, and whether
        # the native commit plane held in the live-manager window
        "commit_hidden_frac": overlap_tbl.get("commit_hidden_frac", 0.0),
        "fanout_s": next(
            (configs[c]["fanout_s"] for c in
             ("6_live_manager_2x100k_x_10k", "7_many_service_10x")
             if c in configs and "fanout_s" in configs[c]), None),
        "native_commit": next(
            (configs[c]["native_commit"] for c in
             ("6_live_manager_2x100k_x_10k", "7_many_service_10x")
             if c in configs and "native_commit" in configs[c]), None),
        # streaming scheduler (ISSUE 14): resident-state evidence from
        # the sustained-churn config's streaming pass
        "streaming": (configs.get("10_steady_state_churn") or {}
                      ).get("streaming"),
        "health": health,
        # device-plane ledger for the whole run: kernel rows keyed by
        # compile bucket, per-reason transfer bytes, the per-signature
        # compile-cache ledger, memory watermarks, donation balance
        "device_telemetry": devicetelemetry.snapshot(),
        # per-plane saturation report (occupancy/depth/age/drops) and
        # the journey-join attribution of e2e time-to-running p99 —
        # trace_report --critical-path prints both from this artifact
        "planes": planes_report,
        "journey_attribution": (e2e or {}).get("journey_attribution"),
        "phase_table": tables,
        "configs": configs,
        "e2e_time_to_running": e2e,
    }
    if "headline" in _flightrec_dumps:
        artifact["flightrec_dump"] = _flightrec_dumps["headline"]
    print(json.dumps(artifact))
    _append_history(artifact)


def _append_history(artifact):
    """One compact JSONL record per run — the regression ledger
    ``scripts/bench_compare.py`` diffs.  Best-effort: an unwritable
    history file must not fail the bench."""
    if not HISTORY_OUT:
        return
    record = {
        "t": round(time.time(), 3),
        "metric": artifact["metric"],
        "value": artifact["value"],
        "unit": artifact["unit"],
        "tick_p50_s": artifact["tick_p50_s"],
        "headline_variance_x": artifact["headline_variance_x"],
        "obs_overhead_pct": (artifact["obs"] or {}).get("overhead_pct"),
        "obs_window_compiles": (artifact["obs"] or {}).get(
            "window_compiles"),
        "obs_window_repeat_misses": (artifact["obs"] or {}).get(
            "window_repeat_misses"),
        "device_transfer_bytes": {
            d: sum(r["bytes"] for r in tbl.values())
            for d, tbl in (artifact.get("device_telemetry") or {})
            .get("transfers", {}).items()},
        "device_bytes_avoided": (artifact.get("device_telemetry")
                                 or {}).get("bytes_avoided"),
        "health": artifact["health"]["status"],
        "health_checks": artifact["health"].get("checks"),
        "planner_compiles": sum(artifact["planner_compiles"].values()),
        "pipeline_depth": artifact["pipeline_depth"],
        "planner_mesh_devices": artifact["planner_mesh_devices"],
        "plan_commit_overlap_s": artifact["plan_commit_overlap_s"],
        "plan_hidden_frac": artifact["plan_hidden_frac"],
        "plan_overlap_source": artifact["plan_overlap_source"],
        "commit_phase_s": artifact["commit_phase_s"],
        "commit_hidden_frac": artifact.get("commit_hidden_frac"),
        "fanout_s": artifact.get("fanout_s"),
        "native_commit": artifact.get("native_commit"),
        "streaming": artifact.get("streaming"),
        "configs": {
            name: {
                "decisions_per_sec": cfg.get("decisions_per_sec"),
                "variance_x": cfg.get("variance_x"),
                "fallback_groups": cfg.get("fallback_groups"),
                "compiles": sum(cfg.get("compiles", {}).values()),
                "shape_cost_x": cfg.get("shape_cost_x"),
                "preemptions": cfg.get("preemptions"),
                "quota_clamps": cfg.get("quota_clamps"),
                "commit_phase_s": cfg.get("commit_phase_s"),
                "fanout_s": cfg.get("fanout_s"),
                "native_commit": cfg.get("native_commit"),
                "streaming": cfg.get("streaming"),
                "streaming_speedup": cfg.get("streaming_speedup"),
                "h2d_bytes_per_tick": cfg.get("h2d_bytes_per_tick"),
                "pending_assigned_p99_s": cfg.get(
                    "pending_assigned_p99_s"),
                "spread_decisions_per_sec": cfg.get(
                    "spread_decisions_per_sec"),
                "binpack_decisions_per_sec": cfg.get(
                    "binpack_decisions_per_sec"),
                "stranded_frac_spread": cfg.get("stranded_frac_spread"),
                "stranded_frac_binpack": cfg.get(
                    "stranded_frac_binpack"),
                "strategy_fallbacks": cfg.get("strategy_fallbacks"),
                "gangs_admitted": cfg.get("gangs_admitted"),
                "gang_deferred": cfg.get("gang_deferred"),
                "gang_atomicity_violations": cfg.get(
                    "gang_atomicity_violations"),
                "gang_fit_host_verdicts": cfg.get(
                    "gang_fit_host_verdicts"),
                "pipeline_gated_deferrals": cfg.get(
                    "pipeline_gated_deferrals"),
                "gang_vs_plain_x": cfg.get("gang_vs_plain_x"),
            }
            for name, cfg in artifact["configs"].items()},
    }
    try:
        with open(HISTORY_OUT, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
    except OSError:
        pass


if __name__ == "__main__":
    main()
