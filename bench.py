"""Headline benchmark: scheduling decisions/sec at 100k tasks × 10k nodes.

Matches BASELINE.json config 4/5 scale (the reference's
BenchmarkScheduler100kNodes*/1kNodes* family,
manager/scheduler/scheduler_test.go:3338-3376): one big task group scheduled
onto a 10k-node cluster through the full path — store → scheduler tick →
(TPU plan | host oracle) → batched store commit — measured from tick start
to all ASSIGNED rows committed.

Baseline: the Go toolchain is not present in this image, so the reference's
own benches cannot run here.  ``vs_baseline`` therefore compares against the
**host oracle path** (the faithful reimplementation of the reference
algorithm) measured in this same process on a proportionally scaled workload
(same 10k nodes, BASELINE_TASKS tasks), normalized per decision.  See
BASELINE.md for the methodology note.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "decisions/sec", "vs_baseline": N, ...}

Env overrides: BENCH_NODES, BENCH_TASKS, BENCH_BASELINE_TASKS, BENCH_SKIP_HOST.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_TASKS = int(os.environ.get("BENCH_TASKS", 100_000))
BASELINE_TASKS = int(os.environ.get("BENCH_BASELINE_TASKS", 5_000))
SKIP_HOST = os.environ.get("BENCH_SKIP_HOST", "") == "1"


def build_cluster(n_nodes, n_tasks):
    from swarmkit_tpu.models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
        Placement, ReplicatedService, Resources, ResourceRequirements,
        Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id

    store = MemoryStore()
    nodes = [
        Node(id=new_id(),
             spec=NodeSpec(annotations=Annotations(
                 name=f"node-{i:05d}", labels={"rack": f"r{i % 20}"})),
             status=NodeStatus(state=NodeState.READY),
             description=NodeDescription(
                 hostname=f"node-{i:05d}",
                 resources=Resources(nano_cpus=32 * 10**9,
                                     memory_bytes=128 << 30)))
        for i in range(n_nodes)
    ]
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(annotations=Annotations(name="bench"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks)),
        spec_version=Version(index=1))
    shared_spec = TaskSpec(
        resources=ResourceRequirements(
            reservations=Resources(nano_cpus=10**9,
                                   memory_bytes=1 << 30)))
    tasks = [
        Task(id=new_id(), service_id=svc.id, slot=s,
             desired_state=TaskState.RUNNING, spec=shared_spec,
             spec_version=Version(index=1),
             status=TaskStatus(state=TaskState.PENDING))
        for s in range(1, n_tasks + 1)
    ]

    def setup(tx):
        for n in nodes:
            tx.create(n)
        tx.create(svc)

    store.update(setup)

    def add_tasks(tx):
        for t in tasks:
            tx.create(t)

    store.update(add_tasks)
    return store, svc


def run_path(n_nodes, n_tasks, planner):
    from swarmkit_tpu.scheduler import Scheduler

    store, svc = build_cluster(n_nodes, n_tasks)
    sched = Scheduler(store, batch_planner=planner)
    store.view(sched._setup_tasks_list)
    t0 = time.perf_counter()
    n_dec = sched.tick()
    dt = time.perf_counter() - t0
    assert n_dec == n_tasks, f"scheduled {n_dec}/{n_tasks}"
    return n_dec / dt, dt


def main():
    from swarmkit_tpu.ops import TPUPlanner

    # warm the kernel compile cache out of the timed region — must use the
    # same node count so the padded N bucket (and thus the jit cache key)
    # matches the measured run
    run_path(N_NODES, 64, TPUPlanner())

    planner = TPUPlanner()
    tpu_dps, tpu_dt = run_path(N_NODES, N_TASKS, planner)
    assert planner.stats["groups_planned"] >= 1, "TPU path did not engage"

    assert planner.stats["tasks_planned"] == N_TASKS, planner.stats
    plan_dps = (planner.stats["tasks_planned"]
                / max(planner.stats["plan_seconds"], 1e-9))

    if SKIP_HOST:
        host_dps = None
        vs = 0.0
    else:
        host_dps, _ = run_path(N_NODES, BASELINE_TASKS, None)
        vs = tpu_dps / host_dps

    print(json.dumps({
        "metric": f"scheduling decisions/sec, {N_TASKS // 1000}k tasks x "
                  f"{N_NODES // 1000}k nodes (single tick, store-committed)",
        "value": round(tpu_dps, 1),
        "unit": "decisions/sec",
        "vs_baseline": round(vs, 2),
        "tick_seconds": round(tpu_dt, 3),
        "plan_phase_decisions_per_sec": round(plan_dps, 1),
        "baseline": "host-oracle path (Go toolchain unavailable; see BASELINE.md)",
        "baseline_decisions_per_sec": round(host_dps, 1) if host_dps else None,
    }))


if __name__ == "__main__":
    main()
