"""Diff two bench runs and fail on per-config regressions.

Usage:
    python scripts/bench_compare.py OLD.json NEW.json
    python scripts/bench_compare.py --history BENCH_HISTORY.jsonl
    python scripts/bench_compare.py --history BENCH_HISTORY.jsonl \
        -a -3 -b -1
    python scripts/bench_compare.py OLD.json NEW.json --threshold 0.1

Inputs are either full bench artifacts (the JSON line ``bench.py``
prints, saved as ``BENCH_*.json``) or entries of the append-only
``BENCH_HISTORY.jsonl`` ledger every run writes — both carry the same
per-config ``decisions_per_sec`` numbers.  ``--history`` compares two
entries of the ledger (defaults: previous vs last).

Exit status: 1 when any config (or the headline) regressed by more than
``--threshold`` (default 0.20 = the round-5 "regression-proof bench"
bar), else 0.  Improvements and new/removed configs never fail the run.
"""

import argparse
import json
import os
import sys


def _norm(doc):
    """Normalize an artifact or history record to
    {"headline": dps, "configs": {name: dps}} plus context fields."""
    configs = {}
    for name, cfg in (doc.get("configs") or {}).items():
        dps = cfg.get("decisions_per_sec")
        if dps:
            configs[name] = float(dps)
    return {
        "headline": float(doc.get("value") or 0.0),
        "configs": configs,
        "t": doc.get("t"),
        "health": (doc.get("health") or {}).get("status")
        if isinstance(doc.get("health"), dict) else doc.get("health"),
        # plan/commit overlap evidence (artifacts and history records
        # both carry these since the pipelined-scheduler PR; older runs
        # report None and are exempt from the overlap gate)
        "pipeline_depth": doc.get("pipeline_depth"),
        "plan_hidden_frac": doc.get("plan_hidden_frac"),
        "plan_commit_overlap_s": doc.get("plan_commit_overlap_s"),
        "plan_overlap_source": doc.get("plan_overlap_source"),
    }


def _load_file(path):
    with open(path) as f:
        text = f.read().strip()
    # artifacts may carry log noise before the JSON line; take the last
    # line that parses
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return _norm(json.loads(line))
        except ValueError:
            continue
    raise SystemExit(f"{path}: no JSON document found")


def _load_history(path, index):
    with open(path) as f:
        entries = [json.loads(line) for line in f if line.strip()]
    if not entries:
        raise SystemExit(f"{path}: empty history")
    try:
        return _norm(entries[index])
    except IndexError:
        raise SystemExit(
            f"{path}: index {index} out of range ({len(entries)} entries)")


def compare(old, new, threshold):
    """Returns (rows, regressions).  A row covers the headline and every
    config present in either run."""
    names = ["headline"] + sorted(set(old["configs"]) | set(new["configs"]))
    rows, regressions = [], []
    for name in names:
        if name == "headline":
            a, b = old["headline"], new["headline"]
        else:
            a = old["configs"].get(name)
            b = new["configs"].get(name)
        if not a or not b:
            rows.append((name, a, b, None, "new" if not a else "gone"))
            continue
        delta = (b - a) / a
        mark = ""
        if delta < -threshold:
            mark = "REGRESSION"
            regressions.append(name)
        rows.append((name, a, b, delta, mark))
    return rows, regressions


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/bench_compare.py")
    p.add_argument("runs", nargs="*",
                   help="two artifact/history-entry JSON files (OLD NEW)")
    p.add_argument("--history", metavar="JSONL",
                   help="compare two entries of a BENCH_HISTORY.jsonl")
    p.add_argument("-a", type=int, default=-2,
                   help="history index of the baseline entry (default -2)")
    p.add_argument("-b", type=int, default=-1,
                   help="history index of the candidate entry (default -1)")
    p.add_argument("--threshold", type=float, default=0.20,
                   help="max tolerated per-config decisions/s regression "
                        "(fraction, default 0.20)")
    args = p.parse_args(argv)

    if args.history:
        old = _load_history(args.history, args.a)
        new = _load_history(args.history, args.b)
        labels = (f"{os.path.basename(args.history)}[{args.a}]",
                  f"{os.path.basename(args.history)}[{args.b}]")
    elif len(args.runs) == 2:
        old = _load_file(args.runs[0])
        new = _load_file(args.runs[1])
        labels = tuple(os.path.basename(r) for r in args.runs)
    else:
        p.error("pass two run files, or --history JSONL")
        return 2

    rows, regressions = compare(old, new, args.threshold)
    print(f"{'config':<28} {labels[0]:>16} {labels[1]:>16} {'delta':>9}")
    for name, a, b, delta, mark in rows:
        sa = f"{a:,.1f}" if a else "-"
        sb = f"{b:,.1f}" if b else "-"
        sd = f"{delta * 100:+.1f}%" if delta is not None else mark
        line = f"{name:<28} {sa:>16} {sb:>16} {sd:>9}"
        if mark == "REGRESSION":
            line += "  <-- REGRESSION"
        print(line)
    if old.get("health") or new.get("health"):
        print(f"\nhealth: {old.get('health')} -> {new.get('health')}")
    # overlap gate: a run with the pipeline ON (depth > 1) whose
    # plan/commit overlap collapsed to 0 lost the pipelining win even if
    # raw throughput hasn't (yet) regressed past the threshold — fail it
    # like any other regression.  The gate keys on the NEW run alone (a
    # zero-overlap baseline must not disarm it), when the overlap was
    # measured in a window where it is meaningful: the cfg6 multi-group
    # tick always is; for source-less records (transitional) fall back
    # to requiring the baseline to have shown overlap.  Runs predating
    # the overlap fields or with the serial escape hatch are exempt —
    # as are headline-window measurements (a single-group tick has no
    # group to overlap with).
    old_h, new_h = old.get("plan_hidden_frac"), new.get("plan_hidden_frac")
    if old_h is not None or new_h is not None:
        print(f"plan_hidden_frac: {old_h} -> {new_h} "
              f"(pipeline depth {old.get('pipeline_depth')} -> "
              f"{new.get('pipeline_depth')})")
    src = new.get("plan_overlap_source")
    meaningful = src == "cfg6" or (src is None and (old_h or 0.0) > 0.0)
    if ((new.get("pipeline_depth") or 1) > 1 and new_h is not None
            and not new_h and meaningful):
        print("\nplan/commit overlap regressed to 0 with the pipeline "
              "on", file=sys.stderr)
        regressions.append("plan_hidden_frac")
    if regressions:
        print(f"\n{len(regressions)} config(s) regressed more than "
              f"{args.threshold * 100:.0f}%: {', '.join(regressions)}",
              file=sys.stderr)
        return 1
    print(f"\nok: no config regressed more than "
          f"{args.threshold * 100:.0f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
