"""Diff two bench runs and fail on per-config regressions.

Usage:
    python scripts/bench_compare.py OLD.json NEW.json
    python scripts/bench_compare.py --history BENCH_HISTORY.jsonl
    python scripts/bench_compare.py --history BENCH_HISTORY.jsonl \
        -a -3 -b -1
    python scripts/bench_compare.py OLD.json NEW.json --threshold 0.1

Inputs are either full bench artifacts (the JSON line ``bench.py``
prints, saved as ``BENCH_*.json``) or entries of the append-only
``BENCH_HISTORY.jsonl`` ledger every run writes — both carry the same
per-config ``decisions_per_sec`` numbers.  ``--history`` compares two
entries of the ledger (defaults: previous vs last).

Exit status: 1 when any config (or the headline) regressed by more than
``--threshold`` (default 0.20 = the round-5 "regression-proof bench"
bar), when the NEW run's cfg6/cfg7 ``shape_cost_x`` exceeds
``--max-shape-cost`` (default 1.5), when plan/commit overlap collapsed
to 0 with the pipeline on, or when timed-region XLA compile counts grew
(compile flatness after warm-up), else 0.  Improvements and new/removed
configs never fail the run.
"""

import argparse
import json
import os
import sys


def _compiles(val):
    """Compile counts appear as a per-bucket dict in artifacts and as a
    pre-summed int in history records."""
    if isinstance(val, dict):
        return sum(val.values())
    return int(val) if val else 0


def _norm(doc):
    """Normalize an artifact or history record to
    {"headline": dps, "configs": {name: dps}} plus context fields."""
    configs, shape_cost, compiles, preempts = {}, {}, {}, {}
    quota_clamps = {}
    commit_phase, native_commit = {}, {}
    streaming, p99 = {}, {}
    strategy = {}
    gangs = {}
    h2d_per_tick = {}
    mesh_resident = {}
    overload = {}
    for name, cfg in (doc.get("configs") or {}).items():
        dps = cfg.get("decisions_per_sec")
        if dps:
            configs[name] = float(dps)
        if cfg.get("shape_cost_x") is not None:
            shape_cost[name] = float(cfg["shape_cost_x"])
        if cfg.get("preemptions") is not None:
            preempts[name] = int(cfg["preemptions"])
        if cfg.get("quota_clamps") is not None:
            quota_clamps[name] = int(cfg["quota_clamps"])
        if cfg.get("commit_phase_s") is not None:
            commit_phase[name] = float(cfg["commit_phase_s"])
        if cfg.get("native_commit") is not None:
            native_commit[name] = cfg["native_commit"]
        if cfg.get("streaming") is not None:
            streaming[name] = cfg["streaming"]
        if cfg.get("pending_assigned_p99_s") is not None:
            p99[name] = float(cfg["pending_assigned_p99_s"])
        if cfg.get("h2d_bytes_per_tick") is not None:
            h2d_per_tick[name] = float(cfg["h2d_bytes_per_tick"])
        if cfg.get("planner_mesh") is not None:
            mesh_resident[name] = {
                "planner_mesh": cfg.get("planner_mesh"),
                "resident_h2d_bytes_per_tick": cfg.get(
                    "resident_h2d_bytes_per_tick"),
                "strategy_host_groups": cfg.get("strategy_host_groups"),
            }
        if cfg.get("stranded_frac_spread") is not None:
            strategy[name] = {
                "stranded_frac_spread": cfg.get("stranded_frac_spread"),
                "stranded_frac_binpack": cfg.get(
                    "stranded_frac_binpack"),
                "spread_decisions_per_sec": cfg.get(
                    "spread_decisions_per_sec"),
                "strategy_fallbacks": cfg.get("strategy_fallbacks"),
                "fallback_groups": cfg.get("fallback_groups"),
            }
        if isinstance(cfg.get("sheds"), dict):
            overload[name] = {
                "sheds": cfg.get("sheds"),
                "sessions": cfg.get("sessions"),
                "hb_stretches": cfg.get("hb_stretches"),
                "hb_stretch_factor": cfg.get("hb_stretch_factor"),
                "premature_expirations": cfg.get(
                    "premature_expirations"),
                "time_to_running_p99_s": (cfg.get("time_to_running")
                                          or {}).get("p99_s"),
            }
        if cfg.get("gangs_admitted") is not None:
            gangs[name] = {
                "gangs_admitted": cfg.get("gangs_admitted"),
                "gang_deferred": cfg.get("gang_deferred"),
                "gang_atomicity_violations": cfg.get(
                    "gang_atomicity_violations"),
                "gang_fit_host_verdicts": cfg.get(
                    "gang_fit_host_verdicts"),
                "pipeline_gated_deferrals": cfg.get(
                    "pipeline_gated_deferrals"),
                "gang_vs_plain_x": cfg.get("gang_vs_plain_x"),
            }
        compiles[name] = _compiles(cfg.get("compiles"))
    return {
        # commit-plane fields (ISSUE 13): per-config commit wall and the
        # native-plane evidence dict ({enabled, active, fallbacks})
        "commit_phase_s": commit_phase,
        "native_commit": native_commit,
        "headline": float(doc.get("value") or 0.0),
        "configs": configs,
        "shape_cost_x": shape_cost,
        # XLA compiles that landed inside timed regions (headline +
        # per config) — must stay flat after warm-up
        "compiles": compiles,
        # preemption counters per config (cfg8 must show them)
        "preemptions": preempts,
        # tenant-quota clamps per config (cfg9 must show them)
        "quota_clamps": quota_clamps,
        # streaming-scheduler evidence per config (cfg10): the
        # {enabled, incremental_ticks, dirty_frac, resyncs, fallbacks}
        # dict and the pending->assigned p99 the regression bound judges
        "streaming": streaming,
        "pending_assigned_p99_s": p99,
        # device-telemetry evidence (this PR): cfg10 steady-state H2D
        # bytes/tick from the transfer ledger, the per-direction run
        # totals, and the compile-cache repeat misses inside the
        # obs-overhead window (a previously-seen signature recompiling)
        "h2d_bytes_per_tick": h2d_per_tick,
        # mesh-resident evidence per config (ISSUE 19): the planner
        # mesh size the run measured under, the resident-tier slice of
        # its H2D ledger, and the host-routed strategy-group count the
        # mesh gate pins at zero
        "mesh_resident": mesh_resident,
        "device_transfer_bytes": {
            d: sum(r["bytes"] for r in tbl.values())
            for d, tbl in (doc.get("device_telemetry") or {})
            .get("transfers", {}).items()}
        if isinstance(doc.get("device_telemetry"), dict)
        else doc.get("device_transfer_bytes"),
        "obs_window_repeat_misses": (doc.get("obs") or {}).get(
            "window_repeat_misses")
        if isinstance(doc.get("obs"), dict)
        else doc.get("obs_window_repeat_misses"),
        # strategy-seam evidence per config (cfg11): fragmentation pair,
        # spread-through-the-seam dec/s, and the fallback counters the
        # gates pin at zero
        "strategy": strategy,
        # gang/pipeline evidence per config (cfg12): atomic-admission
        # counters, the gate-held count, and the gang-vs-plain dec/s
        # ratio the regression bound judges
        "gangs": gangs,
        # overload-plane evidence per config (cfg13): the shed ledger
        # (dispatcher-counted vs client-observed, uncounted/unrecovered
        # pinned at zero), heartbeat-stretch evidence, and the
        # time-to-running p99 the regression bound judges
        "overload": overload,
        "headline_compiles": _compiles(doc.get("planner_compiles")),
        "t": doc.get("t"),
        "health": (doc.get("health") or {}).get("status")
        if isinstance(doc.get("health"), dict) else doc.get("health"),
        # per-check health states ({check: pass|warn|fail}) — artifacts
        # carry them under health.checks, history records flattened as
        # health_checks; pre-ISSUE-17 records report None and are
        # exempt from the saturation gates
        "health_checks": (doc.get("health") or {}).get("checks")
        if isinstance(doc.get("health"), dict)
        else doc.get("health_checks"),
        # observability cost of the journeys+tracing plane (ISSUE 17):
        # headline overhead percentage and the XLA compiles that landed
        # inside the overhead-measurement window (must be 0 or the
        # delta measures compilation, not observability)
        "obs_overhead_pct": (doc.get("obs") or {}).get("overhead_pct")
        if isinstance(doc.get("obs"), dict)
        else doc.get("obs_overhead_pct"),
        "obs_window_compiles": (doc.get("obs") or {}).get(
            "window_compiles")
        if isinstance(doc.get("obs"), dict)
        else doc.get("obs_window_compiles"),
        # plan/commit overlap evidence (artifacts and history records
        # both carry these since the pipelined-scheduler PR; older runs
        # report None and are exempt from the overlap gate)
        "pipeline_depth": doc.get("pipeline_depth"),
        "plan_hidden_frac": doc.get("plan_hidden_frac"),
        "plan_commit_overlap_s": doc.get("plan_commit_overlap_s"),
        "plan_overlap_source": doc.get("plan_overlap_source"),
    }


def _load_file(path):
    with open(path) as f:
        text = f.read().strip()
    # artifacts may carry log noise before the JSON line; take the last
    # line that parses
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return _norm(json.loads(line))
        except ValueError:
            continue
    raise SystemExit(f"{path}: no JSON document found")


def _load_history(path, index):
    with open(path) as f:
        entries = [json.loads(line) for line in f if line.strip()]
    if not entries:
        raise SystemExit(f"{path}: empty history")
    try:
        return _norm(entries[index])
    except IndexError:
        raise SystemExit(
            f"{path}: index {index} out of range ({len(entries)} entries)")


def compare(old, new, threshold):
    """Returns (rows, regressions).  A row covers the headline and every
    config present in either run."""
    names = ["headline"] + sorted(set(old["configs"]) | set(new["configs"]))
    rows, regressions = [], []
    for name in names:
        if name == "headline":
            a, b = old["headline"], new["headline"]
        else:
            a = old["configs"].get(name)
            b = new["configs"].get(name)
        if not a or not b:
            rows.append((name, a, b, None, "new" if not a else "gone"))
            continue
        delta = (b - a) / a
        mark = ""
        if delta < -threshold:
            mark = "REGRESSION"
            regressions.append(name)
        rows.append((name, a, b, delta, mark))
    return rows, regressions


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/bench_compare.py")
    p.add_argument("runs", nargs="*",
                   help="two artifact/history-entry JSON files (OLD NEW)")
    p.add_argument("--history", metavar="JSONL",
                   help="compare two entries of a BENCH_HISTORY.jsonl")
    p.add_argument("-a", type=int, default=-2,
                   help="history index of the baseline entry (default -2)")
    p.add_argument("-b", type=int, default=-1,
                   help="history index of the candidate entry (default -1)")
    p.add_argument("--threshold", type=float, default=0.20,
                   help="max tolerated per-config decisions/s regression "
                        "(fraction, default 0.20)")
    p.add_argument("--max-shape-cost", type=float,
                   default=float(os.environ.get(
                       "BENCH_MAX_SHAPE_COST", 1.5)),
                   help="shape_cost_x ceiling for the live-manager "
                        "configs cfg6/cfg7 (default 1.5, or env "
                        "BENCH_MAX_SHAPE_COST); the NEW run exceeding "
                        "it exits 1.  The bar is the bench-host "
                        "target — on the slower dev container, where "
                        "the miss is a known standing condition, set "
                        "BENCH_MAX_SHAPE_COST so throughput "
                        "regressions stay distinguishable from it")
    args = p.parse_args(argv)

    if args.history:
        old = _load_history(args.history, args.a)
        new = _load_history(args.history, args.b)
        labels = (f"{os.path.basename(args.history)}[{args.a}]",
                  f"{os.path.basename(args.history)}[{args.b}]")
    elif len(args.runs) == 2:
        old = _load_file(args.runs[0])
        new = _load_file(args.runs[1])
        labels = tuple(os.path.basename(r) for r in args.runs)
    else:
        p.error("pass two run files, or --history JSONL")
        return 2

    rows, regressions = compare(old, new, args.threshold)
    # absolute-bar gate failures, kept apart from throughput
    # regressions so each exits 1 under its own name
    gate_failures = []
    print(f"{'config':<28} {labels[0]:>16} {labels[1]:>16} {'delta':>9}")
    for name, a, b, delta, mark in rows:
        sa = f"{a:,.1f}" if a else "-"
        sb = f"{b:,.1f}" if b else "-"
        sd = f"{delta * 100:+.1f}%" if delta is not None else mark
        line = f"{name:<28} {sa:>16} {sb:>16} {sd:>9}"
        if mark == "REGRESSION":
            line += "  <-- REGRESSION"
        print(line)
    if old.get("health") or new.get("health"):
        print(f"\nhealth: {old.get('health')} -> {new.get('health')}")
    # overlap gate: a run with the pipeline ON (depth > 1) whose
    # plan/commit overlap collapsed to 0 lost the pipelining win even if
    # raw throughput hasn't (yet) regressed past the threshold — fail it
    # like any other regression.  The gate keys on the NEW run alone (a
    # zero-overlap baseline must not disarm it), when the overlap was
    # measured in a window where it is meaningful: the cfg6 multi-group
    # tick always is; for source-less records (transitional) fall back
    # to requiring the baseline to have shown overlap.  Runs predating
    # the overlap fields or with the serial escape hatch are exempt —
    # as are headline-window measurements (a single-group tick has no
    # group to overlap with).
    old_h, new_h = old.get("plan_hidden_frac"), new.get("plan_hidden_frac")
    if old_h is not None or new_h is not None:
        print(f"plan_hidden_frac: {old_h} -> {new_h} "
              f"(pipeline depth {old.get('pipeline_depth')} -> "
              f"{new.get('pipeline_depth')})")
    src = new.get("plan_overlap_source")
    meaningful = src in ("cfg6", "cfg7") \
        or (src is None and (old_h or 0.0) > 0.0)
    if ((new.get("pipeline_depth") or 1) > 1 and new_h is not None
            and not new_h and meaningful):
        print("\nplan/commit overlap regressed to 0 with the pipeline "
              "on", file=sys.stderr)
        gate_failures.append(("overlap-collapse", "plan_hidden_frac"))
    # shape_cost_x gate: the live-manager configs' production-shape cost
    # factor is an absolute bar (ROADMAP direction 1), judged on the NEW
    # run alone — an old run that also missed it must not disarm it
    _LIVE_CFGS = ("6_live_manager_2x100k_x_10k", "7_many_service_10x")
    for name in _LIVE_CFGS:
        sc_old = old.get("shape_cost_x", {}).get(name)
        sc_new = new.get("shape_cost_x", {}).get(name)
        if sc_old is not None or sc_new is not None:
            print(f"shape_cost_x[{name}]: {sc_old} -> {sc_new} "
                  f"(bar <= {args.max_shape_cost})")
        if sc_new is not None and sc_new > args.max_shape_cost:
            print(f"\n{name} shape_cost_x {sc_new} exceeds "
                  f"{args.max_shape_cost}", file=sys.stderr)
            gate_failures.append(("shape-cost-bar",
                                  f"shape_cost_x:{name}={sc_new}"))
    # preemption gate: the mixed-priority config must show preemption
    # counters (the subsystem actually fired) AND pay zero XLA compiles
    # inside its timed window (the victim-kernel signatures are warmed
    # by the config's own warm-up pass) — judged on the NEW run alone
    _PRIO_CFG = "8_mixed_priority_jobs"
    if _PRIO_CFG in new.get("configs", {}):
        pre = new.get("preemptions", {}).get(_PRIO_CFG)
        print(f"preemptions[{_PRIO_CFG}]: "
              f"{old.get('preemptions', {}).get(_PRIO_CFG)} -> {pre}")
        if not pre:
            print(f"\n{_PRIO_CFG} ran without preemption counters — the "
                  "priority subsystem never fired", file=sys.stderr)
            gate_failures.append(("preemption-counters",
                                  f"{_PRIO_CFG} preemptions={pre}"))
        cfg8_compiles = new.get("compiles", {}).get(_PRIO_CFG, 0)
        if cfg8_compiles:
            print(f"\n{_PRIO_CFG} paid {cfg8_compiles} XLA compile(s) in "
                  "its timed window", file=sys.stderr)
            gate_failures.append(("preemption-compile-growth",
                                  f"{_PRIO_CFG} compiles={cfg8_compiles}"))
    # tenant-QoS gate: the autoscale/tenant-storm config must show
    # quota clamps (admission control actually fired) AND pay zero XLA
    # compiles inside its timed window (the quota-mask signatures are
    # warmed by the config's own warm-up pass) — NEW run alone
    _QOS_CFG = "9_autoscale_tenant_storm"
    if _QOS_CFG in new.get("configs", {}):
        qc = new.get("quota_clamps", {}).get(_QOS_CFG)
        print(f"quota_clamps[{_QOS_CFG}]: "
              f"{old.get('quota_clamps', {}).get(_QOS_CFG)} -> {qc}")
        if not qc:
            print(f"\n{_QOS_CFG} ran without quota clamps — tenant "
                  "admission control never fired", file=sys.stderr)
            gate_failures.append(("quota-clamp-counters",
                                  f"{_QOS_CFG} quota_clamps={qc}"))
        cfg9_compiles = new.get("compiles", {}).get(_QOS_CFG, 0)
        if cfg9_compiles:
            print(f"\n{_QOS_CFG} paid {cfg9_compiles} XLA compile(s) in "
                  "its timed window", file=sys.stderr)
            gate_failures.append(("quota-compile-growth",
                                  f"{_QOS_CFG} compiles={cfg9_compiles}"))
    # streaming-scheduler gates (ISSUE 14), judged on the NEW run:
    # (a) the churn config with the plane ENABLED but never actually
    # running an incremental tick silently measured full replans and
    # must not pass as streaming evidence; (b) zero XLA compiles inside
    # its timed windows (its warm-up covers the scatter + plan
    # signatures); (c) the pending->assigned p99 regressing >20% loses
    # the latency bound the config exists to hold, even while raw
    # decisions/s stays inside the threshold.
    _STREAM_CFG = "10_steady_state_churn"
    if _STREAM_CFG in new.get("configs", {}):
        sm = new.get("streaming", {}).get(_STREAM_CFG) or {}
        print(f"streaming[{_STREAM_CFG}]: enabled={sm.get('enabled')} "
              f"incremental={sm.get('incremental_ticks')} "
              f"dirty_frac={sm.get('dirty_frac')} "
              f"resyncs={sm.get('resyncs')} "
              f"fallbacks={sm.get('fallbacks')}")
        if sm.get("enabled") and not sm.get("incremental_ticks"):
            print(f"\n{_STREAM_CFG}: streaming plane enabled but never "
                  "ran an incremental tick", file=sys.stderr)
            gate_failures.append(
                ("streaming-inactive",
                 f"{_STREAM_CFG} incremental_ticks="
                 f"{sm.get('incremental_ticks')}"))
        cfg10_compiles = new.get("compiles", {}).get(_STREAM_CFG, 0)
        if cfg10_compiles:
            print(f"\n{_STREAM_CFG} paid {cfg10_compiles} XLA "
                  "compile(s) in its timed window", file=sys.stderr)
            gate_failures.append(
                ("streaming-compile-growth",
                 f"{_STREAM_CFG} compiles={cfg10_compiles}"))
        p99_old = old.get("pending_assigned_p99_s", {}).get(_STREAM_CFG)
        p99_new = new.get("pending_assigned_p99_s", {}).get(_STREAM_CFG)
        if p99_old is not None or p99_new is not None:
            print(f"pending_assigned_p99_s[{_STREAM_CFG}]: "
                  f"{p99_old} -> {p99_new}")
        if p99_old and p99_new and p99_new > p99_old * (1.0 + 0.20):
            print(f"\n{_STREAM_CFG} pending->assigned p99 regressed "
                  f"{p99_old} -> {p99_new} (>20%)", file=sys.stderr)
            gate_failures.append(
                ("streaming-p99-regression",
                 f"{_STREAM_CFG} p99 {p99_old}->{p99_new}"))
        # device-transfer gate (device-telemetry PR): steady-state H2D
        # bytes/tick from the transfer ledger growing >20% run-over-run
        # means the resident tier started re-shipping columns it used
        # to keep device-side — a transfer regression even while
        # decisions/s still clears the threshold
        xb_old = old.get("h2d_bytes_per_tick", {}).get(_STREAM_CFG)
        xb_new = new.get("h2d_bytes_per_tick", {}).get(_STREAM_CFG)
        if xb_old is not None or xb_new is not None:
            print(f"h2d_bytes_per_tick[{_STREAM_CFG}]: "
                  f"{xb_old} -> {xb_new}")
        if xb_old and xb_new and xb_new > xb_old * (1.0 + 0.20):
            print(f"\n{_STREAM_CFG} steady-state H2D bytes/tick grew "
                  f"{xb_old} -> {xb_new} (>20%)", file=sys.stderr)
            gate_failures.append(
                ("device-transfer-regression",
                 f"{_STREAM_CFG} h2d_bytes_per_tick "
                 f"{xb_old}->{xb_new}"))
        # mesh-resident-transfer gate (ISSUE 19), NEW run alone: cfg10
        # measured under a planner mesh (SWARM_PLANNER_MESH > 1) must
        # keep the resident tier device-side — its per-tick
        # resident-column H2D stays within the dirty-row scatter
        # budget (a full column re-upload at these node counts is
        # orders of magnitude above the bar) — and must route every
        # strategy group through the sharded kernels (zero host-oracle
        # groups).  Single-device runs carry the fields but skip the
        # gate: the bar is the MESH contract.
        _MESH_H2D_BAR = float(os.environ.get(
            "BENCH_MESH_H2D_BAR", 65536.0))
        mr = new.get("mesh_resident", {}).get(_STREAM_CFG) or {}
        if (mr.get("planner_mesh") or 1) > 1:
            rb = mr.get("resident_h2d_bytes_per_tick")
            shg = mr.get("strategy_host_groups")
            print(f"mesh_resident[{_STREAM_CFG}]: "
                  f"mesh={mr.get('planner_mesh')} "
                  f"resident_h2d_bytes_per_tick={rb} "
                  f"strategy_host_groups={shg} "
                  f"(bar <= {_MESH_H2D_BAR:g})")
            if rb is None or rb > _MESH_H2D_BAR:
                print(f"\n{_STREAM_CFG} under a planner mesh moved "
                      f"{rb} resident H2D bytes/tick — the resident "
                      "tier is re-shipping columns instead of "
                      "scattering dirty rows", file=sys.stderr)
                gate_failures.append(
                    ("mesh-resident-transfer",
                     f"{_STREAM_CFG} resident_h2d_bytes_per_tick={rb}"))
            if shg:
                print(f"\n{_STREAM_CFG} under a planner mesh routed "
                      f"{shg} strategy group(s) to the host oracle",
                      file=sys.stderr)
                gate_failures.append(
                    ("mesh-resident-transfer",
                     f"{_STREAM_CFG} strategy_host_groups={shg}"))
    # strategy-seam gates (ISSUE 15), judged on the NEW run's cfg11:
    # (a) binpack must actually beat spread on the stranded-capacity
    # fraction — the whole point of shipping the policy; (b) zero
    # strategy fallbacks for spread/binpack (every group served by its
    # selected strategy); (c) fallback_groups 0 (the node.ip-CIDR
    # device column holds — constrained services no longer leave the
    # device path); (d) compile-flat timed windows; (e) spread THROUGH
    # the seam regressing >10% vs the old run loses the seam's
    # no-overhead contract even inside the global 20% threshold.
    _FRAG_CFG = "11_fragmentation_strategies"
    if _FRAG_CFG in new.get("configs", {}):
        sg = new.get("strategy", {}).get(_FRAG_CFG) or {}
        sf, bf = (sg.get("stranded_frac_spread"),
                  sg.get("stranded_frac_binpack"))
        print(f"strategy[{_FRAG_CFG}]: stranded spread={sf} "
              f"binpack={bf} fallbacks={sg.get('strategy_fallbacks')} "
              f"fallback_groups={sg.get('fallback_groups')}")
        if sf is None or bf is None or not bf < sf:
            print(f"\n{_FRAG_CFG}: binpack did not beat spread on "
                  f"stranded capacity ({bf} vs {sf})", file=sys.stderr)
            gate_failures.append(("strategy-fragmentation",
                                  f"binpack={bf} spread={sf}"))
        if sg.get("strategy_fallbacks"):
            print(f"\n{_FRAG_CFG}: strategy fallbacks counted",
                  file=sys.stderr)
            gate_failures.append(
                ("strategy-fallback",
                 f"strategy_fallbacks={sg.get('strategy_fallbacks')}"))
        if sg.get("fallback_groups"):
            print(f"\n{_FRAG_CFG}: node.ip-constrained groups left the "
                  "device path", file=sys.stderr)
            gate_failures.append(
                ("strategy-device-waiver",
                 f"fallback_groups={sg.get('fallback_groups')}"))
        cfg11_compiles = new.get("compiles", {}).get(_FRAG_CFG, 0)
        if cfg11_compiles:
            print(f"\n{_FRAG_CFG} paid {cfg11_compiles} XLA compile(s) "
                  "in its timed window", file=sys.stderr)
            gate_failures.append(("strategy-compile-growth",
                                  f"compiles={cfg11_compiles}"))
        sp_old = (old.get("strategy", {}).get(_FRAG_CFG) or {}).get(
            "spread_decisions_per_sec")
        sp_new = sg.get("spread_decisions_per_sec")
        if sp_old is not None or sp_new is not None:
            print(f"spread_decisions_per_sec[{_FRAG_CFG}]: "
                  f"{sp_old} -> {sp_new}")
        if sp_old and sp_new and sp_new < sp_old * 0.90:
            print(f"\n{_FRAG_CFG} spread-through-the-seam dec/s "
                  f"regressed {sp_old} -> {sp_new} (>10%)",
                  file=sys.stderr)
            gate_failures.append(
                ("strategy-spread-regression",
                 f"spread dps {sp_old}->{sp_new}"))
    # gang/pipeline gates (ISSUE 16), judged on the NEW run's cfg12:
    # (a) zero partially-placed gangs — a strict subset committing is
    # exactly the failure the atomic admission path exists to prevent;
    # (b) every gang admitted with zero deferrals (ample-capacity
    # config: a deferral means admission broke, not that the cluster
    # was full); (c) zero host-oracle gang verdicts (the device
    # gang_fit route held; the oracle is the breaker fallback, not the
    # steady path); (d) the DAG gate actually held — downstream stages
    # deferred at tick 1 — then drained (the config asserts full
    # placement internally); (e) compile-flat timed windows; (f) the
    # gang tick's dec/s within 4x of the plain tick's on the SAME
    # workload — the admission path's overhead bound.
    _GANG_CFG = "12_gang_pipeline"
    if _GANG_CFG in new.get("configs", {}):
        gg = new.get("gangs", {}).get(_GANG_CFG) or {}
        print(f"gangs[{_GANG_CFG}]: "
              f"admitted={gg.get('gangs_admitted')} "
              f"deferred={gg.get('gang_deferred')} "
              f"atomicity_violations="
              f"{gg.get('gang_atomicity_violations')} "
              f"host_verdicts={gg.get('gang_fit_host_verdicts')} "
              f"gated={gg.get('pipeline_gated_deferrals')} "
              f"vs_plain={gg.get('gang_vs_plain_x')}x")
        if gg.get("gang_atomicity_violations"):
            print(f"\n{_GANG_CFG}: partially-placed gang unit(s) "
                  "committed", file=sys.stderr)
            gate_failures.append(
                ("gang-atomicity",
                 f"violations={gg.get('gang_atomicity_violations')}"))
        if not gg.get("gangs_admitted") or gg.get("gang_deferred"):
            print(f"\n{_GANG_CFG}: gang admission did not converge "
                  f"(admitted={gg.get('gangs_admitted')} "
                  f"deferred={gg.get('gang_deferred')})",
                  file=sys.stderr)
            gate_failures.append(
                ("gang-admission",
                 f"admitted={gg.get('gangs_admitted')} "
                 f"deferred={gg.get('gang_deferred')}"))
        if gg.get("gang_fit_host_verdicts"):
            print(f"\n{_GANG_CFG}: gang feasibility fell back to the "
                  "host oracle", file=sys.stderr)
            gate_failures.append(
                ("gang-device-route",
                 f"host_verdicts={gg.get('gang_fit_host_verdicts')}"))
        if not gg.get("pipeline_gated_deferrals"):
            print(f"\n{_GANG_CFG}: downstream pipeline stages were "
                  "never gated — the DAG gate did not hold",
                  file=sys.stderr)
            gate_failures.append(
                ("pipeline-gate",
                 f"gated={gg.get('pipeline_gated_deferrals')}"))
        cfg12_compiles = new.get("compiles", {}).get(_GANG_CFG, 0)
        if cfg12_compiles:
            print(f"\n{_GANG_CFG} paid {cfg12_compiles} XLA "
                  "compile(s) in its timed window", file=sys.stderr)
            gate_failures.append(("gang-compile-growth",
                                  f"compiles={cfg12_compiles}"))
        ratio = gg.get("gang_vs_plain_x")
        if ratio is not None and ratio > 4.0:
            print(f"\n{_GANG_CFG}: gang tick dec/s fell more than 4x "
                  f"below the plain tick's ({ratio}x)", file=sys.stderr)
            gate_failures.append(("gang-admission-overhead",
                                  f"gang_vs_plain_x={ratio}"))
    # overload-plane gates (ISSUE 20), judged on the NEW run's cfg13:
    # (a) the shed ledger must reconcile EXACTLY — an uncounted shed is
    # silent loss, an unrecovered one means a replica never reached
    # RUNNING after admission shed it; (b) the plane must have actually
    # FIRED (zero sheds at a fan-out sized to saturate the admission
    # edge means the bound went dead, and an unstretched heartbeat
    # period at >=1k sessions means the stretch plumbing rotted);
    # (c) zero premature expirations — the stretch an agent was
    # PROMISED must extend its expiry window; (d) compile-flat timed
    # windows; (e) the time-to-running p99 regressing >20% loses the
    # latency bound the config exists to hold.
    _OVL_CFG = "13_million_swarm"
    if _OVL_CFG in new.get("configs", {}):
        ov = new.get("overload", {}).get(_OVL_CFG) or {}
        sheds = ov.get("sheds") or {}
        print(f"overload[{_OVL_CFG}]: sessions={ov.get('sessions')} "
              f"sheds={sheds.get('dispatcher')} "
              f"uncounted={sheds.get('uncounted')} "
              f"unrecovered={sheds.get('unrecovered')} "
              f"hb_stretch={ov.get('hb_stretch_factor')}x "
              f"premature_expirations="
              f"{ov.get('premature_expirations')}")
        if sheds.get("uncounted") or sheds.get("unrecovered"):
            print(f"\n{_OVL_CFG}: shed ledger did not reconcile "
                  f"(uncounted={sheds.get('uncounted')} "
                  f"unrecovered={sheds.get('unrecovered')}) — "
                  "degraded mode went silently lossy", file=sys.stderr)
            gate_failures.append(
                ("shed-ledger",
                 f"uncounted={sheds.get('uncounted')} "
                 f"unrecovered={sheds.get('unrecovered')}"))
        if not sheds.get("dispatcher"):
            print(f"\n{_OVL_CFG}: the admission edge never shed under "
                  "a fan-out sized to saturate it", file=sys.stderr)
            gate_failures.append(
                ("overload-inactive",
                 f"sheds={sheds.get('dispatcher')}"))
        if not ov.get("hb_stretches") \
                or (ov.get("hb_stretch_factor") or 0) <= 1.0:
            print(f"\n{_OVL_CFG}: heartbeat period never stretched at "
                  f"{ov.get('sessions')} sessions", file=sys.stderr)
            gate_failures.append(
                ("heartbeat-stretch-inactive",
                 f"stretches={ov.get('hb_stretches')} "
                 f"factor={ov.get('hb_stretch_factor')}"))
        if ov.get("premature_expirations"):
            print(f"\n{_OVL_CFG}: session(s) expired before their "
                  "promised (stretched) window", file=sys.stderr)
            gate_failures.append(
                ("premature-expiration",
                 f"premature={ov.get('premature_expirations')}"))
        cfg13_compiles = new.get("compiles", {}).get(_OVL_CFG, 0)
        if cfg13_compiles:
            print(f"\n{_OVL_CFG} paid {cfg13_compiles} XLA "
                  "compile(s) in its timed window", file=sys.stderr)
            gate_failures.append(("overload-compile-growth",
                                  f"compiles={cfg13_compiles}"))
        ttr_old = (old.get("overload", {}).get(_OVL_CFG)
                   or {}).get("time_to_running_p99_s")
        ttr_new = ov.get("time_to_running_p99_s")
        if ttr_old is not None or ttr_new is not None:
            print(f"time_to_running_p99_s[{_OVL_CFG}]: "
                  f"{ttr_old} -> {ttr_new}")
        if ttr_old and ttr_new and ttr_new > ttr_old * (1.0 + 0.20):
            print(f"\n{_OVL_CFG} time-to-running p99 regressed "
                  f"{ttr_old} -> {ttr_new} (>20%)", file=sys.stderr)
            gate_failures.append(
                ("overload-p99-regression",
                 f"{_OVL_CFG} p99 {ttr_old}->{ttr_new}"))
    # commit-plane gates (ISSUE 13), judged on the live-manager configs:
    # (a) the commit phase regressing >20% wall-clock loses the columnar
    # plane's win even while decisions/s still clears the threshold;
    # (b) a run whose native commit plane was enabled but inactive — or
    # that counted fallback ticks inside the timed window — silently ran
    # the Python oracle and must not pass as evidence.
    for name in _LIVE_CFGS:
        cp_old = old.get("commit_phase_s", {}).get(name)
        cp_new = new.get("commit_phase_s", {}).get(name)
        if cp_old is not None or cp_new is not None:
            print(f"commit_phase_s[{name}]: {cp_old} -> {cp_new}")
        if cp_old and cp_new and cp_new > cp_old * (1.0 + 0.20):
            print(f"\n{name} commit_phase_s regressed "
                  f"{cp_old} -> {cp_new} (>20%)", file=sys.stderr)
            gate_failures.append(
                ("commit-phase-regression",
                 f"{name} commit_phase_s {cp_old}->{cp_new}"))
        nc = new.get("native_commit", {}).get(name)
        if nc:
            print(f"native_commit[{name}]: enabled={nc.get('enabled')} "
                  f"active={nc.get('active')} "
                  f"fallbacks={nc.get('fallbacks')}")
            if nc.get("enabled") and (
                    not nc.get("active") or nc.get("fallbacks")):
                print(f"\n{name}: native commit plane fell back to "
                      "Python inside the timed window", file=sys.stderr)
                gate_failures.append(
                    ("native-commit-fallback",
                     f"{name} active={nc.get('active')} "
                     f"fallbacks={nc.get('fallbacks')}"))
    # compile-flatness gate: XLA compiles inside timed regions must not
    # GROW — warm-up covers every signature a config touches, so any
    # growth means a new shape leaked into a timed window.  Judged over
    # the headline plus configs present in BOTH runs (a brand-new
    # config's first-run compiles are its own warm-up problem, surfaced
    # by its per-config row, not a regression of this run pair).
    shared_cfgs = set(old.get("compiles", {})) & set(
        new.get("compiles", {}))
    old_c = old.get("headline_compiles", 0) + sum(
        old["compiles"][c] for c in shared_cfgs)
    new_c = new.get("headline_compiles", 0) + sum(
        new["compiles"][c] for c in shared_cfgs)
    print(f"planner_compiles (timed regions): {old_c} -> {new_c}")
    if new_c > old_c:
        print(f"\nplanner_compiles grew {old_c} -> {new_c}: a compile "
              "landed inside a timed region", file=sys.stderr)
        gate_failures.append(("compile-growth",
                              f"planner_compiles {old_c}->{new_c}"))
    # observability gates (ISSUE 17), judged on the NEW run alone:
    # (a) obs-overhead bound — the headline tick with journeys +
    # tracing + a live store tap enabled must run within 3% of the
    # dark tick, else the observability plane is taxing the hot path;
    # (b) the overhead-measurement window must be compile-free — a
    # compile inside either half means the delta measured XLA, not
    # observability; (c) the saturation SLO checks fed by the run's
    # own registry — scheduler-plane occupancy and raft apply lag —
    # reporting FAIL means a plane saturated during the run.
    ov_old = old.get("obs_overhead_pct")
    ov_new = new.get("obs_overhead_pct")
    if ov_old is not None or ov_new is not None:
        print(f"obs_overhead_pct: {ov_old} -> {ov_new} (bar <= 3.0)")
    if ov_new is not None and ov_new > 3.0:
        print(f"\nobservability overhead {ov_new}% exceeds the 3% "
              "bound with journeys+tracing enabled", file=sys.stderr)
        gate_failures.append(("obs-overhead",
                              f"overhead_pct={ov_new}"))
    owc = new.get("obs_window_compiles")
    if owc is not None:
        print(f"obs_window_compiles: "
              f"{old.get('obs_window_compiles')} -> {owc}")
    if owc:
        print(f"\nobs-overhead window paid {owc} XLA compile(s) — the "
              "overhead delta is not trustworthy", file=sys.stderr)
        gate_failures.append(("obs-compile-growth",
                              f"window_compiles={owc}"))
    # compile-cache-hit gate (device-telemetry PR), NEW run alone: any
    # timed-window MISS on a signature the compile-cache ledger had
    # already seen means a warm jit cache was invalidated mid-run —
    # the per-signature twin of the aggregate compile-flatness gate
    wrm = new.get("obs_window_repeat_misses")
    if wrm is not None:
        print(f"obs_window_repeat_misses: "
              f"{old.get('obs_window_repeat_misses')} -> {wrm}")
    if wrm:
        print(f"\ncompile-cache ledger counted timed-window miss(es) "
              f"on previously-seen signature(s): {', '.join(wrm)}",
              file=sys.stderr)
        gate_failures.append(("compile-cache-hit",
                              f"repeat_misses={','.join(wrm)}"))
    dtb_old = old.get("device_transfer_bytes")
    dtb_new = new.get("device_transfer_bytes")
    if dtb_old or dtb_new:
        print(f"device_transfer_bytes: {dtb_old} -> {dtb_new}")
    hc_old = old.get("health_checks") or {}
    hc_new = new.get("health_checks") or {}
    for check, gate in (
            ("scheduler_occupancy", "scheduler-occupancy-saturation"),
            ("apply_lag", "apply-lag-saturation"),
            ("dispatcher_overload", "dispatcher-overload-saturation"),
            ("heartbeat_stretch", "heartbeat-stretch-saturation")):
        st = hc_new.get(check)
        if st is not None or hc_old.get(check) is not None:
            print(f"health[{check}]: {hc_old.get(check)} -> {st}")
        if st == "fail":
            print(f"\nsaturation check {check} FAILED on the new run",
                  file=sys.stderr)
            gate_failures.append((gate, f"{check}={st}"))
    # distinct summaries per gate: a shape-bar or compile miss is NOT a
    # ">20% throughput regression" and must not read like one
    failed = False
    if regressions:
        print(f"\n{len(regressions)} config(s) regressed more than "
              f"{args.threshold * 100:.0f}%: {', '.join(regressions)}",
              file=sys.stderr)
        failed = True
    if gate_failures:
        by_gate = {}
        for gate, detail in gate_failures:
            by_gate.setdefault(gate, []).append(detail)
        for gate, details in sorted(by_gate.items()):
            print(f"gate failed [{gate}]: {', '.join(details)}",
                  file=sys.stderr)
        failed = True
    if failed:
        return 1
    print(f"\nok: no config regressed more than "
          f"{args.threshold * 100:.0f}% and all gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
