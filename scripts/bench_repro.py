"""Reproducibility half of ROADMAP item 1: run the headline + cfg6
bench three consecutive times and assert the bar holds on EVERY run.

Each repeat is a fresh ``bench.py`` process (clean heap, clean jit
cache) restricted to config 6 — the production-shape pipelined tick —
via BENCH_CONFIGS=6, with the e2e/obs-overhead/host-baseline extras
skipped.  Every run appends its record to BENCH_HISTORY.jsonl exactly
as a full bench run would (bench.py owns the append), so the ledger
carries all three and ``bench_compare.py --history`` can diff them.

Bar (each configurable):
  * cfg6 decisions/sec        >= --min-dps        (default 220_000)
  * cfg6 shape_cost_x         <= --max-shape-cost (default 1.5)
  * artifact plan_hidden_frac >  --min-hidden     (default 0.15; only
    enforced while the pipeline is on, i.e. pipeline_depth > 1 —
    lowered from 0.3 when the columnar commit plane shrank the commit
    wall the plan used to hide behind)
  * cfg6 commit_phase_s       <= --max-commit-s   (default 0.665 =
    0.5x the r06 commit wall, the ISSUE 13 acceptance bar)
  * cfg6 native_commit must not have fallen back to Python

Exit status: 0 when every repeat holds the bar, 1 otherwise.

Usage:
    python scripts/bench_repro.py              # 3 repeats, full bar
    python scripts/bench_repro.py --repeat 5
    python scripts/bench_repro.py --min-dps 0  # record-only mode
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG6 = "6_live_manager_2x100k_x_10k"


def run_once(extra_env):
    env = dict(os.environ)
    env.update({
        "BENCH_CONFIGS": "6",
        "BENCH_SKIP_E2E": "1",
        "BENCH_SKIP_OBS": "1",
        "BENCH_SKIP_HOST": "1",
    })
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        raise SystemExit(
            f"bench.py failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise SystemExit("bench.py produced no JSON artifact")


def check(artifact, args):
    """Returns (summary row dict, list of violation strings)."""
    cfg6 = (artifact.get("configs") or {}).get(CFG6) or {}
    dps = cfg6.get("decisions_per_sec") or 0.0
    shape = cfg6.get("shape_cost_x")
    hidden = artifact.get("plan_hidden_frac", 0.0)
    depth = artifact.get("pipeline_depth", 1)
    commit_s = cfg6.get("commit_phase_s")
    native = cfg6.get("native_commit") or {}
    problems = []
    if dps < args.min_dps:
        problems.append(f"cfg6 {dps:,.0f} dec/s < {args.min_dps:,.0f}")
    if shape is not None and shape > args.max_shape_cost:
        problems.append(f"shape_cost_x {shape} > {args.max_shape_cost}")
    if depth > 1 and hidden <= args.min_hidden:
        problems.append(
            f"plan_hidden_frac {hidden} <= {args.min_hidden} with the "
            f"pipeline on (depth {depth})")
    if commit_s is not None and commit_s > args.max_commit_s:
        problems.append(
            f"cfg6 commit_phase_s {commit_s} > {args.max_commit_s}")
    if native.get("enabled") and (not native.get("active")
                                  or native.get("fallbacks")):
        problems.append(
            f"native commit plane fell back to Python ({native})")
    row = {"headline": artifact.get("value"), "cfg6_dps": dps,
           "shape_cost_x": shape, "plan_hidden_frac": hidden,
           "pipeline_depth": depth, "commit_phase_s": commit_s,
           "native_commit": native}
    return row, problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/bench_repro.py")
    p.add_argument("--repeat", type=int, default=3,
                   help="consecutive bench runs (default 3)")
    p.add_argument("--min-dps", type=float, default=220_000,
                   help="cfg6 decisions/sec floor (default 220000)")
    p.add_argument("--max-shape-cost", type=float, default=1.5,
                   help="cfg6 shape_cost_x ceiling (default 1.5)")
    p.add_argument("--min-hidden", type=float, default=0.15,
                   help="plan_hidden_frac floor while pipelined "
                        "(default 0.15; was 0.3 before the columnar "
                        "commit plane — a 3x-smaller commit phase "
                        "leaves less wall to hide the plan behind, so "
                        "the overlap fraction legitimately shrank "
                        "while the tick got strictly faster)")
    p.add_argument("--max-commit-s", type=float, default=0.665,
                   help="cfg6 commit_phase_s ceiling (default 0.665 = "
                        "0.5x the r06 commit wall — the ISSUE 13 "
                        "acceptance bar)")
    args = p.parse_args(argv)

    failures = 0
    for i in range(args.repeat):
        artifact = run_once({})
        row, problems = check(artifact, args)
        status = "ok" if not problems else "FAIL"
        print(f"run {i + 1}/{args.repeat}: {status}  "
              f"cfg6={row['cfg6_dps']:,.0f} dec/s  "
              f"shape_cost_x={row['shape_cost_x']}  "
              f"plan_hidden_frac={row['plan_hidden_frac']}  "
              f"commit_phase_s={row['commit_phase_s']}  "
              f"depth={row['pipeline_depth']}")
        for prob in problems:
            print(f"  - {prob}", file=sys.stderr)
        failures += bool(problems)
    if failures:
        print(f"\n{failures}/{args.repeat} runs failed the bar",
              file=sys.stderr)
        return 1
    print(f"\nok: the bar held on all {args.repeat} consecutive runs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
