#!/usr/bin/env python
"""General chaos sweeper: seed-sweep any scenario subset under the
raft-attached control plane and report fault-type x component coverage.

    python scripts/chaos_sweep.py                       # default suites
    python scripts/chaos_sweep.py --fast                # CI subset
    python scripts/chaos_sweep.py --fuzz 20             # seeds/scenario
    python scripts/chaos_sweep.py --suite update
    python scripts/chaos_sweep.py --scenario long-soak --fuzz 3
    python scripts/chaos_sweep.py --list

Generalizes scripts/failover_fuzz.py (which remains as a thin wrapper):
every (scenario, seed) runs the full control plane — scheduler,
dispatcher, allocator, restart supervisor, replicated + global
orchestrators, and (new in ISSUE 8) the REAL rolling-update supervisor
in threadless drive mode — through its fault timeline under every
invariant checker.

The sweep's verdict is twofold:

* **safety/quality** — every run must hold every invariant (task FSM,
  ledger, fencing, update convergence, version purity, placement
  quality).  Failures print the violations, the exact replay command,
  and the flight-recorder post-mortem path + sha the runner dumped.
* **coverage** — the engine trace records every injected fault
  (``fault <type> <target>`` / ``net drop`` lines).  The sweep
  aggregates them into a fault-type x component matrix and fails when
  any cell REQUIRED for the swept scenario set stayed at zero: a chaos
  suite that silently stopped injecting a fault class is itself a bug.

Exit status is 0 only when every run held every invariant AND no
required coverage cell is empty.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.sim.scenario import (          # noqa: E402
    FAILOVER_SCENARIOS, FUZZ_POOL, GANG_SCENARIOS, LEGACY_RCP_SCENARIOS,
    OVERLOAD_SCENARIOS, PREEMPT_SCENARIOS, QOS_SCENARIOS, READ_SCENARIOS,
    SCENARIOS, STREAMING_SCENARIOS, UPDATE_SCENARIOS, run_scenario,
)

#: named scenario subsets.  "default" is what CI's slow sweep runs; the
#: "fuzz" suite is the same seed-rotating pool `python -m swarmkit_tpu.sim
#: --fuzz` draws from (minus the exclusions documented in scenario.py).
SUITES: Dict[str, tuple] = {
    "failover": FAILOVER_SCENARIOS,
    "update": UPDATE_SCENARIOS,
    "preempt": PREEMPT_SCENARIOS,
    "qos": QOS_SCENARIOS,
    "read": READ_SCENARIOS,
    "streaming": STREAMING_SCENARIOS,
    "gang": GANG_SCENARIOS,
    "overload": OVERLOAD_SCENARIOS,
    "legacy-rcp": LEGACY_RCP_SCENARIOS,
    "default": FAILOVER_SCENARIOS + UPDATE_SCENARIOS
    + PREEMPT_SCENARIOS + QOS_SCENARIOS + READ_SCENARIOS
    + STREAMING_SCENARIOS + GANG_SCENARIOS + OVERLOAD_SCENARIOS
    + LEGACY_RCP_SCENARIOS,
    "fuzz": FUZZ_POOL,
}

# ------------------------------------------------------------- coverage

#: trace grammar: "<ts> fault <type> <target...>" and
#: "<ts> net drop <src>-><dst> <msgtype>"
_FAULT_RE = re.compile(r"^\d+\.\d+ fault ([a-z-]+)(?: (\S+))?")
_DROP_RE = re.compile(r"^\d+\.\d+ net drop ")

#: fault types that always hit one component regardless of target
_FIXED_COMPONENT = {
    "agent-crash": "agent", "agent-restart": "agent",
    "agent-partition": "agent", "task-failure-storm": "agent",
    "rollout-poison": "updater",
    "preempt-burst": "scheduler",
    "autoscale-burst": "scheduler", "quota-clamp": "scheduler",
    "gang-deadlock": "scheduler",
    "pipeline-stage": "orchestrator", "stage-poison": "agent",
    "stale-read-probe": "read-plane", "read-storm": "read-plane",
    # columnar commit plane: logged once per raft-attached run when a
    # binary block entry rides consensus with the native decode active
    "native-commit-plane": "store",
    # streaming scheduler: logged when a leader handoff ACTUALLY
    # rebuilt the resident device-input state (epoch resync observed)
    "streaming-resync": "scheduler",
    # overload plane: logged the first time the dispatcher ACTUALLY
    # shed an admission / the first time the heartbeat period ACTUALLY
    # stretched — an empty cell means the backpressure plane went dead
    "overload-shed": "dispatcher",
    "heartbeat-stretch": "agent",
    "fan-out-burst": "dispatcher",
    "cut": "network", "heal": "network", "split": "network",
    "heal-all": "network", "drop": "network", "drop-burst": "network",
    "clock-skew": "clock",
}


def classify(ftype: str, target: str) -> str:
    """Component a fault perturbs: manager (raft/control plane), agent,
    network, updater (rollout workload), scheduler (priority/preemption
    pressure), or clock."""
    fixed = _FIXED_COMPONENT.get(ftype)
    if fixed is not None:
        return fixed
    # crash / restart / stepdown / isolate / rejoin / partition:
    # manager vs agent by target id convention (m* managers, w* workers)
    if target.startswith("w"):
        return "agent"
    return "manager"


#: coverage cells each scenario is REQUIRED to exercise, judged against
#: the sweep-wide aggregate (a probabilistic fault like a drop burst
#: need not land in every seed, but must land somewhere in the sweep).
#: Keep in sync with the fault timelines in sim/scenario.py — the gate
#: exists so a scenario edit cannot silently drop a fault class.
REQUIRED_CELLS: Dict[str, Set[Tuple[str, str]]] = {
    "rolling-upgrade-chaos": {
        ("stepdown", "manager"), ("isolate", "manager"),
        ("rejoin", "manager"), ("agent-crash", "agent"),
        ("agent-restart", "agent"), ("agent-partition", "agent"),
        ("rollout-poison", "updater"), ("drop", "network")},
    "cascading-failure-rebalance": {
        ("agent-crash", "agent"), ("agent-restart", "agent"),
        ("crash", "manager"), ("restart", "manager")},
    "long-soak": {
        ("agent-crash", "agent"), ("agent-restart", "agent"),
        ("crash", "manager"), ("restart", "manager"),
        ("split", "network"), ("heal-all", "network"),
        ("stepdown", "manager"), ("rollout-poison", "updater"),
        ("drop", "network")},
    "partition-churn-rcp": {
        ("split", "network"), ("heal-all", "network"),
        ("drop-burst", "network"), ("drop", "network")},
    "crash-restart-churn-rcp": {
        ("crash", "manager"), ("restart", "manager"),
        ("agent-crash", "agent"), ("agent-restart", "agent")},
    "agent-storm-rcp": {
        ("task-failure-storm", "agent"), ("agent-crash", "agent")},
    "leader-crash-mid-tick": {
        ("crash", "manager"), ("restart", "manager"),
        ("agent-crash", "agent"), ("agent-restart", "agent")},
    "leader-crash-mid-tick-d1": {
        ("crash", "manager"), ("restart", "manager")},
    "partition-pipelined-commit": {
        ("partition", "manager"), ("isolate", "manager"),
        ("rejoin", "manager")},
    "partition-pipelined-commit-d1": {
        ("partition", "manager"), ("isolate", "manager"),
        ("rejoin", "manager")},
    "failover-churn-rollout": {
        ("crash", "manager"), ("restart", "manager"),
        ("stepdown", "manager"), ("task-failure-storm", "agent"),
        ("agent-crash", "agent"), ("agent-restart", "agent")},
    "preemption-storm": {
        ("preempt-burst", "scheduler"), ("agent-crash", "agent"),
        ("agent-restart", "agent"), ("stepdown", "manager"),
        ("drop", "network"),
        # the raft-attached scheduler's block commits must ride the
        # NATIVE columnar commit plane (ISSUE 13) — an empty cell means
        # it silently fell back to the Python oracle sweep-wide
        ("native-commit-plane", "store")},
    # fused-vs-per-service differential under churn, now also the
    # columnar-commit-plane coverage anchor for the fuzz suite
    "fused-differential-churn": {
        ("native-commit-plane", "store")},
    # streaming scheduler twin-store differential: the stepdown must
    # happen AND the successor reign's refresh must actually resync
    # resident state — an empty cell means the handoff path rotted
    "steady-state-churn": {
        ("stepdown", "manager"),
        ("streaming-resync", "scheduler")},
    # autoscaler + tenant QoS: the burst is injected, but the
    # quota-clamp cell is logged only when the scheduler ACTUALLY
    # clamped — a suite edit that stops clamping empties the cell
    "tenant-storm": {
        ("autoscale-burst", "scheduler"), ("quota-clamp", "scheduler"),
        ("crash", "manager"), ("restart", "manager"),
        ("agent-crash", "agent"), ("agent-restart", "agent"),
        ("drop", "network")},
    # follower-served read plane: partition × read-plane (the stranded
    # ex-leader must be PROBED, not just partitioned) and clock × lease
    # (a skew fault must run while lease reads are in play)
    "follower-read-failover": {
        ("crash", "manager"), ("restart", "manager"),
        ("isolate", "manager"), ("rejoin", "manager"),
        ("stale-read-probe", "read-plane"), ("clock-skew", "clock"),
        ("agent-crash", "agent"), ("agent-restart", "agent")},
    "read-storm-degraded": {
        ("read-storm", "read-plane"), ("stepdown", "manager"),
        ("crash", "manager"), ("restart", "manager"),
        ("drop", "network")},
    # gang scheduling: two half-placeable gangs must actually contend
    # (the injection cell), under agent churn and a stepdown
    "gang-deadlock": {
        ("gang-deadlock", "scheduler"), ("agent-crash", "agent"),
        ("agent-restart", "agent"), ("stepdown", "manager"),
        ("drop", "network")},
    # pipeline workflows: the poisoned mid stage must be injected AND
    # at least one of its tasks must actually die on startup
    "pipeline-chaos": {
        ("pipeline-stage", "orchestrator"),
        ("stage-poison", "agent"),
        ("crash", "manager"), ("restart", "manager"),
        ("stepdown", "manager"), ("drop", "network")},
    # million-swarm overload harness: the dispatcher must ACTUALLY shed
    # (not just be configured to) and the heartbeat period must ACTUALLY
    # stretch under the session load — empty cells mean the fan-out no
    # longer saturates the admission plane and the scenario is testing
    # nothing
    "million-swarm": {
        ("overload-shed", "dispatcher"),
        ("heartbeat-stretch", "agent"),
        ("fan-out-burst", "dispatcher"),
        ("crash", "manager"), ("restart", "manager"),
        ("agent-crash", "agent"), ("agent-restart", "agent"),
        ("drop", "network")},
}


def coverage_matrix(traces: Iterable[List[str]]) -> Dict[str, Dict[str, int]]:
    """Aggregate fault-type x component counts over engine traces."""
    matrix: Dict[str, Dict[str, int]] = {}
    for trace in traces:
        for line in trace:
            m = _FAULT_RE.match(line)
            if m:
                ftype, target = m.group(1), m.group(2) or ""
            elif _DROP_RE.match(line):
                ftype, target = "drop", ""
            else:
                continue
            comp = classify(ftype, target)
            row = matrix.setdefault(ftype, {})
            row[comp] = row.get(comp, 0) + 1
    return {f: dict(sorted(row.items()))
            for f, row in sorted(matrix.items())}


def required_cells(scenarios: Iterable[str]) -> Set[Tuple[str, str]]:
    cells: Set[Tuple[str, str]] = set()
    for name in scenarios:
        cells |= REQUIRED_CELLS.get(name, set())
    return cells


def uncovered(matrix: Dict[str, Dict[str, int]],
              required: Set[Tuple[str, str]]) -> List[Tuple[str, str]]:
    return sorted((f, c) for f, c in required
                  if not matrix.get(f, {}).get(c))


# ---------------------------------------------------------------- sweep

def sweep(scenarios, n_seeds: int, start_seed: int = 0,
          progress=None, keep_trace: bool = True) -> list:
    """Run every (scenario, seed) pair; returns all SimReports (shared
    with scripts/failover_fuzz.py).  ``keep_trace`` retains each run's
    engine trace on the report — required for the coverage matrix, but
    a caller that never reads traces (failover_fuzz) passes False so a
    wide sweep does not hold every run's full log in memory."""
    reports = []
    for name in scenarios:
        for seed in range(start_seed, start_seed + n_seeds):
            r = run_scenario(name, seed, keep_trace=keep_trace)
            reports.append(r)
            if progress is not None:
                progress(r)
    return reports


def verdict(reports, scenarios, n_seeds: int, start_seed: int,
            check_coverage: bool = True) -> dict:
    bad = [r for r in reports if not r.ok]
    matrix = coverage_matrix(r.trace for r in reports)
    required = required_cells(scenarios) if check_coverage else set()
    missing = uncovered(matrix, required)
    return {
        "scenarios": list(scenarios),
        "seeds_per_scenario": n_seeds,
        "start_seed": start_seed,
        "runs": len(reports),
        "coverage": {
            "matrix": matrix,
            "required": sorted(list(c) for c in required),
            "uncovered": [list(c) for c in missing],
        },
        "failures": [
            {"scenario": r.scenario, "seed": r.seed,
             "violations": r.violations,
             "flightrec": r.flightrec_path,
             "flightrec_sha256": r.flightrec_sha256,
             "reproduce": f"python -m swarmkit_tpu.sim --seed {r.seed} "
                          f"--scenario {r.scenario}"}
            for r in bad],
        "ok": not bad and not missing,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="scripts/chaos_sweep.py")
    p.add_argument("--fuzz", type=int, metavar="N", default=5,
                   help="seeds per scenario (default 5)")
    p.add_argument("--start-seed", type=int, default=0)
    p.add_argument("--suite", choices=sorted(SUITES), default=None,
                   help="named scenario subset (default: 'default' = "
                        "failover + update + legacy-rcp)")
    p.add_argument("--scenario", action="append", default=None,
                   choices=sorted(SCENARIOS),
                   help="sweep exactly these scenarios (repeatable; "
                        "overrides --suite)")
    p.add_argument("--fast", action="store_true",
                   help="CI subset: 3 seeds x rolling-upgrade-chaos + "
                        "preemption-storm + follower-read-failover, "
                        "plus 1 tenant-storm, 1 steady-state-churn, "
                        "1 gang-deadlock, 1 pipeline-chaos and "
                        "1 million-swarm seed "
                        "(overrides --fuzz/--suite/--scenario)")
    p.add_argument("--no-coverage-gate", action="store_true",
                   help="report the coverage matrix but never fail on "
                        "an empty cell (for ad-hoc subsets)")
    p.add_argument("--list", action="store_true",
                   help="list suites + scenarios and exit")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-run progress lines")
    args = p.parse_args(argv)

    if args.list:
        for suite in sorted(SUITES):
            print(f"[{suite}]")
            for name in SUITES[suite]:
                doc = (SCENARIOS[name].__doc__ or "").strip()
                print(f"  {name:34s} {doc.split(chr(10))[0]}")
        return 0

    extra_runs: tuple = ()    # (scenario, n_seeds) beyond the main sweep
    if args.fast:
        scenarios: tuple = ("rolling-upgrade-chaos", "preemption-storm",
                            "follower-read-failover")
        n_seeds = 3
        extra_runs = (("tenant-storm", 1), ("steady-state-churn", 1),
                      ("gang-deadlock", 1), ("pipeline-chaos", 1),
                      ("million-swarm", 1))
    else:
        if args.scenario:
            scenarios = tuple(args.scenario)
        else:
            scenarios = SUITES[args.suite or "default"]
        n_seeds = args.fuzz

    def progress(r):
        if args.quiet:
            return
        mark = "ok" if r.ok else "FAIL"
        ctl = r.stats.get("control", {})
        print(f"{r.scenario:34s} seed {r.seed:5d} {mark} "
              f"trace={r.trace_hash[:12]} "
              f"attaches={ctl.get('attaches', 0)} "
              f"rollouts={ctl.get('rollouts', 0)}", file=sys.stderr)

    reports = sweep(scenarios, n_seeds, start_seed=args.start_seed,
                    progress=progress)
    for name, n in extra_runs:
        reports.extend(sweep((name,), n, start_seed=args.start_seed,
                             progress=progress))
    out = verdict(reports, scenarios + tuple(n for n, _ in extra_runs),
                  n_seeds, args.start_seed,
                  check_coverage=not args.no_coverage_gate)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
