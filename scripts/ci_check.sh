#!/usr/bin/env bash
# CI gate: static analysis first (cheap, catches convention drift with
# exact file:line messages), then the tier-1 test suite from ROADMAP.md.
# Exit nonzero on new swarmlint findings, stale/unjustified baseline
# entries, or any tier-1 failure.
set -o pipefail

cd "$(dirname "$0")/.."

echo "== native hotpath freshness (hash check + rebuild) =="
# the committed .so must match the committed hotpath.c: rebuild when the
# source hash stamp disagrees, and FAIL if it still disagrees afterwards
# (a stale .so silently serving old semantics is a correctness bug, not
# a perf nit — the commit plane's fallback counter would hide it)
SRC_SHA=$(sha256sum swarmkit_tpu/native/hotpath.c | cut -d' ' -f1)
STAMP_FILE=swarmkit_tpu/native/_hotpath.src.sha256
if [ "$(cat "$STAMP_FILE" 2>/dev/null | tr -d '[:space:]')" != "$SRC_SHA" ]; then
    echo "stale or missing native stamp; rebuilding _hotpath"
    (cd swarmkit_tpu/native && python build.py) >/dev/null 2>&1
fi
if [ "$(cat "$STAMP_FILE" 2>/dev/null | tr -d '[:space:]')" != "$SRC_SHA" ]; then
    echo "FAIL: _hotpath .so is stale vs hotpath.c and rebuild did not fix it"
    exit 1
fi

echo
echo "== swarmlint (scripts/swarmlint.py) =="
python scripts/swarmlint.py || exit 1

echo
echo "== chaos sweep, fast subset (scripts/chaos_sweep.py --fast) =="
# 3 seeds x (rolling-upgrade-chaos + preemption-storm): real rolling
# updates (pause / rollback / failover handoff) and priority preemption
# under partition+churn, invariants + coverage gate.  The 20-seed
# default-suite sweep and long-soak run in the slow tier
# (tests/test_update_chaos.py / test_preemption.py -m slow).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/chaos_sweep.py --fast --quiet > /tmp/_chaos_fast.json \
    || { cat /tmp/_chaos_fast.json; exit 1; }

echo
echo "== obs critical path (fast bench + trace_report --critical-path) =="
# tiny end-to-end bench (headline + e2e only) so the artifact embeds a
# journey attribution, then the critical-path report must parse it:
# non-empty cohort, per-plane rows, fractions summing to ~100%.  A
# malformed or empty attribution fails CI — the observability plane
# regressed even if every test still passes.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    BENCH_NODES=64 BENCH_TASKS=4096 BENCH_TRIALS=1 \
    BENCH_SKIP_HOST=1 BENCH_SKIP_CONFIGS=1 BENCH_SKIP_OBS=1 \
    BENCH_E2E_REPLICAS=64 BENCH_HISTORY= \
    BENCH_TRACE_OUT=/tmp/_ci_bench_trace.json \
    BENCH_FLIGHTREC_OUT=/tmp/_ci_bench_flightrec.json \
    python bench.py > /tmp/_ci_bench.json 2>/tmp/_ci_bench.err \
    || { cat /tmp/_ci_bench.err; exit 1; }
python scripts/trace_report.py --critical-path /tmp/_ci_bench.json \
    || exit 1

echo
echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
