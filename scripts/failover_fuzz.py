#!/usr/bin/env python
"""Seed-sweep the leader-failover scenarios (raft-attached control
plane) and fail loudly on any invariant violation.

Thin wrapper kept for CLI compatibility: the sweep implementation moved
to scripts/chaos_sweep.py, which generalizes it to any scenario subset
and adds the fault-type x component coverage report.

    python scripts/failover_fuzz.py --fuzz 20
    python scripts/failover_fuzz.py --fuzz 20 --scenario leader-crash-mid-tick
    python scripts/failover_fuzz.py --list

Each (scenario, seed) runs the full raft-attached control plane —
scheduler, dispatcher, allocator, restart supervisor, replicated +
global orchestrators on per-member replicated stores — through its
fault timeline under every invariant checker (single-leader-per-term,
committed-entry ledger, FSM monotonicity, no-double-assign,
control-loops-only-on-leader, no-stale-epoch-commit, failover
re-placement).  Exit status is 0 only when every run held every
invariant; failures print the violations, the exact replay command, and
the flight-recorder post-mortem path + sha the runner dumped.

The tier-1 test (tests/test_failover.py) runs a small deterministic
sweep through this same entry point; the wide sweep is the `slow` tier.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.sim.scenario import (          # noqa: E402
    FAILOVER_SCENARIOS, SCENARIOS,
)
from chaos_sweep import sweep                    # noqa: E402,F401


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="scripts/failover_fuzz.py")
    p.add_argument("--fuzz", type=int, metavar="N", default=5,
                   help="seeds per scenario (default 5)")
    p.add_argument("--start-seed", type=int, default=0)
    p.add_argument("--scenario", action="append", default=None,
                   choices=sorted(FAILOVER_SCENARIOS),
                   help="restrict to one scenario (repeatable); "
                        "default: the whole failover suite")
    p.add_argument("--list", action="store_true",
                   help="list the failover scenarios and exit")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-run progress lines")
    args = p.parse_args(argv)

    if args.list:
        for name in FAILOVER_SCENARIOS:
            doc = (SCENARIOS[name].__doc__ or "").strip().split("\n")[0]
            print(f"{name:32s} {doc}")
        return 0

    scenarios = tuple(args.scenario) if args.scenario \
        else FAILOVER_SCENARIOS

    def progress(r):
        if args.quiet:
            return
        mark = "ok" if r.ok else "FAIL"
        ctl = r.stats.get("control", {})
        print(f"{r.scenario:32s} seed {r.seed:5d} {mark} "
              f"trace={r.trace_hash[:12]} "
              f"attaches={ctl.get('attaches', 0)}", file=sys.stderr)

    reports = sweep(scenarios, args.fuzz, start_seed=args.start_seed,
                    progress=progress, keep_trace=False)
    bad = [r for r in reports if not r.ok]
    print(json.dumps({
        "scenarios": list(scenarios),
        "seeds_per_scenario": args.fuzz,
        "start_seed": args.start_seed,
        "runs": len(reports),
        "failures": [
            {"scenario": r.scenario, "seed": r.seed,
             "violations": r.violations,
             "flightrec": r.flightrec_path,
             "flightrec_sha256": r.flightrec_sha256,
             "reproduce": f"python -m swarmkit_tpu.sim --seed {r.seed} "
                          f"--scenario {r.scenario}"}
            for r in bad],
        "ok": not bad,
    }, indent=2))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
