"""Mesh crossover curve for the fused planner: N ∈ {1,2,4,8} devices.

Measures the steady-state cost of ONE fused chunk (dispatch + compute +
D2H) at the production tick shape — the cfg6/cfg7 node bucket with a
4-group-slot chunk — on a 1-device program (``plan_fused_jit``) and on
``plan_fused_sharded`` meshes of 2/4/8 devices, each in a fresh
subprocess so XLA_FLAGS / device count / jit caches cannot leak between
points.  The carry round-trips device-resident exactly as the planner
drives it (``ShardedPlanFn.prepare_fused`` NamedShardings for meshes).

Output: one JSON artifact (default MULTICHIP_r07.json) with the
seconds-per-chunk / decisions-per-second curve, the winning N, and
per-point parity checks (every mesh must produce byte-identical
placements to the 1-device program — for the plain chunk AND for a
strategy-mixed chunk cycling spread/binpack/weighted/learned group
strategy ids).  Each point also records the device-ledger H2D bytes
moved during the timed window (~0 once the carry is resident) and the
host-route strategy-group counter delta (must stay 0: no sharded
strategy kernel may fall back to the numpy oracle).  ``bench.py``
embeds the artifact under ``mesh_crossover`` when the file is
present, which is how the curve reaches the bench ledger.

The whole --devices list is validated up front against every node
bucket (n >= 1, bucket divisible by n); infeasible points are recorded
under ``skipped`` with a reason instead of dying mid-sweep, and a
child that cannot raise enough devices reports a skip the same way.

Children default to JAX_PLATFORMS=cpu with forced host-platform
devices (slices of the same cores — safe on containers where the TPU
tunnel hangs); the artifact records the measured platform per point
and sets ``host_forced_devices`` from what the children actually saw,
so a curve measured on forced host devices can never masquerade as a
silicon curve.  Export ``JAX_PLATFORMS=tpu`` (or any non-cpu backend)
to map the true multi-chip curve — no force flag is injected then.
On forced host devices no silicon is added, and repeat sweeps on a
shared host swing per-point medians ±10-30% — within that noise the
measured curve is flat at the 16k/64k buckets (the ~120 per-scan-step
[L]-psums cost about what the smaller per-device working set saves
when XLA executes the shard programs across host cores).  At the
131072-node bucket the per-shard columns drop back into cache and the
mesh crosses over for real: N=4 beats N=1 on decisions/sec even with
zero added silicon — the break-even floor the cost model predicts for
devices sharing one memory system, and the regime the sharded
resident tier exists for.  The cost model lives in
docs/architecture.md ("Fused many-service planning & mesh sharding").

Usage:
    python scripts/mesh_crossover.py                 # full curve
    python scripts/mesh_crossover.py --nodes 65536 --repeats 5
    python scripts/mesh_crossover.py --child 4       # (internal)
"""

import argparse
import hashlib
import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(REPO, "MULTICHIP_r07.json")


def _child(n_devices: int, nb: int, groups: int, k: int,
           repeats: int) -> None:
    """One measurement point, in an isolated process."""
    sys.path.insert(0, REPO)
    import time

    import jax
    import numpy as np

    from swarmkit_tpu.obs import devicetelemetry as _devtel
    from swarmkit_tpu.ops import fusedbatch
    from swarmkit_tpu.ops.kernel import (
        FusedCarry, FusedGroups, FusedShared, FusedStrategy, fetch_plan,
        plan_fused_jit,
    )
    from swarmkit_tpu.ops.planner import _jit_cache_size
    from swarmkit_tpu.scheduler import strategy as strategy_mod
    from swarmkit_tpu.utils.metrics import registry

    def _host_routed_groups() -> int:
        return sum(v for key, v in registry.counters_snapshot(
            "swarm_strategy_groups").items() if 'route="host"' in key)

    devices = jax.devices()
    if len(devices) < n_devices:
        print(json.dumps({"skipped": f"need {n_devices} devices, "
                                     f"have {len(devices)}"}))
        return

    rng = np.random.RandomState(0)
    gb = fusedbatch.pow2_bucket(groups)
    sb = fusedbatch.pow2_bucket(groups)   # one service slot per group
    shared = FusedShared(
        valid=np.ones(nb, bool), ready=np.ones(nb, bool),
        os_hash=np.zeros((2, nb), np.int32),
        arch_hash=np.zeros((2, nb), np.int32),
        svc0=rng.randint(0, 4, (sb, nb)).astype(np.int32))
    g = FusedGroups(
        k=np.array([k] * groups + [0] * (gb - groups), np.int32),
        slot=np.arange(gb, dtype=np.int32) % sb,
        maxrep=np.zeros(gb, np.int32),
        cpu_d=np.full(gb, 10 ** 8, np.int64),
        mem_d=np.full(gb, 64 << 20, np.int64),
        con_hash=np.zeros((gb, 1, 2, nb), np.int32),
        con_op=np.full((gb, 1), 2, np.int32),
        con_exp=np.zeros((gb, 1, 2), np.int32),
        plat=np.full((gb, 1, 4), -1, np.int32),
        failures=np.zeros((gb, nb), np.int32),
        leaf=np.zeros((gb, nb), np.int32),
        extra_mask=np.ones((gb, nb), bool))
    carry = FusedCarry(
        total=rng.randint(0, 8, nb).astype(np.int32),
        cpu=np.full(nb, 64 * 10 ** 9, np.int64),
        mem=np.full(nb, 256 << 30, np.int64),
        svc_acc=np.zeros((sb, nb), np.int32))

    # strategy-mixed chunk: group strategy ids cycle spread / binpack /
    # weighted / learned with fixed weighted terms and zero learned
    # params — deterministic, so its placements digest must agree at
    # every N (the ShardedPlanFn.fused route the planner takes)
    f_dim = len(strategy_mod.MLP_FEATURES)
    strat = FusedStrategy(
        sid=(np.arange(gb, dtype=np.int32) % 4),
        weights=np.tile(np.array([3, 1, 0, 0], np.int32), (gb, 1)),
        w1=np.zeros((f_dim, 1), np.int32), b1=np.zeros(1, np.int32),
        w2=np.zeros(1, np.int32), b2=np.zeros((), np.int32))

    with fusedbatch.x64():
        if n_devices == 1:
            import jax.numpy as jnp
            # the device ledger accounts this point's staging the same
            # way the planner's _prepare_fused cold path does
            _devtel.note_h2d("cold_build", _devtel.tree_nbytes(
                (tuple(shared), tuple(carry))))
            sh = FusedShared(*(jnp.asarray(a) for a in shared))
            ca = FusedCarry(*(jnp.asarray(a) for a in carry))
            probe = plan_fused_jit

            def run(ca, strat=None):
                xs, fcs, spills, ca = plan_fused_jit(sh, g, ca, 1,
                                                     strat)
                return fetch_plan((xs, fcs, spills)), ca
        else:
            from swarmkit_tpu.parallel.sharded import (
                ShardedPlanFn, make_mesh, plan_fused_sharded,
            )
            fn = ShardedPlanFn(make_mesh(devices[:n_devices]))
            # ShardedPlanFn._shard accounts the mesh_reshard H2D itself
            sh, ca = fn.prepare_fused(shared, carry)
            probe = plan_fused_sharded

            def run(ca, strat=None):
                xs, fcs, spills, ca = fn.fused(sh, g, ca, 1, strat)
                return fetch_plan((xs, fcs, spills)), ca

        (x0, _, _), _ = run(ca)            # compile + parity sample
        warm_compiles = _jit_cache_size(probe) or 0
        tt0 = _devtel.transfer_totals()
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            _, _ = run(ca)                 # fresh carry each repeat
            times.append(time.perf_counter() - t0)
        tt1 = _devtel.transfer_totals()
        timed_compiles = (_jit_cache_size(probe) or 0) - warm_compiles

        # untimed strategy-mixed dispatch: digest parity across N plus
        # proof no strategy group fell back to the numpy host oracle
        host_before = _host_routed_groups()
        (xs_s, _, _), _ = run(ca, strat)
        strat_fallbacks = _host_routed_groups() - host_before

    def _digest(x):
        return hashlib.sha256(np.ascontiguousarray(
            np.asarray(x).astype(np.int64)).tobytes()).hexdigest()

    med = statistics.median(times)
    print(json.dumps({
        "n_devices": n_devices,
        "chunk_seconds": round(med, 6),
        "chunk_seconds_min": round(min(times), 6),
        "decisions_per_sec": round(groups * k / med),
        "placements_digest": _digest(x0),
        "strategy_placements_digest": _digest(xs_s),
        "strategy_host_fallbacks": strat_fallbacks,
        "placed": int(np.asarray(x0).sum()),
        # per-point device-ledger evidence: bytes moved during the
        # timed repeats (steady-state D2H; H2D must be ~0 — the
        # carry stays device-resident) and the jit signatures this
        # point compiled, with timed-window growth pinned at 0
        "transfer_bytes": {d: tt1[d] - tt0.get(d, 0) for d in tt1},
        "resident_h2d_bytes_timed": tt1["h2d"] - tt0.get("h2d", 0),
        "compiles": warm_compiles,
        "timed_window_compiles": timed_compiles,
        "platform": devices[0].platform,
    }))


def _validate_devices(devices, nodes_list):
    """Whole-sweep feasibility check BEFORE any child runs: every
    requested N must be >= 1 and divide every node bucket (fused
    shards are unpadded so idx tie-keys match the 1-device program).
    Infeasible Ns land in the returned ``skipped`` map with a reason
    and the sweep proceeds over the rest — never dies mid-sweep."""
    valid, skipped = [], {}
    for n in devices:
        if n < 1:
            skipped[str(n)] = "n_devices must be >= 1"
        elif any(nb % n for nb in nodes_list):
            bad = [nb for nb in nodes_list if nb % n]
            skipped[str(n)] = (f"node buckets {bad} not divisible "
                               f"by {n}")
        else:
            valid.append(n)
    return valid, skipped


def _measure_shape(nodes, groups, k, repeats, devices, skipped):
    points = {n: {"skipped": reason} for n, reason in skipped.items()}
    for n in devices:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # force host devices only on the cpu backend — a real
        # accelerator backend supplies its own device inventory
        flags = env.get("XLA_FLAGS", "")
        if (env["JAX_PLATFORMS"] == "cpu"
                and "xla_force_host_platform_device_count" not in flags):
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(8, n)}").strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--child", str(n), "--nodes", str(nodes),
             "--groups", str(groups), "--k", str(k),
             "--repeats", str(repeats)],
            cwd=REPO, env=env, capture_output=True, text=True)
        if proc.returncode != 0:
            points[str(n)] = {"skipped": "child process failed: "
                              + proc.stderr[-500:]}
            continue
        points[str(n)] = json.loads(proc.stdout.strip().splitlines()[-1])
        print(f"nb={nodes} N={n}: {points[str(n)]}", file=sys.stderr)

    ok = {n: pt for n, pt in points.items() if "chunk_seconds" in pt}
    digests = {pt["placements_digest"] for pt in ok.values()}
    strat_digests = {pt["strategy_placements_digest"]
                     for pt in ok.values()}
    winner = min(ok, key=lambda n: ok[n]["chunk_seconds"]) if ok else None
    base = ok.get("1", {}).get("chunk_seconds")
    return {
        "shape": {"nodes": nodes, "groups_per_chunk": groups,
                  "tasks_per_group": k},
        "curve": {n: pt.get("chunk_seconds") for n, pt in points.items()},
        "decisions_per_sec": {n: pt.get("decisions_per_sec")
                              for n, pt in points.items()},
        "overhead_x": {n: round(pt["chunk_seconds"] / base, 3)
                       for n, pt in ok.items()} if base else {},
        "placements_equal_across_mesh": len(digests) <= 1,
        "strategy_placements_equal_across_mesh": len(strat_digests) <= 1,
        "strategy_host_fallbacks": sum(
            pt.get("strategy_host_fallbacks", 0) for pt in ok.values()),
        "max_timed_h2d_bytes": max(
            (pt.get("resident_h2d_bytes_timed", 0)
             for pt in ok.values()), default=0),
        "winner_devices": int(winner) if winner else None,
        "points": points,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/mesh_crossover.py")
    p.add_argument("--nodes", type=int, nargs="*",
                   default=[16384, 65536, 131072],
                   help="node buckets to sweep (default: 16384 = the "
                        "cfg6/cfg7 10k-node shape, 65536 = the "
                        "50k-node target shape, 131072 = the 100k+ "
                        "regime where per-shard working sets drop "
                        "back into cache and the mesh crosses over)")
    p.add_argument("--groups", type=int, default=4,
                   help="groups per fused chunk (default 4)")
    p.add_argument("--k", type=int, default=50_000,
                   help="tasks per group (default 50000)")
    p.add_argument("--repeats", type=int, default=9)
    p.add_argument("--devices", type=int, nargs="*",
                   default=[1, 2, 4, 8])
    p.add_argument("--out", default=DEFAULT_OUT)
    p.add_argument("--child", type=int, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.child is not None:
        _child(args.child, args.nodes[0], args.groups, args.k,
               args.repeats)
        return 0

    valid_devices, skipped = _validate_devices(args.devices, args.nodes)
    for n, reason in skipped.items():
        print(f"skipping N={n}: {reason}", file=sys.stderr)
    shapes = {str(nb): _measure_shape(nb, args.groups, args.k,
                                      args.repeats, valid_devices,
                                      skipped)
              for nb in args.nodes}
    all_parity = all(s["placements_equal_across_mesh"]
                     and s["strategy_placements_equal_across_mesh"]
                     for s in shapes.values())
    platforms = sorted({pt["platform"]
                        for s in shapes.values()
                        for pt in s["points"].values()
                        if "platform" in pt})
    artifact = {
        "metric": "fused planner chunk seconds vs mesh size N",
        "devices_swept": args.devices,
        "skipped": skipped,
        "shapes": shapes,
        "winner_by_shape": {nb: s["winner_devices"]
                            for nb, s in shapes.items()},
        "placements_equal_across_mesh": all_parity,
        "strategy_host_fallbacks": sum(
            s["strategy_host_fallbacks"] for s in shapes.values()),
        # honest provenance: True only when every point actually ran
        # on forced host-cpu devices — a silicon curve says so
        "platforms": platforms,
        "host_forced_devices": platforms == ["cpu"],
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(artifact))
    return 0 if all_parity and shapes and valid_devices else 1


if __name__ == "__main__":
    sys.exit(main())
