"""Profile the block-mode scheduler tick (apply + commit phases).

Usage: JAX_PLATFORMS=cpu python scripts/profile_tick.py [n_nodes n_tasks]
Prints a phase breakdown and a cProfile top-30 of the tick.
"""
import cProfile
import gc
import pstats
import sys
import time

sys.path.insert(0, ".")

from bench import build_cluster, one_tick  # noqa: E402
from swarmkit_tpu.ops import TPUPlanner  # noqa: E402


def main():
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    n_tasks = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000

    # warm compile cache
    store, *_ = build_cluster(n_nodes, 64)
    wp = TPUPlanner()
    wp.enable_small_group_routing = False
    one_tick(store, wp)
    TPUPlanner()._measure_launch_overhead()

    t0 = time.perf_counter()
    store, svc, nodes, tasks = build_cluster(n_nodes, n_tasks)
    print(f"build: {time.perf_counter() - t0:.2f}s")
    planner = TPUPlanner()

    prof = cProfile.Profile()
    prof.enable()
    sched, n_dec, dt = one_tick(store, planner)
    prof.disable()
    print(f"tick: {dt:.3f}s  decisions: {n_dec}  "
          f"plan: {planner.stats['plan_seconds']:.3f}s  "
          f"commit: {sched.stats['commit_seconds']:.3f}s")
    st = pstats.Stats(prof)
    st.sort_stats("cumulative").print_stats(30)


if __name__ == "__main__":
    main()
