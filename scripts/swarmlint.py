#!/usr/bin/env python3
"""swarmlint CLI — run the project's AST invariant linter.

Usage:
    python scripts/swarmlint.py                     # full tree, all rules
    python scripts/swarmlint.py swarmkit_tpu/state  # subtree
    python scripts/swarmlint.py --rules determinism-seam,layering
    python scripts/swarmlint.py --format json
    python scripts/swarmlint.py --list-rules
    python scripts/swarmlint.py --write-baseline    # regenerate grandfather
                                                    # list (entries keep their
                                                    # justifications)

Exit status: 0 clean (baselined findings are fine), 1 on new findings,
stale/unjustified baseline entries, or parse errors.
"""

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from swarmkit_tpu.analysis import (  # noqa: E402
    DEFAULT_BASELINE, DEFAULT_ROOTS, checker_names, lint_tree,
    make_checkers, write_baseline)
from swarmkit_tpu.analysis.reporters import human_report, json_report  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="swarmlint",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_ROOTS})")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path, repo-relative "
                         f"(default: {DEFAULT_BASELINE}); 'none' disables")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings")
    ap.add_argument("--verbose", action="store_true",
                    help="also print grandfathered findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for c in make_checkers():
            print(f"{c.name:24s} {c.description}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    baseline = None if args.baseline == "none" else args.baseline
    roots = args.paths or DEFAULT_ROOTS
    result = lint_tree(REPO_ROOT, roots=roots, rules=rules,
                       baseline_path=baseline)

    if args.write_baseline:
        if baseline is None:
            ap.error("--write-baseline conflicts with --baseline none "
                     "(there is no file to write)")
        n = write_baseline(REPO_ROOT, result, baseline)
        print(f"wrote {n} entries to {baseline} "
              "(fill in 'justification' for each)")
        return 0

    if args.format == "json":
        print(json_report(result))
    else:
        print(human_report(result, verbose=args.verbose))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
