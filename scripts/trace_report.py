"""Summarize or diff Chrome trace-event JSON as per-phase tables.

Usage:
    python scripts/trace_report.py bench_trace.json
    python scripts/trace_report.py bench_trace.json --validate
    python scripts/trace_report.py sim_trace.json --json
    python scripts/trace_report.py --diff A.json B.json
    python scripts/trace_report.py --critical-path BENCH_ART.json
    python scripts/trace_report.py --device BENCH_ART.json

Works on any trace the obs tracer emits: ``bench.py``'s BENCH_TRACE_OUT,
``python -m swarmkit_tpu.sim --trace-json``, or a ``/debug/trace``
download.  When the trace carries ``bench.config`` marker spans, a table
is printed per config; otherwise one table covers the whole trace.
``--validate`` schema-checks the document and exits non-zero on problems
(the tier-1 smoke test runs exactly this check in-process).
``--diff A B`` prints a side-by-side phase table with per-phase total_s
deltas (A = baseline, B = candidate), matched per config window where
both traces carry the same ``bench.config`` markers — the same
``obs/report.py`` aggregation the bench artifact embeds.
``--critical-path ART`` takes a bench ARTIFACT (not a trace): it joins
the task-journey attribution of time-to-running p99 with the per-plane
saturation windows and prints one row per plane — which plane owns the
slow tail, and whether that plane's occupancy/backlog corroborates it.
Exits 1 when the attribution is missing, empty, or does not account
for ~100% of the tail (the CI wiring keys on that).
``--device ART`` also takes a bench artifact: it renders the device
telemetry ledger (kernel rows per compile bucket joined with the device
plane's occupancy window, per-reason transfer bytes, the compile-cache
ledger, memory watermarks, donation balance).  Exits 1 when the
artifact predates the ledger.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.obs.report import (  # noqa: E402
    config_windows, device_table, diff_phase_tables, format_device_table,
    format_diff, format_table, phase_table, validate_chrome_trace,
    x_events,
)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _tables(doc):
    windows = config_windows(doc)
    if not windows:
        windows = [("all", None)]
    return {name: phase_table(doc, window=w) for name, w in windows}


def _run_diff(path_a: str, path_b: str, as_json: bool) -> int:
    doc_a, doc_b = _load(path_a), _load(path_b)
    ta, tb = _tables(doc_a), _tables(doc_b)
    only_a = sorted(set(ta) - set(tb))
    only_b = sorted(set(tb) - set(ta))
    names = [n for n in ta if n in tb]
    matched = {}
    if names:
        matched = {n: (ta[n], tb[n]) for n in names}
    else:
        # no shared config windows: diff whole-trace tables (and still
        # report the disjoint config sets below — that mismatch is the
        # headline when it happens)
        matched = {"all": (phase_table(doc_a), phase_table(doc_b))}
        names = ["all"]
    diffs = {name: diff_phase_tables(a, b)
             for name, (a, b) in matched.items()}
    if as_json:
        print(json.dumps(diffs, indent=2, sort_keys=True))
        return 0
    print(f"A = {path_a}\nB = {path_b}\n")
    for name in names:
        print(f"=== {name} ===")
        print(format_diff(diffs[name]))
        print()
    if only_a:
        print(f"configs only in A: {', '.join(only_a)}")
    if only_b:
        print(f"configs only in B: {', '.join(only_b)}")
    return 0


def _load_artifact(path):
    """A saved bench artifact may carry log noise before the JSON line;
    take the last line that parses (bench_compare discipline)."""
    with open(path) as f:
        text = f.read().strip()
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise SystemExit(f"{path}: no JSON document found")


def _run_critical_path(path: str, as_json: bool) -> int:
    """Join the artifact's journey attribution with its plane windows:
    one row per plane of the time-to-running p99 tail.  Non-zero exit
    on malformed or empty attribution — ci_check.sh runs this against
    the fast bench config as the observability smoke gate."""
    art = _load_artifact(path)
    attr = art.get("journey_attribution")
    planes = art.get("planes") or {}
    problems = []
    e2e = art.get("e2e_time_to_running")
    if not isinstance(attr, dict) and isinstance(e2e, dict) \
            and str(e2e.get("error", "")).startswith("skipped:"):
        # the e2e config self-skipped for an environmental reason (no
        # `cryptography` for the manager's CA bootstrap): there is no
        # attribution to judge, which is not an observability failure
        msg = (f"critical-path: e2e config was skipped "
               f"({e2e['error']}); nothing to attribute")
        if as_json:
            print(json.dumps({"source": path, "skipped": e2e["error"],
                              "attribution": None, "problems": []},
                             indent=2, sort_keys=True))
        else:
            print(msg, file=sys.stderr)
        return 0
    if not isinstance(attr, dict):
        problems.append("artifact carries no journey_attribution "
                        "(bench ran without the e2e config, or "
                        "journeys were disabled)")
        attr = {}
    by_plane = attr.get("planes") or {}
    if not problems and not attr.get("cohort"):
        problems.append("attribution cohort is empty — no complete "
                        "created->running journeys were sampled")
    if not problems and not by_plane:
        problems.append("attribution has a cohort but no per-plane "
                        "rows")
    frac_sum = sum(float(r.get("frac") or 0.0)
                   for r in by_plane.values())
    if not problems and abs(frac_sum - 1.0) > 0.02:
        problems.append(f"per-plane fractions sum to {frac_sum:.4f}, "
                        "not ~1.0 — the edges no longer partition the "
                        "journey interval")
    doc = {"source": path, "attribution": attr,
           "plane_windows": planes, "frac_sum": round(frac_sum, 6),
           "problems": problems}
    if as_json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if problems else 0
    if problems:
        for pr in problems:
            print(f"critical-path: {pr}", file=sys.stderr)
        return 1
    print(f"time-to-running p{int(attr['p'] * 100)} critical path "
          f"({attr['cohort']} tail task(s) of {attr['tasks']} "
          f"complete, {attr['total_s']:.4f}s attributed)")
    hdr = (f"{'plane':<12} {'seconds':>10} {'frac':>7} "
           f"{'occupancy':>10} {'depth':>7} {'oldest_s':>9} "
           f"{'drops':>6}")
    print(hdr)
    order = sorted(by_plane, key=lambda pl: -by_plane[pl]["seconds"])
    for pl in order:
        row = by_plane[pl]
        w = planes.get(pl) or {}
        print(f"{pl:<12} {row['seconds']:>10.4f} "
              f"{row['frac'] * 100:>6.1f}% "
              f"{w.get('occupancy', 0.0):>10.4f} "
              f"{w.get('queue_depth', 0.0):>7.0f} "
              f"{w.get('oldest_age_s', 0.0):>9.3f} "
              f"{w.get('drops', 0):>6d}")
    spectators = sorted(set(planes) - set(by_plane))
    if spectators:
        print(f"planes with no tail share: {', '.join(spectators)}")
    return 0


def _run_device(path: str, as_json: bool) -> int:
    """Render a bench artifact's device-telemetry ledger: kernel rows
    joined with the device plane's occupancy window, per-reason
    transfer bytes, compile-cache ledger, watermarks, donation
    balance.  Exits 1 when the artifact predates the ledger."""
    art = _load_artifact(path)
    table = device_table(art)
    if table is None:
        print(f"{path}: artifact carries no device_telemetry (bench "
              "predates the device ledger, or telemetry was disabled)",
              file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(table, indent=2, sort_keys=True))
        return 0
    print(f"device telemetry ({path})")
    print(format_device_table(table))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/trace_report.py")
    p.add_argument("trace", nargs="+",
                   help="Chrome trace-event JSON file(s); two with "
                        "--diff; a bench artifact with --critical-path")
    p.add_argument("--validate", action="store_true",
                   help="schema-check only; exit 1 on problems")
    p.add_argument("--json", action="store_true",
                   help="emit the phase table(s) as JSON")
    p.add_argument("--diff", action="store_true",
                   help="side-by-side phase diff of two traces (A B)")
    p.add_argument("--critical-path", action="store_true",
                   help="per-plane attribution of time-to-running p99 "
                        "from a bench ARTIFACT (exit 1 when empty or "
                        "malformed)")
    p.add_argument("--device", action="store_true",
                   help="device-telemetry ledger from a bench ARTIFACT: "
                        "kernel rows per compile bucket + device-plane "
                        "window, per-reason transfer bytes, "
                        "compile-cache ledger (exit 1 when absent)")
    args = p.parse_args(argv)

    if args.device:
        if len(args.trace) != 1:
            p.error("--device takes exactly one bench artifact")
        return _run_device(args.trace[0], args.json)
    if args.critical_path:
        if len(args.trace) != 1:
            p.error("--critical-path takes exactly one bench artifact")
        return _run_critical_path(args.trace[0], args.json)
    if args.diff:
        if len(args.trace) != 2:
            p.error("--diff takes exactly two trace files")
        return _run_diff(args.trace[0], args.trace[1], args.json)
    if len(args.trace) != 1:
        p.error("pass one trace file (or two with --diff)")

    doc = _load(args.trace[0])

    problems = validate_chrome_trace(doc)
    if args.validate:
        for pr in problems:
            print(pr, file=sys.stderr)
        print(f"{args.trace[0]}: "
              f"{'INVALID' if problems else 'ok'} "
              f"({len(x_events(doc))} spans)")
        return 1 if problems else 0
    if problems:
        print(f"warning: {len(problems)} schema problems "
              f"(run --validate)", file=sys.stderr)

    tables = _tables(doc)
    if args.json:
        print(json.dumps(tables, indent=2, sort_keys=True))
        return 0
    for name, table in tables.items():
        print(f"=== {name} ===")
        print(format_table(table))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
