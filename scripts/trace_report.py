"""Summarize a Chrome trace-event JSON into a per-phase table.

Usage:
    python scripts/trace_report.py bench_trace.json
    python scripts/trace_report.py bench_trace.json --validate
    python scripts/trace_report.py sim_trace.json --json

Works on any trace the obs tracer emits: ``bench.py``'s BENCH_TRACE_OUT,
``python -m swarmkit_tpu.sim --trace-json``, or a ``/debug/trace``
download.  When the trace carries ``bench.config`` marker spans, a table
is printed per config; otherwise one table covers the whole trace.
``--validate`` schema-checks the document and exits non-zero on problems
(the tier-1 smoke test runs exactly this check in-process).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.obs.report import (  # noqa: E402
    config_windows, format_table, phase_table, validate_chrome_trace,
    x_events,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/trace_report.py")
    p.add_argument("trace", help="Chrome trace-event JSON file")
    p.add_argument("--validate", action="store_true",
                   help="schema-check only; exit 1 on problems")
    p.add_argument("--json", action="store_true",
                   help="emit the phase table(s) as JSON")
    args = p.parse_args(argv)

    with open(args.trace) as f:
        doc = json.load(f)

    problems = validate_chrome_trace(doc)
    if args.validate:
        for pr in problems:
            print(pr, file=sys.stderr)
        print(f"{args.trace}: "
              f"{'INVALID' if problems else 'ok'} "
              f"({len(x_events(doc))} spans)")
        return 1 if problems else 0
    if problems:
        print(f"warning: {len(problems)} schema problems "
              f"(run --validate)", file=sys.stderr)

    windows = config_windows(doc)
    if not windows:
        windows = [("all", None)]
    tables = {name: phase_table(doc, window=w) for name, w in windows}
    if args.json:
        print(json.dumps(tables, indent=2, sort_keys=True))
        return 0
    for name, table in tables.items():
        print(f"=== {name} ===")
        print(format_table(table))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
