"""Summarize or diff Chrome trace-event JSON as per-phase tables.

Usage:
    python scripts/trace_report.py bench_trace.json
    python scripts/trace_report.py bench_trace.json --validate
    python scripts/trace_report.py sim_trace.json --json
    python scripts/trace_report.py --diff A.json B.json

Works on any trace the obs tracer emits: ``bench.py``'s BENCH_TRACE_OUT,
``python -m swarmkit_tpu.sim --trace-json``, or a ``/debug/trace``
download.  When the trace carries ``bench.config`` marker spans, a table
is printed per config; otherwise one table covers the whole trace.
``--validate`` schema-checks the document and exits non-zero on problems
(the tier-1 smoke test runs exactly this check in-process).
``--diff A B`` prints a side-by-side phase table with per-phase total_s
deltas (A = baseline, B = candidate), matched per config window where
both traces carry the same ``bench.config`` markers — the same
``obs/report.py`` aggregation the bench artifact embeds.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.obs.report import (  # noqa: E402
    config_windows, diff_phase_tables, format_diff, format_table,
    phase_table, validate_chrome_trace, x_events,
)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _tables(doc):
    windows = config_windows(doc)
    if not windows:
        windows = [("all", None)]
    return {name: phase_table(doc, window=w) for name, w in windows}


def _run_diff(path_a: str, path_b: str, as_json: bool) -> int:
    doc_a, doc_b = _load(path_a), _load(path_b)
    ta, tb = _tables(doc_a), _tables(doc_b)
    only_a = sorted(set(ta) - set(tb))
    only_b = sorted(set(tb) - set(ta))
    names = [n for n in ta if n in tb]
    matched = {}
    if names:
        matched = {n: (ta[n], tb[n]) for n in names}
    else:
        # no shared config windows: diff whole-trace tables (and still
        # report the disjoint config sets below — that mismatch is the
        # headline when it happens)
        matched = {"all": (phase_table(doc_a), phase_table(doc_b))}
        names = ["all"]
    diffs = {name: diff_phase_tables(a, b)
             for name, (a, b) in matched.items()}
    if as_json:
        print(json.dumps(diffs, indent=2, sort_keys=True))
        return 0
    print(f"A = {path_a}\nB = {path_b}\n")
    for name in names:
        print(f"=== {name} ===")
        print(format_diff(diffs[name]))
        print()
    if only_a:
        print(f"configs only in A: {', '.join(only_a)}")
    if only_b:
        print(f"configs only in B: {', '.join(only_b)}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python scripts/trace_report.py")
    p.add_argument("trace", nargs="+",
                   help="Chrome trace-event JSON file(s); two with --diff")
    p.add_argument("--validate", action="store_true",
                   help="schema-check only; exit 1 on problems")
    p.add_argument("--json", action="store_true",
                   help="emit the phase table(s) as JSON")
    p.add_argument("--diff", action="store_true",
                   help="side-by-side phase diff of two traces (A B)")
    args = p.parse_args(argv)

    if args.diff:
        if len(args.trace) != 2:
            p.error("--diff takes exactly two trace files")
        return _run_diff(args.trace[0], args.trace[1], args.json)
    if len(args.trace) != 1:
        p.error("pass one trace file (or two with --diff)")

    doc = _load(args.trace[0])

    problems = validate_chrome_trace(doc)
    if args.validate:
        for pr in problems:
            print(pr, file=sys.stderr)
        print(f"{args.trace[0]}: "
              f"{'INVALID' if problems else 'ok'} "
              f"({len(x_events(doc))} spans)")
        return 1 if problems else 0
    if problems:
        print(f"warning: {len(problems)} schema problems "
              f"(run --validate)", file=sys.stderr)

    tables = _tables(doc)
    if args.json:
        print(json.dumps(tables, indent=2, sort_keys=True))
        return 0
    for name, table in tables.items():
        print(f"=== {name} ===")
        print(format_table(table))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
