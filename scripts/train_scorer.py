#!/usr/bin/env python
"""Offline trainer for the experimental learned placement scorer.

Produces the checked-in artifact ``swarmkit_tpu/scheduler/
learned_scorer.json`` consumed by ``scheduler/strategy.learned_params``
and the device kernel (``ops/kernel.plan_strategy`` strategy=learned).

The scorer is a tiny fixed-point MLP (6 features -> 8 hidden -> 1) whose
integer forward pass is EXACTLY the one both the host oracle and the
device kernel run (clip/shift formulas from scheduler/strategy.py) — the
trainer optimizes through that quantized forward, not a float proxy, so
what ships is what was fitted.

Training data: per-node feature rows sampled from seeded distributions
distilled from the ``sim/scenario.py`` steady-state-churn and
tenant-storm workloads (service-count geometrics under Poisson churn,
headroom profiles of the production-shaped arrival services, sparse
failure bursts).  The teacher is a robust load-balance score — spread
pressure plus saturating headroom terms plus a failure penalty — i.e.
the behavior the weighted strategy approximates linearly, with the
saturation nonlinearity the MLP's hidden layer can actually buy us.
Robust-scheduling framing per PAPERS.md 2302.05446 (GFlowNet-style
trajectory sampling is the stretch goal; this artifact is the
plumbing-complete distillation baseline).

Deterministic end to end: one seeded generator, no wall clock; re-running
with the same --seed reproduces the artifact byte for byte.

Usage:  python scripts/train_scorer.py [--seed 7] [--samples 20000]
                                       [--out path.json]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from swarmkit_tpu.scheduler.strategy import (  # noqa: E402
    FEAT_CLAMP, MLP_FEATURES, MLP_SHIFT, MLP_W_CLAMP, SCORE_CLAMP,
)

HIDDEN = 8


def sample_features(rng, n):
    """Feature rows shaped like the churn scenarios' node mirrors."""
    svc = np.minimum(rng.geometric(0.08, n) - 1, FEAT_CLAMP)
    total = np.minimum(svc + rng.geometric(0.02, n) - 1, FEAT_CLAMP)
    # failure bursts are sparse and clustered (preemption-storm shape)
    failures = np.where(rng.random(n) < 0.06,
                        rng.integers(1, 12, n), 0)
    # headroom: mixture of mostly-empty, mid-loaded and near-full nodes
    mode = rng.integers(0, 3, n)
    hr_cpu = np.select(
        [mode == 0, mode == 1],
        [rng.integers(700, FEAT_CLAMP + 1, n),
         rng.integers(100, 700, n)],
        rng.integers(0, 100, n))
    hr_mem = np.clip(hr_cpu + rng.integers(-80, 81, n), 0, FEAT_CLAMP)
    ready = np.where(rng.random(n) < 0.97, FEAT_CLAMP, 0)
    f = np.stack([svc, total, failures, hr_cpu, hr_mem, ready],
                 axis=-1).astype(np.int32)
    return np.clip(f, 0, FEAT_CLAMP)


def teacher_score(f):
    """Robust load-balance target, lower = preferred: spread pressure,
    saturating headroom preference, hard failure/not-ready penalties."""
    svc, total, failures, hr_cpu, hr_mem, ready = (
        f[:, i].astype(np.float64) for i in range(6))
    sat = lambda h: np.sqrt(np.maximum(h, 0.0) / FEAT_CLAMP)  # noqa: E731
    score = (40.0 * svc + 4.0 * total
             + 900.0 * (1.0 - sat(hr_cpu)) + 450.0 * (1.0 - sat(hr_mem))
             + 600.0 * np.minimum(failures, 8.0)
             + 4000.0 * (ready < FEAT_CLAMP / 2))
    return score


def int_forward_hidden(f, w1, b1):
    h = np.right_shift(f.astype(np.int64) @ w1 + b1, MLP_SHIFT)
    return np.clip(h, 0, FEAT_CLAMP)


def int_forward(f, w1, b1, w2, b2):
    h = int_forward_hidden(f, w1, b1)
    out = np.right_shift(h @ w2 + b2, MLP_SHIFT)
    return np.clip(out, 0, SCORE_CLAMP)


def fit(seed, n_samples):
    rng = np.random.default_rng(seed)
    f = sample_features(rng, n_samples)
    y = teacher_score(f)

    best = None
    # random-feature fit through the QUANTIZED forward: draw int8 first
    # layers, solve the second layer by least squares on the integer
    # hidden activations, quantize, keep the best candidate by Spearman
    # rank correlation (ordering is all a scorer is judged on)
    for draw in range(24):
        w1 = rng.integers(-MLP_W_CLAMP, MLP_W_CLAMP + 1,
                          (len(MLP_FEATURES), HIDDEN)).astype(np.int32)
        b1 = rng.integers(-(1 << 12), 1 << 12, HIDDEN).astype(np.int32)
        h = int_forward_hidden(f, w1, b1).astype(np.float64)
        # least squares h @ w2f ~= y * 2^SHIFT (the final shift undoes it)
        target = y * (1 << MLP_SHIFT)
        a = np.concatenate([h, np.ones((len(h), 1))], axis=1)
        sol, *_ = np.linalg.lstsq(a, target, rcond=None)
        scale = max(np.abs(sol[:-1]).max() / MLP_W_CLAMP, 1.0)
        w2 = np.clip(np.round(sol[:-1] / scale), -MLP_W_CLAMP,
                     MLP_W_CLAMP).astype(np.int32)
        b2 = np.int32(np.clip(round(sol[-1] / scale), -(1 << 20),
                              1 << 20))
        pred = int_forward(f, w1, b1, w2, b2).astype(np.float64)
        # Spearman via rank correlation
        ra = np.argsort(np.argsort(pred))
        rb = np.argsort(np.argsort(y))
        rho = float(np.corrcoef(ra, rb)[0, 1])
        if best is None or rho > best[0]:
            best = (rho, draw, w1, b1, w2, b2)
    return f, y, best


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--samples", type=int, default=20_000)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "swarmkit_tpu", "scheduler", "learned_scorer.json"))
    args = ap.parse_args(argv)

    f, y, (rho, draw, w1, b1, w2, b2) = fit(args.seed, args.samples)
    holdout = sample_features(np.random.default_rng(args.seed + 1), 4096)
    pred = int_forward(holdout, w1, b1, w2, b2).astype(np.float64)
    yh = teacher_score(holdout)
    ra = np.argsort(np.argsort(pred))
    rb = np.argsort(np.argsort(yh))
    rho_holdout = float(np.corrcoef(ra, rb)[0, 1])

    artifact = {
        "format": "swarm-learned-scorer-v1",
        "features": list(MLP_FEATURES),
        "hidden": HIDDEN,
        "shift": MLP_SHIFT,
        "w1": w1.tolist(),
        "b1": b1.tolist(),
        "w2": w2.tolist(),
        "b2": int(b2),
        "provenance": {
            "trainer": "scripts/train_scorer.py",
            "seed": args.seed,
            "samples": args.samples,
            "draw": draw,
            "teacher": "spread+saturating-headroom+failure penalty "
                       "(sim/scenario.py churn-shaped distributions)",
            "spearman_train": round(rho, 4),
            "spearman_holdout": round(rho_holdout, 4),
        },
    }
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {args.out}: spearman train={rho:.4f} "
          f"holdout={rho_holdout:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
