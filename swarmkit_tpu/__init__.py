"""swarmkit_tpu — a TPU-native cluster-orchestration framework.

Capabilities of moby/swarmkit, re-designed TPU-first: a host-side control
plane (replicated store, orchestrators, dispatcher, agents, CA) around a
JAX/XLA scheduling kernel that evaluates the per-task filter pipeline and
spread scorer as batched tasks×nodes array programs, sharded over a device
mesh for large clusters.
"""

__version__ = "0.1.0"
