from .agent import Agent
from .exec import Controller, Executor, do_task
from .worker import TaskManager, Worker

__all__ = ["Agent", "Controller", "Executor", "TaskManager", "Worker",
           "do_task"]
