from .agent import Agent
from .exec import Controller, Executor, do_task
from .procexec import ProcessController, ProcessExecutor
from .worker import TaskManager, Worker

__all__ = ["Agent", "Controller", "Executor", "ProcessController",
           "ProcessExecutor", "TaskManager", "Worker", "do_task"]
