"""Agent: the node-side runtime connecting a worker to the dispatcher.

Reference: agent/{agent.go,session.go,reporter.go}.

One session loop: register → heartbeat keepalive → assignments stream →
worker; status changes flow back through a batching reporter.  On any
session failure the agent backs off exponentially and re-registers — the
dispatcher sends a fresh COMPLETE set on reconnect (session.go:120,
agent.go:179).

The ``client`` is anything with the dispatcher's surface (register /
heartbeat / open_assignments / update_task_status); in-process that is the
Dispatcher object itself, over the network a gRPC client wrapper.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict, List, Optional, Tuple

from ..models.types import TaskStatus
from ..remotes import backoff_with_jitter
from ..state.watch import Closed
from .exec import Executor
from .worker import Worker

log = logging.getLogger("agent")


class Agent:
    def __init__(self, node_id: str, executor: Executor, client,
                 description=None, task_db_path=None,
                 rng: Optional[random.Random] = None):
        self.node_id = node_id
        self.executor = executor
        self.client = client
        self.description = description
        # reconnect-jitter rng: injectable so the simulator's reconnect
        # storms stay deterministic per seed (see remotes.backoff_with_jitter)
        self._rng = rng or random.Random()
        db = None
        if task_db_path:
            from .storage import TaskDB
            db = TaskDB(task_db_path)
        # node-side CSI: volumes arrive as assignment dependencies; they
        # stage/publish under a local dir and unpublish reports flow back
        # through the dispatcher (reference: agent/csi/volumes.go)
        import os as _os
        import tempfile as _tempfile
        vol_dir = (_os.path.join(_os.path.dirname(task_db_path), "csi")
                   if task_db_path else
                   _tempfile.mkdtemp(prefix="swarm-csi-"))
        from .csivol import NodeVolumesManager
        self.volumes = NodeVolumesManager(
            vol_dir, on_unpublished=self._report_volume_unpublished)
        self._unpublished_mu = threading.Lock()
        self._unpublished: list = []
        self.worker = Worker(executor, self._report, db=db,
                             volumes=self.volumes)
        self.session_id: Optional[str] = None
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # status reporter batching (reference: reporter.go)
        self._statuses_mu = threading.Lock()
        self._statuses: Dict[str, TaskStatus] = {}
        self._statuses_cond = threading.Condition(self._statuses_mu)
        self._reporter_thread: Optional[threading.Thread] = None
        self._log_thread: Optional[threading.Thread] = None
        self._log_offsets: Dict[str, int] = {}
        self.log_ship_interval = 0.5
        self.stats = {"sessions": 0, "reports": 0, "log_batches": 0}
        self._applied_key_clock = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="agent",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._statuses_cond:
            self._statuses_cond.notify_all()
        self.worker.close()
        self._done.wait(timeout=10)

    def _log_shipper(self) -> None:
        """Ship new task-log bytes to the manager's log broker
        (reference: agent/reporter + log publisher; executors that expose
        per-task ``read_logs`` — e.g. the process executor — feed it).
        Offsets are tracked per task so only deltas travel."""
        while not self._stop.wait(self.log_ship_interval):
            try:
                self._ship_logs_once()
            except Exception:
                # nothing here may kill the shipper thread: a transient
                # error just means this interval's batch waits
                log.exception("log shipping pass failed")

    def _ship_logs_once(self) -> None:
        publish = getattr(self.client, "publish_logs", None)
        controllers = getattr(self.executor, "controllers", None)
        if publish is None or not controllers:
            return
        snapshot = dict(controllers)   # racing the worker thread is fine;
        # a task missed this pass ships next interval
        batch = []
        for task_id, ctlr in snapshot.items():
            read = getattr(ctlr, "read_logs", None)
            if read is None:
                continue
            data = read()
            start = self._log_offsets.get(task_id, 0)
            if len(data) > start:
                batch.append({"task_id": task_id,
                              "node_id": self.node_id,
                              "stream": "stdout",
                              "data": data[start:]})
                self._log_offsets[task_id] = len(data)
        # prune offsets for tasks the executor no longer tracks, or a
        # long-lived agent grows one entry per historical task forever
        for task_id in list(self._log_offsets):
            if task_id not in snapshot:
                del self._log_offsets[task_id]
        if not batch:
            return
        try:
            publish(self.node_id, self.session_id or "", batch)
            self.stats["log_batches"] += 1
        except Exception:
            # transient transport trouble: offsets were advanced, so
            # roll them back for a retry next interval (at-least-once)
            for m in batch:
                self._log_offsets[m["task_id"]] -= len(m["data"])

    def run(self) -> None:
        attempt = 0
        try:
            self._log_thread = threading.Thread(
                target=self._log_shipper, name="agent-logs", daemon=True)
            self._log_thread.start()
            self._reporter_thread = threading.Thread(
                target=self._reporter_loop, name="agent-reporter",
                daemon=True)
            self._reporter_thread.start()
            # resume persisted tasks only once the reporter machinery is
            # fully constructed and running
            try:
                self.worker.init_from_db()
            except Exception:
                log.exception("resuming persisted tasks failed")
            while not self._stop.is_set():
                try:
                    self._session()
                    attempt = 0
                except Exception as e:
                    if self._stop.is_set():
                        return
                    # session failover: count the cause and make sure the
                    # re-register targets a DIFFERENT manager — an
                    # invalidated session or a closed assignment stream
                    # usually means THIS manager is mid-teardown, and
                    # hammering it just races the teardown
                    from ..remotes import SESSION_ERROR_CODES, \
                        count_reconnect
                    reason = (
                        "session_invalid"
                        if getattr(e, "code", "") in SESSION_ERROR_CODES
                        else "stream_closed"
                        if isinstance(e, ConnectionError)
                        else "transport"
                        if isinstance(e, (OSError, TimeoutError))
                        else "error")
                    count_reconnect(reason)
                    rotate = getattr(self.client,
                                     "note_session_failure", None)
                    if rotate is not None:
                        rotate()
                    # jittered exponential backoff: the ceiling doubles
                    # per consecutive failure (capped), the actual sleep
                    # is drawn uniformly below it so a manager failover
                    # does not produce a synchronized re-register storm
                    delay = backoff_with_jitter(attempt, self._rng)
                    log.info("agent session failed (%s); backing off "
                             "%.2fs (attempt %d)", e, delay, attempt + 1)
                    self._stop.wait(timeout=delay)
                    attempt += 1
        finally:
            self._done.set()

    # --------------------------------------------------------------- session

    def _session(self) -> None:
        description = self.description
        if description is None:
            try:
                description = self.executor.describe()
            except Exception:
                description = None
        session_id, period = self.client.register(
            self.node_id, description=description)
        self.session_id = session_id
        self.stats["sessions"] += 1
        log.info("agent session established (%s)", session_id[:8])

        failed = threading.Event()

        def heartbeat_loop():
            p = period
            while not self._stop.is_set() and not failed.is_set():
                if self._stop.wait(timeout=p):
                    return
                try:
                    p = self.client.heartbeat(self.node_id, session_id)
                except Exception:
                    failed.set()
                    return
                self._apply_network_keys()

        hb = threading.Thread(target=heartbeat_loop, name="agent-heartbeat",
                              daemon=True)
        hb.start()

        stream = self.client.open_assignments(self.node_id, session_id)
        try:
            while not self._stop.is_set() and not failed.is_set():
                self._flush_volume_reports(session_id)
                self.volumes.retry_pending()
                try:
                    msg = stream.get(timeout=0.2)
                except TimeoutError:
                    continue
                except Closed:
                    raise stream.error or ConnectionError("stream closed")
                if msg.type == "complete":
                    self.worker.assign(msg.changes)
                else:
                    self.worker.update(msg.changes)
                self._flush_volume_reports(session_id)
            if failed.is_set():
                raise ConnectionError("heartbeat failed")
        finally:
            stream.close()
            failed.set()
            hb.join(timeout=2)

    def _apply_network_keys(self) -> None:
        """Hand rotated dataplane keys to the executor (reference:
        agent.go handleSessionMessage -> SetNetworkBootstrapKeys).  The
        wire client stashes the heartbeat piggyback; the lamport clock
        gates re-delivery so the executor sees each rotation once."""
        delivery = getattr(self.client, "network_key_delivery", None)
        if delivery is not None:
            clock, raw = delivery          # atomic pair (failover client)
        else:
            clock = getattr(self.client, "last_key_clock", None)
            raw = getattr(self.client, "last_network_keys", None)
        if clock is None or raw is None or clock == self._applied_key_clock:
            return
        from ..models.types import EncryptionKey
        from ..state import serde
        try:
            keys = [k if isinstance(k, EncryptionKey)
                    else serde.from_dict(EncryptionKey, k) for k in raw]
            self.executor.set_network_bootstrap_keys(keys)
            self._applied_key_clock = clock
        except Exception:
            log.exception("applying network bootstrap keys failed")

    # -------------------------------------------------------------- reporter

    def _report_volume_unpublished(self, volume_id: str) -> None:
        with self._unpublished_mu:
            self._unpublished.append(volume_id)

    def _flush_volume_reports(self, session_id: str) -> None:
        with self._unpublished_mu:
            pending, self._unpublished = self._unpublished, []
        if not pending:
            return
        update = getattr(self.client, "update_volume_status", None)
        if update is None:
            return
        try:
            update(self.node_id, session_id,
                   [(vid, True) for vid in pending])
        except Exception:
            # report again on the next heartbeat; unpublish is idempotent
            with self._unpublished_mu:
                self._unpublished = pending + self._unpublished

    def _report(self, task_id: str, status: TaskStatus) -> None:
        if self.worker.db is not None:
            try:
                self.worker.db.put_status(task_id, status)
            except Exception:
                log.exception("persisting task status failed")
        with self._statuses_cond:
            self._statuses[task_id] = status
            self._statuses_cond.notify()

    def _reporter_loop(self) -> None:
        while not self._stop.is_set():
            with self._statuses_cond:
                if not self._statuses:
                    self._statuses_cond.wait(timeout=0.2)
                batch, self._statuses = self._statuses, {}
            if not batch:
                continue
            session_id = self.session_id
            if session_id is None:
                self._requeue(batch)
                continue
            try:
                self.client.update_task_status(
                    self.node_id, session_id, list(batch.items()))
                self.stats["reports"] += len(batch)
            except Exception:
                # retry on next session; newer statuses win
                self._requeue(batch)
                self._stop.wait(timeout=0.2)

    def _requeue(self, batch: Dict[str, TaskStatus]) -> None:
        with self._statuses_cond:
            for task_id, status in batch.items():
                cur = self._statuses.get(task_id)
                if cur is None or cur.state < status.state:
                    self._statuses[task_id] = status
