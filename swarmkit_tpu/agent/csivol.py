"""Node-side CSI: stage/publish volumes on the worker before tasks run.

Reference: agent/csi/volumes.go (volumes manager: Add/Remove/Get with a
retry queue, publishVolume = NodeStage + NodePublish, unpublishVolume =
NodeUnpublish + NodeUnstage) and agent/csi/plugin.go (node plugin iface).

Volumes arrive as assignment dependencies from the dispatcher (alongside
secrets/configs); the worker adds them here before starting tasks that
mount them, and removes them when the dependency is released.  Removal
completion is reported back through the dispatcher's
``update_volume_status`` so the control plane can advance the volume from
PENDING_NODE_UNPUBLISH to PENDING_UNPUBLISH (dispatcher.go:682).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import Callable, Dict, Optional

log = logging.getLogger("agent.csivol")


class NodeCSIPlugin:
    """Node half of a CSI plugin (reference: agent/csi/plugin.go
    NodePlugin: NodeStageVolume/NodePublishVolume and inverses)."""

    def node_stage(self, volume) -> None:
        raise NotImplementedError

    def node_publish(self, volume) -> str:
        """Make the volume available; returns the node-local path."""
        raise NotImplementedError

    def node_unpublish(self, volume) -> None:
        raise NotImplementedError

    def node_unstage(self, volume) -> None:
        raise NotImplementedError


class FSNodePlugin(NodeCSIPlugin):
    """Filesystem-backed node plugin: volumes are directories under a
    staging root — the real-runtime analogue for the process executor
    (no block devices or kernel mounts in this environment)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _staging(self, volume) -> str:
        return os.path.join(self.base_dir, "staging", volume.id)

    def _publish_path(self, volume) -> str:
        return os.path.join(self.base_dir, "published", volume.id)

    def node_stage(self, volume) -> None:
        os.makedirs(self._staging(volume), exist_ok=True)

    def node_publish(self, volume) -> str:
        path = self._publish_path(volume)
        os.makedirs(path, exist_ok=True)
        return path

    def node_unpublish(self, volume) -> None:
        shutil.rmtree(self._publish_path(volume), ignore_errors=True)

    def node_unstage(self, volume) -> None:
        shutil.rmtree(self._staging(volume), ignore_errors=True)


class NodeVolumesManager:
    """Worker-side volume state (reference: agent/csi/volumes.go:48).

    ``add`` stages+publishes; ``remove`` unpublishes+unstages and calls
    ``on_unpublished(volume_id)`` so the agent can report completion.
    Plugins are looked up by the volume spec's driver name; a filesystem
    plugin handles drivers with no registered node plugin, so in-memory
    control-plane drivers ("inmem") still get a real local path."""

    def __init__(self, base_dir: str,
                 plugins: Optional[Dict[str, NodeCSIPlugin]] = None,
                 on_unpublished: Optional[Callable[[str], None]] = None):
        self._mu = threading.Lock()
        self._default = FSNodePlugin(base_dir)
        self.plugins: Dict[str, NodeCSIPlugin] = dict(plugins or {})
        self.on_unpublished = on_unpublished
        self._paths: Dict[str, str] = {}     # volume_id -> published path
        self._volumes: Dict[str, object] = {}
        self._pending: Dict[str, object] = {}   # failed adds, retried

    def _plugin_for(self, volume) -> NodeCSIPlugin:
        name = volume.spec.driver.name if volume.spec.driver else ""
        return self.plugins.get(name, self._default)

    # ------------------------------------------------------------- lifecycle

    def add(self, volume) -> None:
        """Stage + node-publish (idempotent).  Failures park the volume
        in a pending set retried by ``retry_pending`` — the reference
        drives the same loop through its volumequeue
        (agent/csi/volumes.go:60 retryVolumes)."""
        with self._mu:
            plugin = self._plugin_for(volume)
            try:
                plugin.node_stage(volume)
                path = plugin.node_publish(volume)
            except Exception:
                log.exception("node publish of volume %s failed; will "
                              "retry", volume.id)
                self._pending[volume.id] = volume
                return
            self._pending.pop(volume.id, None)
            self._paths[volume.id] = path
            self._volumes[volume.id] = volume

    def retry_pending(self) -> None:
        """Re-attempt failed stage/publish calls (driven from the agent's
        session loop)."""
        with self._mu:
            pending = list(self._pending.values())
        for volume in pending:
            self.add(volume)

    def remove(self, volume_id: str) -> None:
        """Node-unpublish + unstage, then report completion."""
        with self._mu:
            self._pending.pop(volume_id, None)
            volume = self._volumes.pop(volume_id, None)
            self._paths.pop(volume_id, None)
            if volume is not None:
                plugin = self._plugin_for(volume)
                try:
                    plugin.node_unpublish(volume)
                    plugin.node_unstage(volume)
                except Exception:
                    log.exception("node unpublish of volume %s failed",
                                  volume_id)
        cb = self.on_unpublished
        if cb is not None:
            try:
                cb(volume_id)
            except Exception:
                log.exception("unpublish report for %s failed", volume_id)

    # ----------------------------------------------------------------- reads

    def get(self, volume_id: str) -> Optional[str]:
        """Node-local path of a published volume (reference:
        volumes.go:128 Get), or None when not (yet) published."""
        with self._mu:
            return self._paths.get(volume_id)

    def ready(self, volume_id: str) -> bool:
        with self._mu:
            return volume_id in self._paths
