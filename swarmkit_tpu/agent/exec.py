"""Executor/Controller interfaces and the task state advancer.

Reference: agent/exec/{executor.go,controller.go,errors.go}.

``Controller`` controls one task's runtime (prepare/start/wait/shutdown/
terminate/remove); ``do_task`` is the state machine that advances a task's
observed state toward its desired state by calling controller methods —
the direct counterpart of exec.Do (controller.go:142).
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

from ..models.objects import Task
from ..models.types import TaskState, TaskStatus, now

log = logging.getLogger("exec")


class TaskError(Exception):
    pass


class ErrTaskNoop(TaskError):
    """A second call to do_task would result in no change."""


class ErrTaskRetry(TaskError):
    """Transient failure; retry after backoff."""


class ErrTaskPrepared(TaskError):
    """Prepare was called on an already-prepared task."""


class ErrTaskStarted(TaskError):
    """Start was called on an already-started task."""


class TemporaryError(TaskError):
    """Failure that should be retried rather than failing the task."""


class Controller:
    """Per-task runtime controller (reference: controller.go:16)."""

    def update(self, t: Task) -> None:
        """The task definition changed (mainly desired state)."""

    def prepare(self) -> None:
        raise NotImplementedError

    def start(self) -> None:
        raise NotImplementedError

    def wait(self) -> None:
        """Block until the task exits; raise to report failure.  Must
        return or raise TemporaryError promptly after ``interrupt()``."""
        raise NotImplementedError

    def interrupt(self) -> None:
        """Cancel an in-flight blocking call (wait/start/prepare) so the
        task manager can act on an updated task definition — the Python
        equivalent of the reference's context cancellation in
        agent/task.go (blocked Do is cancelled when an update arrives)."""

    def shutdown(self) -> None:
        raise NotImplementedError

    def terminate(self) -> None:
        raise NotImplementedError

    def remove(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class Executor:
    """Node-level runtime backend (reference: executor.go:10)."""

    def describe(self):
        """Return a NodeDescription for this node."""
        raise NotImplementedError

    def configure(self, node) -> None:
        """Apply node object changes (labels etc.)."""

    def set_network_bootstrap_keys(self, keys) -> None:
        """Receive the cluster's dataplane encryption keys (gossip/IPSec)
        when the key manager rotates them (reference:
        agent/exec/executor.go:30 SetNetworkBootstrapKeys, delivered via
        the session stream's SessionMessage.NetworkBootstrapKeys).
        Executors without a dataplane ignore them."""

    def controller(self, t: Task) -> Controller:
        raise NotImplementedError


def do_task(t: Task, ctlr: Controller) -> Tuple[TaskStatus, Optional[type]]:
    """Advance the task one state toward its desired state.

    Returns (new_status, flag) where flag is ErrTaskNoop when nothing more
    can be done without external change, ErrTaskRetry for transient
    failures, or None when a transition was made (reference:
    controller.go:142 Do).
    """
    status = t.status.copy()

    def noop():
        return status, ErrTaskNoop

    def retry():
        return status, ErrTaskRetry

    def transition(state: TaskState, msg: str):
        assert status.state <= state, "invalid state transition"
        status.state = state
        status.message = msg
        status.err = ""
        status.timestamp = now()
        return status, None

    def fatal(e: Exception):
        status.err = str(e)
        if isinstance(e, TemporaryError):
            return retry()
        status.timestamp = now()
        # terminal failure state depends on how far the task got
        if status.state < TaskState.STARTING:
            status.state = TaskState.REJECTED
        else:
            status.state = TaskState.FAILED
        return status, None

    # the agent's ceiling is SHUTDOWN: desired REMOVE also means "stop it"
    if t.desired_state >= TaskState.SHUTDOWN:
        if status.state >= TaskState.COMPLETE:
            return noop()
        try:
            ctlr.shutdown()
        except Exception as e:
            return fatal(e)
        return transition(TaskState.SHUTDOWN, "shutdown")

    if status.state > t.desired_state:
        return noop()  # way beyond desired state, pause

    # states that may proceed past the desired state
    if status.state == TaskState.PREPARING:
        try:
            ctlr.prepare()
        except ErrTaskPrepared:
            pass
        except Exception as e:
            return fatal(e)
        return transition(TaskState.READY, "prepared")
    if status.state == TaskState.STARTING:
        try:
            ctlr.start()
        except ErrTaskStarted:
            pass
        except Exception as e:
            return fatal(e)
        return transition(TaskState.RUNNING, "started")
    if status.state == TaskState.RUNNING:
        try:
            ctlr.wait()
        except Exception as e:
            return fatal(e)
        return transition(TaskState.COMPLETE, "finished")

    # pause states: proceed only when desired state is beyond current
    if status.state >= t.desired_state:
        return noop()
    if status.state in (TaskState.NEW, TaskState.PENDING,
                        TaskState.ASSIGNED):
        return transition(TaskState.ACCEPTED, "accepted")
    if status.state == TaskState.ACCEPTED:
        return transition(TaskState.PREPARING, "preparing")
    if status.state == TaskState.READY:
        return transition(TaskState.STARTING, "starting")
    return noop()
