"""Process executor: runs tasks as real OS processes.

Reference role: swarmd's container executor (agent/exec/dockerapi/
controller.go, executor.go) — the production runtime backend behind the
Executor/Controller seam.  This image has no container runtime, so the
native backend supervises plain processes instead: ``ContainerSpec.command
+ args`` become the argv, ``env`` is merged over the parent environment,
``dir`` is the working directory, and the "image" is informational.

Lifecycle mapping (controller.go:142 Do):
  prepare  -> resolve argv + stage a log file
  start    -> subprocess.Popen (new session, so shutdown can signal the
              whole process group)
  wait     -> poll the process (interruptible, like a cancelled context)
  shutdown -> SIGTERM to the group, escalating to SIGKILL after a grace
              period (dockerapi stop-grace equivalent)
  terminate-> SIGKILL immediately
  remove   -> delete the log file

Exit status: code 0 completes the task; non-zero raises with the tail of
the captured output as the error message (surfacing in Task.status.err,
like the reference's exit-code ExitError).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
import time
from typing import Dict, Optional

from ..models.objects import Task
from ..models.types import NodeDescription, Platform, Resources
from .exec import (Controller, ErrTaskRetry, Executor, TaskError,
                   TemporaryError)

log = logging.getLogger("procexec")

STOP_GRACE_PERIOD = 10.0     # SIGTERM -> SIGKILL escalation
WAIT_POLL_INTERVAL = 0.05
ERR_TAIL_BYTES = 512


class ProcessController(Controller):
    """Supervises one task's process (reference: dockerapi/controller.go)."""

    def __init__(self, task: Task, log_dir: str,
                 stop_grace: float = STOP_GRACE_PERIOD, volumes=None,
                 dependencies=None):
        self.task = task
        self.log_dir = log_dir
        self.stop_grace = stop_grace
        self.volumes = volumes   # node-side CSI manager (paths by id)
        # worker-backed secret/config getter (secret_for/config_for)
        self.dependencies = dependencies
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = os.path.join(log_dir, f"{task.id}.log")
        # secrets/configs materialize as files here (the process
        # equivalent of the reference's /run/secrets mounts)
        self.deps_dir = os.path.join(log_dir, f"{task.id}.deps")
        self._argv: Optional[list] = None
        self._env: Optional[dict] = None
        self._cwd: Optional[str] = None
        self._interrupted = threading.Event()
        self._log_file = None
        self._health_failures = 0
        self._next_health_check: Optional[float] = None

    # ----------------------------------------------------------- lifecycle

    def update(self, t: Task) -> None:
        self.task = t

    def interrupt(self) -> None:
        self._interrupted.set()

    def prepare(self) -> None:
        spec = self.task.spec.container
        if spec is None:
            raise TaskError("task has no container spec")
        argv = list(spec.command) + list(spec.args)
        if not argv:
            raise TaskError("no command to run (container.command/args)")
        env = dict(os.environ)
        for kv in spec.env:
            key, _, value = kv.partition("=")
            env[key] = value
        # published CSI volume paths surface as SWARM_VOLUME_<TARGET>
        # env vars (process tasks have no mount namespace to bind into);
        # a task with an unpublished volume must not start yet
        used_keys = set()
        if self.volumes is not None:
            for va in self.task.volumes:
                path = self.volumes.get(va.id)
                if path is None:
                    # TemporaryError: do_task retries with backoff, the
                    # task stays PREPARING until the volume publishes
                    raise TemporaryError(
                        f"volume {va.id[:8]} not yet published on node")
                key = self._dep_env_key("SWARM_VOLUME_", va.target,
                                        va.id, used_keys)
                env[key] = path
        # secrets/configs materialize as files under a per-task dir;
        # their paths surface as SWARM_SECRET_<NAME> / SWARM_CONFIG_<NAME>
        # env vars (the reference bind-mounts them at /run/secrets — a
        # process task has no mount namespace, so files + env it is).
        # A referenced-but-undelivered dependency delays the start: the
        # dispatcher ships deps before tasks, but a driver-backed secret
        # whose provider is down arrives late (reference: the container
        # waits in PREPARING until its secrets resolve)
        if self.dependencies is not None:
            for ref in spec.secrets:
                obj = self.dependencies.secret_for(self.task.id,
                                                   ref.secret_id)
                if obj is None:
                    # TemporaryError: retried with backoff — a driver-
                    # backed secret whose provider was down arrives late
                    raise TemporaryError(
                        f"secret {ref.secret_name or ref.secret_id[:8]} "
                        "not yet delivered to this node")
                key = self._dep_env_key("SWARM_SECRET_",
                                        ref.target or ref.secret_name,
                                        ref.secret_id, used_keys)
                env[key] = self._write_dep(
                    "secrets", ref.target or ref.secret_name
                    or ref.secret_id, obj.spec.data, 0o600)
            for ref in spec.configs:
                obj = self.dependencies.config_for(self.task.id,
                                                   ref.config_id)
                if obj is None:
                    raise TemporaryError(
                        f"config {ref.config_name or ref.config_id[:8]} "
                        "not yet delivered to this node")
                key = self._dep_env_key("SWARM_CONFIG_",
                                        ref.target or ref.config_name,
                                        ref.config_id, used_keys)
                env[key] = self._write_dep(
                    "configs", ref.target or ref.config_name
                    or ref.config_id, obj.spec.data, 0o644)
        self._argv = argv
        self._env = env
        self._cwd = spec.dir or None
        os.makedirs(self.log_dir, exist_ok=True)

    @staticmethod
    def _dep_env_key(prefix: str, name: str, obj_id: str,
                     used_keys: set) -> str:
        """One mangle for every dependency kind; distinct names can
        mangle identically (db-pass vs db.pass), so collisions
        disambiguate by object id."""
        mangled = "".join(ch if ch.isalnum() else "_"
                          for ch in (name or "").strip("/")).upper()
        key = prefix + (mangled or "UNNAMED")
        if key in used_keys:
            key = f"{key}_{obj_id[:6].upper()}"
        used_keys.add(key)
        return key

    def _write_dep(self, kind: str, name: str, data: bytes,
                   mode: int) -> str:
        """Secrets and configs live in separate subdirs so same-named
        targets cannot overwrite each other across kinds."""
        d = os.path.join(self.deps_dir, kind)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, name.strip("/").replace("/", "_") or "dep")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        os.fchmod(fd, mode)   # O_CREAT mode only applies to new files
        with os.fdopen(fd, "wb") as f:
            f.write(data or b"")
        return path

    def start(self) -> None:
        if self.proc is not None:
            return
        assert self._argv is not None, "start before prepare"
        self._close_log()   # a failed spawn retry must not leak the fd
        self._log_file = open(self.log_path, "ab")
        try:
            # own session: signals reach the whole process group, so a
            # task that spawns children cannot leak them past shutdown
            self.proc = subprocess.Popen(
                self._argv, env=self._env, cwd=self._cwd,
                stdout=self._log_file, stderr=subprocess.STDOUT,
                start_new_session=True)
        except FileNotFoundError as e:
            raise TaskError(f"executable not found: {e.filename}")
        except OSError as e:
            raise TemporaryError(f"spawn failed: {e}")
        # health state lives on the controller, not in wait(): an
        # interrupt-triggered wait() retry must neither reset the
        # consecutive-failure count nor re-apply start_period
        argv, hc = self._health_argv()
        self._health_failures = 0
        self._next_health_check = None
        self._health_grace_until = 0.0
        if argv is not None:
            # probes run on the normal interval from the start;
            # start_period only suppresses failure COUNTING, it does not
            # delay probing (reference: dockerd health.go — probes during
            # the start period run but failures don't count, and one
            # success ends the period early)
            self._next_health_check = time.monotonic() + \
                (hc.interval or 30.0)
            self._health_grace_until = time.monotonic() + \
                (hc.start_period or 0.0)

    def _health_argv(self):
        """Health probe argv from the spec, or None when disabled
        (reference: api/types.proto HealthConfig.Test — ["NONE"]
        disables, ["CMD", ...] is exec form, ["CMD-SHELL", s] runs via
        the shell; dockerapi executes these inside the container, here
        they run as host probes beside the process)."""
        c = self.task.spec.container
        hc = c.healthcheck if c is not None else None
        if hc is None or not hc.test:
            return None, None
        test = list(hc.test)
        if test[0] == "NONE":
            return None, None
        if test[0] == "CMD":
            argv = test[1:]
        elif test[0] == "CMD-SHELL":
            argv = ["sh", "-c", " ".join(test[1:])]
        else:
            argv = test
        return (argv or None), hc

    def wait(self) -> None:
        proc = self.proc
        if proc is None:
            raise TaskError("wait before start")
        health_argv, hc = self._health_argv()
        while proc.poll() is None:
            if self._interrupted.is_set():
                # one-shot: the retried wait() must be able to block again
                # (a sticky event would spin the task in retries forever)
                self._interrupted.clear()
                raise TemporaryError("wait interrupted by task update")
            if self._next_health_check is not None \
                    and time.monotonic() >= self._next_health_check:
                # reference defaults (dockerd): interval/timeout 30s,
                # 3 retries; start_period delays the first verdict
                self._next_health_check = \
                    time.monotonic() + (hc.interval or 30.0)
                failed = self._health_probe_failed(health_argv, hc)
                if self._interrupted.is_set():
                    continue   # probe aborted: verdict is inconclusive
                if failed:
                    if time.monotonic() < self._health_grace_until:
                        continue   # start period: failures don't count
                    self._health_failures += 1
                    if self._health_failures >= (hc.retries or 3):
                        # unhealthy: stop the task so the restart policy
                        # takes over (reference: dockerapi controller
                        # Wait returns when the container turns
                        # unhealthy -> task fails -> orchestrator heals)
                        self.shutdown()
                        raise TaskError(
                            f"task failed health check "
                            f"({self._health_failures} consecutive "
                            f"failures): {' '.join(health_argv)}")
                else:
                    self._health_failures = 0
                    # a success ends the start period early: later
                    # failures count from here on
                    self._health_grace_until = 0.0
            time.sleep(WAIT_POLL_INTERVAL)
        code = proc.returncode
        if code != 0:
            raise TaskError(
                f"process exited with {code}: {self._err_tail()}")

    def _health_probe_failed(self, argv, hc) -> bool:
        """Run one probe in its own process group, polling so an
        interrupt() aborts promptly (the Controller.wait contract) and a
        timed-out shell pipeline cannot leak children past the kill."""
        try:
            p = subprocess.Popen(
                argv, env=self._env, cwd=self._cwd,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True)
        except OSError:
            return True
        deadline = time.monotonic() + (hc.timeout or 30.0)
        while p.poll() is None:
            timed_out = time.monotonic() >= deadline
            if timed_out or self._interrupted.is_set():
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()
                return timed_out   # interrupt: inconclusive, not a fail
            time.sleep(WAIT_POLL_INTERVAL)
        return p.returncode != 0

    def _err_tail(self) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - ERR_TAIL_BYTES))
                return f.read().decode("utf-8", "replace").strip()
        except OSError:
            return ""

    def _signal_group(self, sig: int) -> bool:
        proc = self.proc
        if proc is None or proc.poll() is not None:
            return False
        try:
            os.killpg(os.getpgid(proc.pid), sig)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    def shutdown(self) -> None:
        """Graceful stop: SIGTERM, then SIGKILL after the grace period."""
        if self._signal_group(signal.SIGTERM):
            deadline = time.monotonic() + self.stop_grace
            proc = self.proc
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(WAIT_POLL_INTERVAL)
            if proc.poll() is None:
                self._signal_group(signal.SIGKILL)
                proc.wait(timeout=self.stop_grace)
        self._close_log()

    def terminate(self) -> None:
        if self._signal_group(signal.SIGKILL):
            self.proc.wait(timeout=self.stop_grace)
        self._close_log()

    def remove(self) -> None:
        self._close_log()
        try:
            os.unlink(self.log_path)
        except OSError:
            pass
        import shutil
        shutil.rmtree(self.deps_dir, ignore_errors=True)

    def close(self) -> None:
        self._close_log()
        # plaintext secret material must not outlive the task's
        # controller (remove() has no caller in the task lifecycle;
        # close() always runs when the manager winds down)
        import shutil
        shutil.rmtree(self.deps_dir, ignore_errors=True)

    def _close_log(self) -> None:
        if self._log_file is not None:
            try:
                self._log_file.close()
            except OSError:
                pass
            self._log_file = None

    # -------------------------------------------------------------- logs

    def read_logs(self) -> bytes:
        try:
            with open(self.log_path, "rb") as f:
                return f.read()
        except OSError:
            return b""


class ProcessExecutor(Executor):
    """Runtime backend running tasks as supervised OS processes."""

    def __init__(self, hostname: str = "", log_dir: str = "",
                 stop_grace: float = STOP_GRACE_PERIOD):
        import socket
        import tempfile
        # node-side CSI manager, injected by the Worker so controllers
        # can hand tasks their published volume paths
        self.volumes = None
        # worker-backed secret/config getter, injected by the Worker
        self.dependencies = None
        self.hostname = hostname or socket.gethostname()
        self.log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "swarmkit-tpu-tasks")
        self.stop_grace = stop_grace
        self.controllers: Dict[str, ProcessController] = {}
        self._mu = threading.Lock()

    def describe(self) -> NodeDescription:
        cpus = os.cpu_count() or 1
        mem = 0
        try:
            mem = (os.sysconf("SC_PAGE_SIZE")
                   * os.sysconf("SC_PHYS_PAGES"))
        except (ValueError, OSError):
            pass
        uname = os.uname()
        return NodeDescription(
            hostname=self.hostname,
            platform=Platform(architecture=uname.machine,
                              os=uname.sysname.lower()),
            resources=Resources(nano_cpus=cpus * 10 ** 9,
                                memory_bytes=mem))

    MAX_EXITED_CONTROLLERS = 256

    def controller(self, t: Task) -> ProcessController:
        ctlr = ProcessController(t, self.log_dir,
                                 stop_grace=self.stop_grace,
                                 volumes=self.volumes,
                                 dependencies=self.dependencies)
        with self._mu:
            self.controllers[t.id] = ctlr
            self._sweep_locked()
        return ctlr

    def _sweep_locked(self) -> None:
        """Drop the oldest exited controllers beyond a bound (a long-
        running daemon must not grow memory/log references linearly with
        every task ever run; recent ones stay reachable for log reads)."""
        exited = [tid for tid, c in self.controllers.items()
                  if c.proc is not None and c.proc.poll() is not None]
        for tid in exited[:max(0, len(exited)
                               - self.MAX_EXITED_CONTROLLERS)]:
            self.controllers.pop(tid).close()
