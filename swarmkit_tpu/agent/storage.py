"""Agent-side task database: assigned tasks persisted across restarts.

Reference: agent/storage.go (bbolt buckets for task data / status /
assigned flag).

One JSON file per node, written atomically; tasks-per-node counts are tens,
so full-file rewrites are cheap and keep the format trivially inspectable.
On agent restart the worker reloads assigned tasks and resumes supervising
them before the dispatcher connection is back (the reference's
worker.Init).
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..models.objects import Task
from ..models.types import TaskStatus
from ..state import serde


class TaskDB:
    def __init__(self, path: str):
        self.path = path
        self._mu = threading.Lock()
        self._tasks: Dict[str, dict] = {}      # id -> serialized task
        self._statuses: Dict[str, dict] = {}   # id -> serialized status
        self._assigned: Dict[str, bool] = {}
        self._defer = 0
        self._load()

    # ------------------------------------------------------------------ disk

    def _load(self) -> None:
        try:
            with open(self.path, "rb") as f:
                data = json.loads(f.read())
            self._tasks = data.get("tasks", {})
            self._statuses = data.get("statuses", {})
            self._assigned = data.get("assigned", {})
        except FileNotFoundError:
            pass
        except Exception:
            # a torn write loses local supervision state only; the
            # dispatcher's COMPLETE assignment set rebuilds it
            self._tasks = {}
            self._statuses = {}
            self._assigned = {}

    @contextmanager
    def batch(self):
        """Defer flushing while applying a whole assignment set: one
        file rewrite instead of one per task."""
        with self._mu:
            self._defer += 1
        try:
            yield self
        finally:
            with self._mu:
                self._defer -= 1
                if self._defer == 0:
                    self._flush_locked()

    def _flush_locked(self) -> None:
        if self._defer:
            return
        payload = json.dumps({
            "tasks": self._tasks,
            "statuses": self._statuses,
            "assigned": self._assigned,
        }, sort_keys=True).encode()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self.path)

    # ------------------------------------------------------------------- api

    def put_task(self, t: Task, assigned: bool = True) -> None:
        with self._mu:
            self._tasks[t.id] = serde.to_dict(t)
            self._assigned[t.id] = assigned
            self._flush_locked()

    def put_status(self, task_id: str, status: TaskStatus) -> None:
        with self._mu:
            if task_id not in self._tasks:
                return
            self._statuses[task_id] = serde.to_dict(status)
            self._flush_locked()

    def get_status(self, task_id: str) -> Optional[TaskStatus]:
        with self._mu:
            d = self._statuses.get(task_id)
        return serde.from_dict(TaskStatus, d) if d else None

    def remove(self, task_id: str) -> None:
        with self._mu:
            self._tasks.pop(task_id, None)
            self._statuses.pop(task_id, None)
            self._assigned.pop(task_id, None)
            self._flush_locked()

    def assigned_tasks(self) -> List[Task]:
        """Tasks to resume supervising, with their last reported status
        folded in."""
        with self._mu:
            items = [(tid, dict(d)) for tid, d in self._tasks.items()
                     if self._assigned.get(tid)]
            statuses = dict(self._statuses)
        out = []
        for tid, d in items:
            t = serde.from_dict(Task, d)
            st = statuses.get(tid)
            if st:
                t.status = serde.from_dict(TaskStatus, st)
            out.append(t)
        return out
