"""Test executor/controller fakes (reference: agent/testutils/fakes.go).

TestController runs tasks without any real runtime: prepare/start succeed
instantly, wait blocks until shutdown (long-running service semantics) or
completes/fails on cue.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..models.objects import Task
from ..models.types import NodeDescription
from .exec import Controller, Executor, TaskError


class TestController(Controller):
    __test__ = False  # not a pytest class
    def __init__(self, fail_on_start: bool = False,
                 exit_after: Optional[float] = None,
                 exit_error: Optional[str] = None):
        self.task: Optional[Task] = None
        self.prepared = threading.Event()
        self.started = threading.Event()
        self.stopped = threading.Event()
        self.interrupted = threading.Event()
        self.fail_on_start = fail_on_start
        self.exit_after = exit_after
        self.exit_error = exit_error

    def update(self, t: Task) -> None:
        self.task = t

    def interrupt(self) -> None:
        self.interrupted.set()

    def prepare(self) -> None:
        self.prepared.set()

    def start(self) -> None:
        if self.fail_on_start:
            raise TaskError("TestController told to fail on start")
        self.started.set()

    def wait(self) -> None:
        from .exec import TemporaryError
        deadline = None
        if self.exit_after is not None:
            import time
            deadline = time.monotonic() + self.exit_after
        while True:
            if self.stopped.wait(timeout=0.02):
                return
            if self.interrupted.is_set():
                self.interrupted.clear()
                raise TemporaryError("wait interrupted by task update")
            if deadline is not None:
                import time
                if time.monotonic() >= deadline:
                    if self.exit_error:
                        raise TaskError(self.exit_error)
                    return  # ran to completion

    def shutdown(self) -> None:
        self.stopped.set()

    def terminate(self) -> None:
        self.stopped.set()

    def remove(self) -> None:
        pass

    def close(self) -> None:
        self.stopped.set()


class TestExecutor(Executor):
    __test__ = False  # not a pytest class
    def __init__(self, hostname: str = "test-node", resources=None,
                 **controller_kwargs):
        self.hostname = hostname
        # reported in describe(): without it a registration overwrites
        # the node's description and zeroes its capacity, starving any
        # reservation-carrying workload (None keeps legacy behavior)
        self.resources = resources
        self.controller_kwargs = controller_kwargs
        self.controllers: Dict[str, TestController] = {}
        self._mu = threading.Lock()

    def describe(self) -> NodeDescription:
        return NodeDescription(hostname=self.hostname,
                               resources=self.resources)

    def set_network_bootstrap_keys(self, keys) -> None:
        # recorded for tests asserting key-manager rotations reach agents
        self.network_keys = list(keys)

    def controller(self, t: Task) -> TestController:
        ctlr = TestController(**self.controller_kwargs)
        ctlr.task = t
        with self._mu:
            self.controllers[t.id] = ctlr
        return ctlr
