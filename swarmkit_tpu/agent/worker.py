"""Worker: applies assignment sets and supervises per-task managers.

Reference: agent/{worker.go,task.go} plus the dependency stores in
agent/dependency.go.

The worker holds the node's view of its assigned tasks (plus the secrets/
configs they reference) and runs one TaskManager per task.  A TaskManager
drives the Controller FSM via exec.do_task in its own thread and reports
every status change through the agent's reporter.  Assigned tasks persist
in the agent task DB (storage.py) so supervision survives daemon restarts,
like the reference's bbolt store (agent/storage.go).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..models.objects import Config, Secret, Task
from ..models.types import TaskState, TaskStatus, now
from . import exec as exec_mod

log = logging.getLogger("agent.worker")

Reporter = Callable[[str, TaskStatus], None]


class TaskManager:
    """Supervises one task: drives the controller FSM and pushes status
    (reference: agent/task.go:16)."""

    RETRY_BACKOFF = 0.1

    def __init__(self, task: Task, ctlr: exec_mod.Controller,
                 reporter: Reporter, on_exit=None):
        self.task = task.copy()
        self.ctlr = ctlr
        self.reporter = reporter
        self.on_exit = on_exit   # fires after ctlr.close() completes
        self._update_cond = threading.Condition()
        self._pending_update: Optional[Task] = None
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"taskmanager-{task.id[:8]}", daemon=True)
        self._thread.start()

    def update(self, t: Task) -> None:
        with self._update_cond:
            desired_changed = t.desired_state != self.task.desired_state
            self._pending_update = t.copy()
            self._update_cond.notify()
        if desired_changed:
            # pop the manager thread out of a blocking controller call so
            # it can act on the new desired state (e.g. shut down a task
            # that is blocked in wait())
            try:
                self.ctlr.interrupt()
            except Exception:
                log.exception("controller interrupt failed")

    def close(self) -> None:
        self._closed.set()
        with self._update_cond:
            self._update_cond.notify()
        try:
            self.ctlr.interrupt()
        except Exception:
            pass

    def join(self, timeout=5) -> None:
        self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._closed.is_set():
            with self._update_cond:
                if self._pending_update is not None:
                    update = self._pending_update
                    self._pending_update = None
                    self.task.desired_state = update.desired_state
                    self.task.spec = update.spec
                    try:
                        self.ctlr.update(self.task)
                    except Exception:
                        log.exception("controller update failed")

            status, flag = exec_mod.do_task(self.task, self.ctlr)
            changed = (status.state != self.task.status.state
                       or status.message != self.task.status.message
                       or status.err != self.task.status.err)
            self.task.status = status
            if changed:
                try:
                    self.reporter(self.task.id, status.copy())
                except Exception:
                    log.exception("status report failed")

            if flag is exec_mod.ErrTaskNoop:
                # nothing to do until the task definition changes
                with self._update_cond:
                    if self._pending_update is None \
                            and not self._closed.is_set():
                        self._update_cond.wait(timeout=0.5)
            elif flag is exec_mod.ErrTaskRetry:
                self._closed.wait(timeout=self.RETRY_BACKOFF)
        try:
            self.ctlr.close()
        except Exception:
            pass
        if self.on_exit is not None:
            try:
                self.on_exit(self.task.id)
            except Exception:
                log.exception("task-manager exit hook failed")


class Worker:
    """reference: agent/worker.go:30."""

    def __init__(self, executor: exec_mod.Executor, reporter: Reporter,
                 db=None, volumes=None):
        self.executor = executor
        self.reporter = reporter
        self.db = db   # agent/storage.py TaskDB (optional persistence)
        # node-side CSI manager (agent/csivol.py); volumes ship as
        # assignment dependencies like secrets/configs
        self.volumes = volumes
        if volumes is not None:
            # executors read published volume paths from here (the
            # reference hands controllers a restricted volume getter)
            executor.volumes = volumes
        # executors resolve secret/config dependencies through the worker
        # (reference: agent/dependency.go dependencyManager handed to
        # controllers as a restricted getter)
        if hasattr(executor, "dependencies"):
            executor.dependencies = self
        self._mu = threading.Lock()
        self.task_managers: Dict[str, TaskManager] = {}
        self.secrets: Dict[str, Secret] = {}
        self.configs: Dict[str, Config] = {}
        # volume removals wait until no live/closing task references the
        # volume: unstaging under a running process would rip its data
        # directory away mid-write
        self._pending_volume_removals: set = set()
        self._closing_tasks: Dict[str, Task] = {}
        self._closed = False

    # ------------------------------------------------- dependency getters

    def secret_for(self, task_id: str, secret_id: str):
        """Resolve a task's secret: task-specific id first (driver-backed
        DoNotReuse values ship as '<secret_id>.<task_id>'), then the
        shared id (reference: agent/secrets.go taskRestrictedSecrets +
        identity.CombineTwoIDs naming)."""
        return (self.secrets.get(f"{secret_id}.{task_id}")
                or self.secrets.get(secret_id))

    def config_for(self, task_id: str, config_id: str):
        return self.configs.get(config_id)

    def init_from_db(self) -> None:
        """Resume supervision of persisted assigned tasks before the
        dispatcher reconnects (reference: worker.go:82 Init)."""
        if self.db is None:
            return
        with self._mu:
            for t in self.db.assigned_tasks():
                if t.id not in self.task_managers:
                    self._start_task(t)

    # ------------------------------------------------------------- applying

    def assign(self, changes: List[tuple]) -> None:
        """Apply a COMPLETE assignment set (reference: worker.go:129)."""
        with self._mu:
            if self._closed:
                return
            self._reconcile_deps(changes, full=True)
            self._reconcile_tasks(changes, full=True)
            self._process_volume_removals_locked()

    def update(self, changes: List[tuple]) -> None:
        """Apply an INCREMENTAL assignment set
        (reference: worker.go:168)."""
        with self._mu:
            if self._closed:
                return
            self._reconcile_deps(changes, full=False)
            self._reconcile_tasks(changes, full=False)
            self._process_volume_removals_locked()

    def _process_volume_removals_locked(self) -> None:
        if self.volumes is None or not self._pending_volume_removals:
            return
        referenced = set()
        for holder in (self.task_managers, self._closing_tasks):
            for mgr_or_task in holder.values():
                t = getattr(mgr_or_task, "task", mgr_or_task)
                for va in t.volumes:
                    referenced.add(va.id)
        for vid in list(self._pending_volume_removals):
            if vid in referenced:
                continue
            self._pending_volume_removals.discard(vid)
            self.volumes.remove(vid)

    def _on_manager_exit(self, task_id: str) -> None:
        """Runs on the task manager's thread once its controller has
        fully closed (the process is gone): deferred volume removals for
        volumes this task referenced can proceed now."""
        with self._mu:
            self._closing_tasks.pop(task_id, None)
            self._process_volume_removals_locked()

    def _reconcile_deps(self, changes: List[tuple], full: bool) -> None:
        seen_secrets, seen_configs, seen_volumes = set(), set(), set()
        for action, kind, obj in changes:
            if kind == "secret":
                if action == "update":
                    self.secrets[obj.id] = obj
                    seen_secrets.add(obj.id)
                else:
                    self.secrets.pop(obj.id, None)
            elif kind == "config":
                if action == "update":
                    self.configs[obj.id] = obj
                    seen_configs.add(obj.id)
                else:
                    self.configs.pop(obj.id, None)
            elif kind == "volume" and self.volumes is not None:
                # adds stage+publish before tasks in the same message
                # start (deps precede task changes); removals defer until
                # no referencing task is live (_process_volume_removals)
                if action == "update":
                    self.volumes.add(obj)
                    seen_volumes.add(obj.id)
                else:
                    self._pending_volume_removals.add(obj.id)
        if full:
            for sid in list(self.secrets):
                if sid not in seen_secrets:
                    del self.secrets[sid]
            for cid in list(self.configs):
                if cid not in seen_configs:
                    del self.configs[cid]
            if self.volumes is not None:
                for vid in list(self.volumes._paths):
                    if vid not in seen_volumes:
                        self._pending_volume_removals.add(vid)

    def _reconcile_tasks(self, changes: List[tuple], full: bool) -> None:
        updated: List[Task] = []
        removed: List[Task] = []
        for action, kind, obj in changes:
            if kind != "task":
                continue
            (updated if action == "update" else removed).append(obj)

        assigned = set()
        db_batch = self.db.batch() if self.db is not None \
            else contextlib.nullcontext()
        with db_batch:
            for t in updated:
                assigned.add(t.id)
                if self.db is not None:
                    # fold our last reported status back in so a restarted
                    # agent does not re-run earlier lifecycle steps; DB
                    # errors must never block task execution
                    try:
                        st = self.db.get_status(t.id)
                        if st is not None and st.state > t.status.state:
                            t = t.copy()
                            t.status = st
                        self.db.put_task(t)
                    except Exception:
                        log.exception("task DB write failed")
                mgr = self.task_managers.get(t.id)
                if mgr is not None:
                    mgr.update(t)
                else:
                    self._start_task(t)

            if full:
                for task_id in list(self.task_managers):
                    if task_id not in assigned:
                        self._close_manager(task_id)
                if self.db is not None:
                    # also sweep persisted tasks that never got a manager
                    # (e.g. controller resolution failed): a COMPLETE set
                    # is the full truth
                    try:
                        for t in self.db.assigned_tasks():
                            if t.id not in assigned:
                                self.db.remove(t.id)
                    except Exception:
                        log.exception("task DB sweep failed")
            for t in removed:
                self._close_manager(t.id)

    def _start_task(self, t: Task) -> None:
        try:
            ctlr = self.executor.controller(t)
        except Exception:
            log.exception("controller resolution failed")
            self.reporter(t.id, TaskStatus(
                state=TaskState.REJECTED, timestamp=now(),
                err="controller resolution failed"))
            return
        self.task_managers[t.id] = TaskManager(
            t, ctlr, self.reporter, on_exit=self._on_manager_exit)

    def _close_manager(self, task_id: str) -> None:
        mgr = self.task_managers.pop(task_id, None)
        if mgr is not None:
            # keep the task visible to volume-removal gating until the
            # controller has fully closed (on_exit fires)
            self._closing_tasks[task_id] = mgr.task
            mgr.close()
        if self.db is not None:
            self.db.remove(task_id)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            managers = list(self.task_managers.values())
            self.task_managers.clear()
        for mgr in managers:
            mgr.close()
