"""swarmlint: AST-based invariant linter for swarmkit-tpu.

Mechanically enforces the conventions the runtime invariants hang on —
determinism seams, leadership-epoch fencing, lock discipline, package
layering, device-path purity, metric hygiene.  Run it with
``python scripts/swarmlint.py``; the framework lives in
:mod:`swarmkit_tpu.analysis.core`, the project rules in
:mod:`swarmkit_tpu.analysis.rules`.
"""

from .baseline import Baseline, BaselineEntry
from .core import ALL_RULES, Checker, Finding, ModuleInfo, checker_names, \
    make_checkers, register
from .runner import DEFAULT_BASELINE, DEFAULT_ROOTS, LintResult, \
    iter_source_files, lint_tree, write_baseline

# importing the rules package registers every project rule
from . import rules  # noqa: E402,F401

__all__ = [
    "ALL_RULES", "Baseline", "BaselineEntry", "Checker", "Finding",
    "LintResult", "ModuleInfo", "DEFAULT_BASELINE", "DEFAULT_ROOTS",
    "checker_names", "iter_source_files", "lint_tree", "make_checkers",
    "register", "write_baseline",
]
