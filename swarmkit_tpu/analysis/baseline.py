"""Committed-findings baseline: the grandfather list that may only shrink.

The baseline is a JSON file of findings that predate a rule (or are
justified permanent exceptions too broad for a per-line suppression).
Every entry MUST carry a one-line ``justification`` — an entry without
one is itself an error.  Matching is by (rule, path, stripped source
line), so entries survive line-number drift but die the moment the
offending code changes or disappears; a dead ("stale") entry is an
error too, which is what makes the baseline a ratchet: fixing a
violation forces the entry's removal, and new violations can never be
added without editing the committed file in review.  Matching is
count-aware: one entry absorbs exactly ONE occurrence, so pasting a
textually identical violation elsewhere in the same file surfaces as a
new finding instead of hiding behind the grandfathered line.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Tuple

from .core import Finding


@dataclass
class BaselineEntry:
    rule: str
    path: str
    code: str
    justification: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.code)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "code": self.code,
                "justification": self.justification}


class Baseline:
    def __init__(self, entries: List[BaselineEntry]):
        self.entries = entries
        # key -> how many entries carry it (normally 1; a file with N
        # identical grandfathered lines commits N entries)
        self._budget: Dict[Tuple[str, str, str], int] = {}
        for e in entries:
            self._budget[e.key()] = self._budget.get(e.key(), 0) + 1

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return cls([])
        return cls([BaselineEntry(
            rule=e["rule"], path=e["path"], code=e["code"],
            justification=e.get("justification", ""))
            for e in raw.get("entries", [])])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"entries": [e.to_dict() for e in sorted(
                self.entries, key=lambda e: e.key())]}, f, indent=2,
                sort_keys=True)
            f.write("\n")

    def split(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """Partition findings into (new, grandfathered) and return the
        stale entries that matched nothing.  Count-aware: each entry
        absorbs at most one finding — an (N+1)-th occurrence of an
        N-entry key is a NEW finding, and an entry beyond the number of
        live occurrences is STALE."""
        used: Dict[Tuple[str, str, str], int] = {}
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            key = f.key()
            if used.get(key, 0) < self._budget.get(key, 0):
                used[key] = used.get(key, 0) + 1
                old.append(f)
            else:
                new.append(f)
        stale: List[BaselineEntry] = []
        seen: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            seen[e.key()] = seen.get(e.key(), 0) + 1
            if seen[e.key()] > used.get(e.key(), 0):
                stale.append(e)
        return new, old, stale

    def unjustified(self) -> List[BaselineEntry]:
        """Entries with no real justification: empty, or the
        --write-baseline placeholder — a regenerated baseline must not
        pass the gate until a human writes each line."""
        return [e for e in self.entries
                if not e.justification.strip()
                or e.justification.strip().upper().startswith("TODO")]
