"""swarmlint core: findings, checker registry, per-module AST context.

The reference SwarmKit leans on ``go vet``/staticcheck/``-race`` to keep
its concurrent control plane honest; this package is the Python
equivalent, specialized to THIS codebase's invariants (see
``swarmkit_tpu/analysis/rules/``).  The framework is deliberately small:

* a :class:`Finding` is one diagnostic, fingerprinted by the *source
  text* of the offending line (not its number) so committed baselines
  survive unrelated edits;
* a :class:`Checker` visits one module at a time and may emit more
  findings from :meth:`Checker.finalize` once the whole tree has been
  seen (cross-module rules: layering, lock-order cycles, metric
  cardinality);
* suppressions are per-line comments — ``# swarmlint: disable=<rule>``
  on the offending line, or on a comment-only line directly above it —
  and the runner rejects suppressions naming unknown rules, so a typo
  can never silently disable enforcement.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Type

#: sentinel rule name: ``disable=all`` suppresses every rule on a line
ALL_RULES = "all"

_SUPPRESS_RE = re.compile(r"#\s*swarmlint:\s*disable=([A-Za-z0-9_\-]+"
                          r"(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``code`` (the stripped source line) is the
    baseline fingerprint: rule+path+code identifies a grandfathered
    finding across line-number drift."""

    rule: str
    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int
    message: str
    code: str = ""

    def key(self):
        return (self.rule, self.path, self.code)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: " \
               f"[{self.rule}] {self.message}"


class ModuleInfo:
    """Parsed module + everything checkers need: dotted name, package
    segment, source lines, import alias map, suppression map."""

    def __init__(self, relpath: str, source: str, tree: ast.AST):
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        raw = self.relpath[:-3].split("/") \
            if self.relpath.endswith(".py") else self.relpath.split("/")
        parts = raw[:-1] if raw and raw[-1] == "__init__" else raw
        self.module = ".".join(parts)
        # first package segment under swarmkit_tpu/ ("" for top-level
        # modules like swarmd.py, and for scripts/ / bench.py); computed
        # from the PATH so a package's own __init__ belongs to it
        if raw[0] == "swarmkit_tpu" and len(raw) > 2:
            self.package = raw[1]
        else:
            self.package = ""
        self.suppressions = self._parse_suppressions()
        annotate_parents(tree)

    @classmethod
    def from_source(cls, source: str, relpath: str) -> "ModuleInfo":
        return cls(relpath, source, ast.parse(source))

    # ---------------------------------------------------- suppressions
    def _parse_suppressions(self) -> Dict[int, Set[str]]:
        """Directive scan over REAL comment tokens (via tokenize), so a
        string literal that merely mentions the directive — help text,
        an error message — neither suppresses anything nor trips the
        bad-suppression audit."""
        import io
        import tokenize

        out: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            line, col = tok.start
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(line, set()).update(rules)
            # a comment-only line suppresses the next source line too,
            # so long call lines don't have to exceed the column limit
            if self.lines[line - 1][:col].strip() == "":
                out.setdefault(line + 1, set()).update(rules)
        return out

    def suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        if not rules:
            return False
        return finding.rule in rules or ALL_RULES in rules

    def all_suppression_names(self) -> Set[str]:
        names: Set[str] = set()
        for rules in self.suppressions.values():
            names.update(rules)
        return names

    # --------------------------------------------------------- helpers
    def code_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, code=self.code_at(line))


class Checker:
    """Base class.  Subclasses set ``name``/``description`` and
    implement :meth:`check`; cross-module rules accumulate state there
    and emit from :meth:`finalize`.  One instance per lint run."""

    name: str = ""
    description: str = ""

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.name:
        raise ValueError(f"checker {cls!r} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate checker name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def checker_names() -> List[str]:
    return sorted(_REGISTRY)


def make_checkers(names: Optional[Iterable[str]] = None) -> List[Checker]:
    if names is None:
        names = checker_names()
    out = []
    for n in names:
        if n not in _REGISTRY:
            raise KeyError(f"unknown swarmlint rule {n!r} "
                           f"(known: {', '.join(checker_names())})")
        out.append(_REGISTRY[n]())
    return out


# ------------------------------------------------------------ AST utilities

def annotate_parents(tree: ast.AST) -> None:
    """Attach ``_swarmlint_parent`` backlinks (idempotent)."""
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._swarmlint_parent = parent  # type: ignore[attr-defined]


def parent_of(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_swarmlint_parent", None)


class ImportMap:
    """Alias resolution for dotted-call matching: after ``import time as
    _time`` the call ``_time.monotonic()`` resolves to
    ``time.monotonic``; after ``from uuid import uuid4`` the bare
    ``uuid4()`` resolves to ``uuid.uuid4``.  Function-level imports are
    folded in too (module-wide scope — fine for linting)."""

    def __init__(self, tree: ast.AST):
        self.aliases: Dict[str, str] = {}     # local name -> module path
        self.from_names: Dict[str, str] = {}  # local name -> full dotted
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.from_names[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain with the leading alias
        resolved, or None for non-trivial expressions."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = cur.id
        if parts:
            head = self.aliases.get(head, head)
        else:
            head = self.from_names.get(head, head)
        parts.append(head)
        return ".".join(reversed(parts))


def attr_tail(node: ast.AST) -> Optional[str]:
    """The final attribute of a call target (``x.y.fetch_group`` ->
    ``fetch_group``; bare ``fetch_group`` -> itself)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def has_epoch_argument(call: ast.Call) -> bool:
    """True when the call threads an epoch: an ``epoch=`` keyword, a
    ``**kwargs`` splat (forwarders), or a positional name mentioning
    epoch (rare, but honest)."""
    for kw in call.keywords:
        if kw.arg is None:          # **kwargs forward
            return True
        if kw.arg == "epoch":
            return True
    for a in call.args:
        if isinstance(a, ast.Name) and "epoch" in a.id:
            return True
        if isinstance(a, ast.Attribute) and "epoch" in a.attr:
            return True
    return False
