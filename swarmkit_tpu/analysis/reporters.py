"""Human and JSON reporters for lint results."""

from __future__ import annotations

import json
from typing import List

from .runner import LintResult


def human_report(result: LintResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in result.new:
        lines.append(f.render())
    if result.stale:
        lines.append("")
        lines.append("stale baseline entries (fixed or moved code — "
                     "remove them from the baseline; it only shrinks):")
        for e in result.stale:
            lines.append(f"  {e.path}: [{e.rule}] {e.code!r}")
    if result.unjustified:
        lines.append("")
        lines.append("baseline entries missing a one-line justification:")
        for e in result.unjustified:
            lines.append(f"  {e.path}: [{e.rule}] {e.code!r}")
    if verbose and result.baselined:
        lines.append("")
        lines.append("grandfathered (baselined) findings:")
        for f in result.baselined:
            lines.append("  " + f.render())
    lines.append("")
    lines.append(
        f"swarmlint: {len(result.new)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed, "
        f"{len(result.stale)} stale baseline entr(y/ies), "
        f"{len(result.modules)} module(s), "
        f"{len(result.rules)} rule(s): "
        f"{'FAIL' if not result.ok else 'ok'}")
    return "\n".join(lines)


def json_report(result: LintResult) -> str:
    return json.dumps({
        "ok": result.ok,
        "rules": result.rules,
        "modules": len(result.modules),
        "suppressed": result.suppressed,
        "findings": [vars(f) for f in result.new],
        "baselined": [vars(f) for f in result.baselined],
        "stale_baseline": [e.to_dict() for e in result.stale],
        "unjustified_baseline": [e.to_dict() for e in result.unjustified],
    }, indent=2, sort_keys=True)
