"""Rule modules register themselves with the checker registry on import."""

from . import backpressure, determinism, device, fencing, layering, locking, metrics  # noqa: F401
