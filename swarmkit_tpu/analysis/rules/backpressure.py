"""backpressure-discipline: no unbounded intake on serving hot paths.

The overload plane (ISSUE 20) has one load-bearing rule: every
container a dispatcher or scheduler hot path GROWS in response to
agent traffic must either carry a declared bound (an admission check
against a ``max_*`` config knob, a ``deque(maxlen=...)``, an
evict/compact pass) or count what it sheds.  An append with neither is
the memory leak that kills a manager at 1000x agent scale — slowly,
under exactly the fan-out a chaos seed won't reproduce on a laptop.

Lexical contract, in the spirit of the lock rule:

* **scope** — modules under ``swarmkit_tpu/manager/`` and
  ``swarmkit_tpu/scheduler/`` (the serving planes; sim, obs and
  orchestrators buffer on their own clocks and are not agent-driven).
* **growable container** — a ``self.X`` initialized in ``__init__`` as
  a bare ``[]`` or a ``deque()`` WITHOUT ``maxlen`` (a ``maxlen``
  deque is self-bounding and exempt by construction).
* **hot path** — a method carrying a ``session_id`` parameter (the
  session-gated agent RPC surface: heartbeat, status writeback,
  assignment streams), plus the named intake edges ``register``,
  ``tick``, ``enqueue``/``_enqueue``.
* **violation** — ``self.X.append/appendleft/extend(...)`` or
  ``heappush(self.X, ...)`` inside a hot path whose body mentions NO
  bound/shed vocabulary (``max_*``, ``limit``, ``bound``, ``cap``,
  ``budget``, ``shed``, ``evict``, ``compact``, ``trim``, ``prune``,
  ``drop``).  Mentioning the vocabulary is the declaration: the bound
  check and the grown container sit in the same method, reviewable in
  one screenful.

Lexical scope is the limit, as ever: a bound enforced by a helper the
hot path calls under a non-matching name needs a rename or a per-line
suppression with its justification — which is the point: the bound
must be visible where the growth is.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from ..core import Checker, Finding, ModuleInfo, register

#: the serving planes whose intake is agent-driven
HOT_ROOTS = ("swarmkit_tpu/manager/", "swarmkit_tpu/scheduler/")

#: named intake edges that are hot without a session_id parameter
HOT_NAMES = {"register", "tick", "enqueue", "_enqueue"}

#: vocabulary that declares a bound or a counted shed in the method
_BOUND_RE = re.compile(
    r"max_|limit|bound|cap|budget|shed|evict|compact|trim|prune|drop",
    re.IGNORECASE)

_GROW_METHODS = {"append", "appendleft", "extend"}


def _growable_attrs(cls: ast.ClassDef) -> Set[str]:
    """``self.X`` attrs initialized in ``__init__`` as ``[]`` or an
    unbounded ``deque()`` — the containers the rule tracks."""
    out: Set[str] = set()
    for fn in cls.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name != "__init__":
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                tgt, val = node.target, node.value
            else:
                continue
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if isinstance(val, ast.List) and not val.elts:
                out.add(tgt.attr)
            elif isinstance(val, ast.Call):
                f = val.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if name == "deque" and not any(
                        kw.arg == "maxlen" for kw in val.keywords):
                    out.add(tgt.attr)
    return out


def _self_attr(expr: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``, else None."""
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _is_hot(fn: ast.FunctionDef) -> bool:
    if fn.name in HOT_NAMES:
        return True
    return any(a.arg == "session_id" for a in fn.args.args)


@register
class BackpressureDiscipline(Checker):
    name = "backpressure-discipline"
    description = ("dispatcher/scheduler hot paths may only grow a "
                   "queue behind a declared bound or a counted shed")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not mod.relpath.startswith(HOT_ROOTS):
            return []
        out: List[Finding] = []
        for cls in [n for n in mod.tree.body
                    if isinstance(n, ast.ClassDef)]:
            attrs = _growable_attrs(cls)
            if not attrs:
                continue
            for fn in [n for n in cls.body
                       if isinstance(n, ast.FunctionDef)]:
                if not _is_hot(fn):
                    continue
                declared = bool(_BOUND_RE.search(ast.unparse(fn)))
                if declared:
                    continue
                for site, attr in self._grow_sites(fn, attrs):
                    out.append(mod.finding(
                        self.name, site,
                        f"{cls.name}.{fn.name} grows self.{attr} on a "
                        "serving hot path with no declared bound or "
                        "shed counter: agent traffic sizes this "
                        "container, so it needs an admission check "
                        "against a max_* knob, a maxlen deque, or a "
                        "counted shed/evict pass in the same method "
                        "(see dispatcher.py update_task_status for "
                        "the sanctioned shape)"))
        return out

    @staticmethod
    def _grow_sites(fn: ast.FunctionDef, attrs: Set[str]):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # self.X.append / appendleft / extend
            if isinstance(f, ast.Attribute) and f.attr in _GROW_METHODS:
                attr = _self_attr(f.value)
                if attr in attrs:
                    yield node, attr
            # heapq.heappush(self.X, ...) / heappush(self.X, ...)
            is_heappush = (
                isinstance(f, ast.Attribute) and f.attr == "heappush"
            ) or (isinstance(f, ast.Name) and f.id == "heappush")
            if is_heappush and node.args:
                attr = _self_attr(node.args[0])
                if attr in attrs:
                    yield node, attr
