"""determinism-seam: all time and randomness flows through the seams.

The deterministic simulator (``swarmkit_tpu/sim``) replays the whole
control plane under a virtual clock and seeded id source; that only
works because production code reads wall-clock time through
``models.types.now()`` and mints randomness/ids through injected
``random.Random`` seams / ``utils.identity``.  This rule flags the
bypasses that silently break seed-reproducibility:

* ``time.time()`` / ``time.monotonic()`` calls — use
  ``models.types.now()`` (``time.perf_counter`` is allowed: it measures
  durations for metrics and never steers control flow);
* ``random.Random()`` with no seed, and module-level ``random.*``
  draws from the global unseeded RNG — inject a ``random.Random(seed)``
  (the ``rng or random.Random()`` constructor-default idiom for an
  injected seam parameter is allowed);
* ``uuid.uuid4()`` — use ``utils.identity.new_id()`` (routes through
  the sim's ``set_id_source`` seam);
* ``os.urandom()`` — use ``utils.identity.new_secret()`` unless the
  bytes are cryptographic key material (suppress with a justification
  in that case).

Whitelisted modules are the seams themselves, the virtual clock, the
real-subprocess executor (wall-clock health timers are its point),
crypto (``security/``), and host-side tooling (``scripts/``,
``bench.py``) that measures real time on purpose.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Checker, Finding, ImportMap, ModuleInfo, parent_of, \
    register

ALLOWED_PATHS = (
    "swarmkit_tpu/models/types.py",    # defines the now() seam
    "swarmkit_tpu/sim/clock.py",       # the virtual clock implementation
    "swarmkit_tpu/utils/identity.py",  # the id seam (crypto source)
    "swarmkit_tpu/agent/procexec.py",  # real subprocesses, real deadlines
    "swarmkit_tpu/agent/testutils.py",
    "swarmkit_tpu/security/",          # cert validity / key material are
                                       # real-world crypto by definition
    "scripts/",
    "bench.py",
)

_BANNED_CALLS = {
    "time.time":
        "bare wall-clock read; route through models.types.now() so the "
        "sim's virtual clock controls it",
    "time.monotonic":
        "bare monotonic read; route deadlines through models.types.now()"
        " (or take an injected clock seam)",
    "uuid.uuid4":
        "unseamed id; use utils.identity.new_id() (respects the sim's "
        "set_id_source seam)",
    "os.urandom":
        "unseamed entropy; use utils.identity.new_secret(), or suppress "
        "with a justification if this is cryptographic key material",
}

# module-level draws from the global, unseeded RNG
_RANDOM_GLOBAL_FNS = {"random", "randint", "uniform", "choice", "shuffle",
                      "randrange", "sample", "betavariate", "gauss"}

# numpy's global-RNG twins (ISSUE 15: the learned-scorer strategy made
# numpy arrays a production data path — weight loading must read the
# checked-in artifact, NEVER fall back to a random init; device kernels
# must not mint noise outside an injected seeded Generator)
_NUMPY_GLOBAL_FNS = {"rand", "randn", "randint", "random", "choice",
                     "shuffle", "permutation", "normal", "uniform",
                     "seed"}


def _is_or_default(node: ast.Call) -> bool:
    """True for the injected-seam constructor-default idiom
    ``self._rng = rng or random.Random()`` — the fallback only fires in
    production, where nondeterminism is the correct behavior."""
    p = parent_of(node)
    return isinstance(p, ast.BoolOp) and isinstance(p.op, ast.Or) \
        and p.values and p.values[-1] is node


@register
class DeterminismSeam(Checker):
    name = "determinism-seam"
    description = ("time/randomness/ids must flow through the injected "
                   "seams (models.types.now, utils.identity, rng params)")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        if any(mod.relpath.startswith(p) for p in ALLOWED_PATHS):
            return ()
        imports = ImportMap(mod.tree)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = imports.resolve(node.func)
            if dotted is None:
                continue
            if dotted in _BANNED_CALLS:
                out.append(mod.finding(
                    self.name, node, f"{dotted}(): {_BANNED_CALLS[dotted]}"))
            elif dotted == "random.Random" and not node.args \
                    and not node.keywords and not _is_or_default(node):
                out.append(mod.finding(
                    self.name, node,
                    "random.Random() with no seed: inject a seeded rng "
                    "(Agent(rng=...) style) or seed explicitly"))
            elif dotted.startswith("random.") \
                    and dotted.split(".", 1)[1] in _RANDOM_GLOBAL_FNS:
                out.append(mod.finding(
                    self.name, node,
                    f"{dotted}() draws from the global unseeded RNG; use "
                    "an injected random.Random(seed)"))
            elif dotted == "numpy.random.default_rng" and not node.args \
                    and not node.keywords:
                out.append(mod.finding(
                    self.name, node,
                    "numpy.random.default_rng() with no seed: pass an "
                    "explicit seed (learned-scorer weights load from the "
                    "checked-in artifact, never a random init)"))
            elif dotted.startswith("numpy.random.") \
                    and dotted.rsplit(".", 1)[1] in _NUMPY_GLOBAL_FNS:
                out.append(mod.finding(
                    self.name, node,
                    f"{dotted}() draws from numpy's global RNG; use a "
                    "seeded numpy.random.default_rng(seed) (and never "
                    "random-init scorer weights)"))
        return out
