"""device-path-purity: no host syncs or debug hooks inside plan fns.

The planner's throughput story (async dispatch overlapping host commit,
PR 4) dies the moment a jitted plan fn — or a helper it calls — forces
a host round-trip.  Inside device-path functions in ``ops``/``parallel``
(any function reaching jit: decorated with ``@jax.jit`` /
``functools.partial(jax.jit, ...)``, wrapped via ``jax.jit(fn)``, or
called from one within the same module) this rule flags:

* ``.item()`` / ``float(tracer)`` / ``int(tracer)`` — implicit D2H
  syncs (literal-constant args are fine);
* ``jax.device_get`` / ``.block_until_ready()`` — explicit syncs that
  belong in the *fetch* stage (``ops/kernel.py fetch_plan``), never
  inside the compiled program;
* ``np.*`` — numpy ops silently fall back to the host; device code uses
  ``jnp``;
* ``jax.debug.*`` — debug callbacks in the hot path recompile and
  serialize the program.

The streaming scheduler's resident device state (ops/streaming.py,
ISSUE 14) adds the DONATION shapes: a jit program built with
``donate_argnums`` hands its input buffers to XLA — the old array
object is dead the moment the call dispatches.  In the HOST drivers of
the same modules this rule therefore also flags **reuse of a donated
buffer after dispatch**: an argument passed at a donated position of a
donating jitted callable that is read again later in the same function
without being rebound from the call's result.  (The companion hazard —
a host read of a resident array *inside* the program — is the np./
.item() class above and already fires.)

The device-telemetry ledger (obs/devicetelemetry.py, ISSUE 18) adds the
UNACCOUNTED TRANSFER shape in the host drivers: every H2D staged with
``jax.device_put`` and every ``.block_until_ready()`` fetch sync in a
host function of these modules must flow through the device ledger — a
transfer the ledger never sees is a byte stream the bench regression
gates cannot gate on.  A host function touching those seams passes only
when its body also carries an accounting call (``note_h2d`` /
``note_d2h`` / ``note_bytes_avoided``, or any dotted call through
``devicetelemetry``).

The mesh-native resident tier (ISSUE 19) adds the CROSS-SHARD shapes
in the host drivers: the sharded fused pipeline keeps its carry and
resident columns laid out across the mesh between chunk dispatches, so

* a **mid-chunk ``jax.device_get``** — a value fetched D2H and then
  passed onward to a device dispatcher later in the same function —
  round-trips the sharded carry through the host between chunks
  (gather + re-lay-out across every shard) instead of fetching once
  after the last dispatch;
* a **re-``device_put`` of an already-resident array** — re-staging a
  name that is itself bound from a prior ``jax.device_put`` — pays a
  full cross-mesh re-lay-out for an array the devices already hold.

Other host-side driver code in the same modules (``TPUPlanner``, the
``ShardedPlanFn`` padding wrapper) is untouched: syncs are its job —
but transfers must be counted.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import Checker, Finding, ImportMap, ModuleInfo, register

SCOPE_PREFIXES = ("swarmkit_tpu/ops/", "swarmkit_tpu/parallel/")

_SYNC_ATTRS = {"item", "block_until_ready"}

#: a host fn carrying any of these calls is "accounted": the transfer
#: seams it touches report into the device ledger
_ACCOUNT_ATTRS = {"note_h2d", "note_d2h", "note_bytes_avoided"}


def _is_accounted(fn: ast.FunctionDef) -> bool:
    """True when the function body carries a device-ledger accounting
    call — an ``_ACCOUNT_ATTRS`` attr call (works for the conventional
    ``_devtel`` alias) or any dotted call through ``devicetelemetry``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ACCOUNT_ATTRS:
            return True
        d = _dotted(node.func)
        if d and "devicetelemetry" in d:
            return True
    return False


def _is_jit_decorator(dec: ast.AST, imports: ImportMap) -> bool:
    """Matches @jax.jit, @jit, @functools.partial(jax.jit, ...) and
    @partial(jit, ...)."""
    if isinstance(dec, ast.Call):
        dotted = imports.resolve(dec.func)
        if dotted in ("jax.jit", "jit"):
            return True
        if dotted in ("functools.partial", "partial") and dec.args:
            return imports.resolve(dec.args[0]) in ("jax.jit", "jit")
        return False
    return imports.resolve(dec) in ("jax.jit", "jit")


def _donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """Donated arg positions from a ``jax.jit``/``partial(jax.jit, …)``
    call's ``donate_argnums`` keyword; None when absent/unparsable."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return {e.value for e in v.elts}
        return None
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted source form of a Name/Attribute chain ("self.cpu_dev"),
    None for anything fancier."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _module_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Module-level and class-level defs by (unqualified) name."""
    out: Dict[str, ast.FunctionDef] = {}
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            stack.extend(ast.iter_child_nodes(node))
        elif isinstance(node, ast.FunctionDef):
            out.setdefault(node.name, node)
    return out


@register
class DevicePathPurity(Checker):
    name = "device-path-purity"
    description = ("no .item()/float()/np./jax.debug host syncs inside "
                   "jitted plan fns (ops/, parallel/)")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not mod.relpath.startswith(SCOPE_PREFIXES):
            return ()
        imports = ImportMap(mod.tree)
        fns = _module_functions(mod.tree)

        # roots: jit-decorated defs + fns wrapped as `x = jax.jit(f)`
        device: Set[str] = set()
        for name, fn in fns.items():
            if any(_is_jit_decorator(d, imports) for d in fn.decorator_list):
                device.add(name)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and imports.resolve(node.func) == "jax.jit" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in fns:
                device.add(node.args[0].id)

        # closure: helpers called (by bare name) from device fns, within
        # this module, are device code too
        frontier = list(device)
        while frontier:
            fn = fns.get(frontier.pop())
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id in fns \
                        and sub.func.id not in device:
                    device.add(sub.func.id)
                    frontier.append(sub.func.id)

        out: List[Finding] = []
        for name in sorted(device):
            out.extend(self._check_fn(mod, fns[name], imports))

        # ---- donation discipline in the HOST drivers: collect the
        # module's donating jitted callables, then flag any donated
        # buffer read again after dispatch without a rebind
        donating: Dict[str, Set[int]] = {}
        for fn_name, fn in fns.items():
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call) \
                        and _is_jit_decorator(dec, imports):
                    pos = _donated_positions(dec)
                    if pos:
                        donating[fn_name] = pos
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and imports.resolve(node.value.func) in ("jax.jit",
                                                            "jit"):
                pos = _donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            donating[tgt.id] = pos
        if donating:
            for fn in fns.values():
                out.extend(self._check_donation_reuse(mod, fn, donating))

        # ---- transfer accounting in the HOST drivers: device_put /
        # block_until_ready outside the telemetry-wrapped seams is a
        # byte stream the device ledger (and every regression gate
        # keyed on it) never sees
        for name, fn in fns.items():
            if name in device:
                continue   # device fns: the sync shapes above own these
            out.extend(self._check_unaccounted_transfer(
                mod, fn, imports))

        # ---- cross-shard discipline in the HOST drivers (ISSUE 19):
        # mid-chunk D2H of a value still being dispatched, and re-puts
        # of arrays a prior device_put already made resident
        dispatchers = device | set(donating)
        for name, fn in fns.items():
            if name in device:
                continue
            out.extend(self._check_cross_shard(
                mod, fn, imports, dispatchers))
        return out

    def _check_cross_shard(self, mod: ModuleInfo, fn: ast.FunctionDef,
                           imports: ImportMap,
                           dispatchers: Set[str]) -> List[Finding]:
        """One host function: flag ``jax.device_get(x)`` where the same
        dotted ``x`` is passed to a device dispatcher (a jitted or
        donating callable of this module) on a LATER line — the sharded
        carry is round-tripping through the host mid-chunk — and flag
        ``jax.device_put`` of a name bound from a prior ``device_put``
        — the array is already device-resident and the re-put re-lays
        it out across the whole mesh."""
        out: List[Finding] = []
        dispatch_arg_lines: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in dispatchers:
                for a in node.args:
                    d = _dotted(a)
                    if d:
                        dispatch_arg_lines.setdefault(d, []).append(
                            node.lineno)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and imports.resolve(node.func) == "jax.device_get" \
                    and node.args:
                d = _dotted(node.args[0])
                if d and any(ln > node.lineno
                             for ln in dispatch_arg_lines.get(d, ())):
                    out.append(mod.finding(
                        self.name, node,
                        f"mid-chunk jax.device_get of {d!r} in host fn "
                        f"{fn.name}: the value feeds a device dispatch "
                        "below — keep the sharded carry device-resident "
                        "between chunks and fetch once, after the last "
                        "dispatch"))
        resident: Dict[str, int] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and imports.resolve(node.value.func) \
                    == "jax.device_put":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        resident[tgt.id] = node.lineno
        if resident:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and imports.resolve(node.func) \
                        == "jax.device_put" \
                        and node.args:
                    d = _dotted(node.args[0])
                    if d in resident and node.lineno > resident[d]:
                        out.append(mod.finding(
                            self.name, node,
                            f"re-device_put of already-resident {d!r} "
                            f"in host fn {fn.name}: staged at line "
                            f"{resident[d]} — reuse the resident "
                            "handle (a sharded column re-put re-lays "
                            "out the whole mesh)"))
        return out

    def _check_unaccounted_transfer(self, mod: ModuleInfo,
                                    fn: ast.FunctionDef,
                                    imports: ImportMap) -> List[Finding]:
        """One host function: collect its ``jax.device_put`` calls
        (direct or via a local ``put = jax.device_put`` alias) and its
        ``.block_until_ready()`` syncs; all pass when the body carries
        an accounting call, all fire when it does not."""
        aliases: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and imports.resolve(node.value) == "jax.device_put":
                aliases.update(t.id for t in node.targets
                               if isinstance(t, ast.Name))
        puts: List[ast.Call] = []
        syncs: List[ast.Call] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if imports.resolve(node.func) == "jax.device_put" \
                    or (isinstance(node.func, ast.Name)
                        and node.func.id in aliases):
                puts.append(node)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                syncs.append(node)
        if not (puts or syncs) or _is_accounted(fn):
            return []
        out: List[Finding] = []
        for node in puts:
            out.append(mod.finding(
                self.name, node,
                f"unaccounted transfer: jax.device_put in host fn "
                f"{fn.name} with no device-ledger accounting — note "
                "the staged bytes (obs.devicetelemetry.note_h2d) or "
                "route through an accounted seam"))
        for node in syncs:
            out.append(mod.finding(
                self.name, node,
                f"unaccounted transfer: .block_until_ready() in host "
                f"fn {fn.name} with no device-ledger accounting — "
                "note the fetch (obs.devicetelemetry.note_d2h) or "
                "fetch via ops/kernel.py fetch_plan"))
        return out

    def _check_donation_reuse(self, mod: ModuleInfo,
                              fn: ast.FunctionDef,
                              donating: Dict[str, Set[int]]
                              ) -> List[Finding]:
        """Lexical donated-buffer-reuse scan over one (host) function:
        for every call to a donating jitted callable, any read of a
        donated argument below the call — with no intervening rebind —
        is a dead buffer being consumed."""
        out: List[Finding] = []
        loads: Dict[str, List[int]] = {}
        stores: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            d = _dotted(node) if isinstance(
                node, (ast.Name, ast.Attribute)) else None
            if d is None:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                stores.setdefault(d, []).append(node.lineno)
            elif isinstance(ctx, ast.Load):
                loads.setdefault(d, []).append(node.lineno)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donating):
                continue
            call_end = getattr(node, "end_lineno", None) or node.lineno
            for p in donating[node.func.id]:
                if p >= len(node.args) or any(
                        isinstance(a, ast.Starred)
                        for a in node.args[:p + 1]):
                    continue   # starred unpacking: positions unknowable
                d = _dotted(node.args[p])
                if d is None:
                    continue   # subscript/call args: not tracked
                for load_line in loads.get(d, ()):
                    if load_line <= call_end:
                        continue   # the call's own argument lines
                    if any(node.lineno <= s <= load_line
                           for s in stores.get(d, ())):
                        continue   # rebound from the result: fine
                    out.append(mod.finding(
                        self.name, node,
                        f"donated buffer {d!r} (arg {p} of "
                        f"{node.func.id}) read again at line "
                        f"{load_line} after dispatch: donation hands "
                        "the buffer to XLA — rebind it from the "
                        "call's result"))
                    break
        return out

    def _check_fn(self, mod: ModuleInfo, fn: ast.FunctionDef,
                  imports: ImportMap) -> List[Finding]:
        out: List[Finding] = []
        numpy_aliases = {alias for alias, target in imports.aliases.items()
                         if target == "numpy"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = imports.resolve(node.func)
                tail = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else None
                if tail in _SYNC_ATTRS:
                    out.append(mod.finding(
                        self.name, node,
                        f".{tail}() inside device fn {fn.name}: implicit "
                        "host sync; keep values on device (fetch "
                        "belongs in ops/kernel.py fetch_plan)"))
                elif dotted == "jax.device_get":
                    out.append(mod.finding(
                        self.name, node,
                        f"jax.device_get inside device fn {fn.name}: "
                        "D2H belongs in the fetch stage, not the "
                        "compiled program"))
                elif dotted and dotted.startswith("jax.debug."):
                    out.append(mod.finding(
                        self.name, node,
                        f"{dotted} inside device fn {fn.name}: debug "
                        "callbacks serialize the hot path; gate or "
                        "remove"))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in ("float", "int") \
                        and node.args \
                        and not isinstance(node.args[0], ast.Constant) \
                        and not (isinstance(node.args[0], ast.Name)
                                 and node.args[0].id.isupper()):
                    out.append(mod.finding(
                        self.name, node,
                        f"{node.func.id}() on a traced value inside "
                        f"device fn {fn.name}: implicit host sync; use "
                        "jnp dtype casts"))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in numpy_aliases:
                out.append(mod.finding(
                    self.name, node,
                    f"np.{node.attr} inside device fn {fn.name}: numpy "
                    "runs on host; use jnp"))
        return out
