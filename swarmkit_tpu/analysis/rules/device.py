"""device-path-purity: no host syncs or debug hooks inside plan fns.

The planner's throughput story (async dispatch overlapping host commit,
PR 4) dies the moment a jitted plan fn — or a helper it calls — forces
a host round-trip.  Inside device-path functions in ``ops``/``parallel``
(any function reaching jit: decorated with ``@jax.jit`` /
``functools.partial(jax.jit, ...)``, wrapped via ``jax.jit(fn)``, or
called from one within the same module) this rule flags:

* ``.item()`` / ``float(tracer)`` / ``int(tracer)`` — implicit D2H
  syncs (literal-constant args are fine);
* ``jax.device_get`` / ``.block_until_ready()`` — explicit syncs that
  belong in the *fetch* stage (``ops/kernel.py fetch_plan``), never
  inside the compiled program;
* ``np.*`` — numpy ops silently fall back to the host; device code uses
  ``jnp``;
* ``jax.debug.*`` — debug callbacks in the hot path recompile and
  serialize the program.

Host-side driver code in the same modules (``TPUPlanner``, the
``ShardedPlanFn`` padding wrapper) is untouched: syncs are its job.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import Checker, Finding, ImportMap, ModuleInfo, register

SCOPE_PREFIXES = ("swarmkit_tpu/ops/", "swarmkit_tpu/parallel/")

_SYNC_ATTRS = {"item", "block_until_ready"}


def _is_jit_decorator(dec: ast.AST, imports: ImportMap) -> bool:
    """Matches @jax.jit, @jit, @functools.partial(jax.jit, ...) and
    @partial(jit, ...)."""
    if isinstance(dec, ast.Call):
        dotted = imports.resolve(dec.func)
        if dotted in ("jax.jit", "jit"):
            return True
        if dotted in ("functools.partial", "partial") and dec.args:
            return imports.resolve(dec.args[0]) in ("jax.jit", "jit")
        return False
    return imports.resolve(dec) in ("jax.jit", "jit")


def _module_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Module-level and class-level defs by (unqualified) name."""
    out: Dict[str, ast.FunctionDef] = {}
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            stack.extend(ast.iter_child_nodes(node))
        elif isinstance(node, ast.FunctionDef):
            out.setdefault(node.name, node)
    return out


@register
class DevicePathPurity(Checker):
    name = "device-path-purity"
    description = ("no .item()/float()/np./jax.debug host syncs inside "
                   "jitted plan fns (ops/, parallel/)")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not mod.relpath.startswith(SCOPE_PREFIXES):
            return ()
        imports = ImportMap(mod.tree)
        fns = _module_functions(mod.tree)

        # roots: jit-decorated defs + fns wrapped as `x = jax.jit(f)`
        device: Set[str] = set()
        for name, fn in fns.items():
            if any(_is_jit_decorator(d, imports) for d in fn.decorator_list):
                device.add(name)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and imports.resolve(node.func) == "jax.jit" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in fns:
                device.add(node.args[0].id)

        # closure: helpers called (by bare name) from device fns, within
        # this module, are device code too
        frontier = list(device)
        while frontier:
            fn = fns.get(frontier.pop())
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id in fns \
                        and sub.func.id not in device:
                    device.add(sub.func.id)
                    frontier.append(sub.func.id)

        out: List[Finding] = []
        for name in sorted(device):
            out.extend(self._check_fn(mod, fns[name], imports))
        return out

    def _check_fn(self, mod: ModuleInfo, fn: ast.FunctionDef,
                  imports: ImportMap) -> List[Finding]:
        out: List[Finding] = []
        numpy_aliases = {alias for alias, target in imports.aliases.items()
                         if target == "numpy"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = imports.resolve(node.func)
                tail = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else None
                if tail in _SYNC_ATTRS:
                    out.append(mod.finding(
                        self.name, node,
                        f".{tail}() inside device fn {fn.name}: implicit "
                        "host sync; keep values on device (fetch "
                        "belongs in ops/kernel.py fetch_plan)"))
                elif dotted == "jax.device_get":
                    out.append(mod.finding(
                        self.name, node,
                        f"jax.device_get inside device fn {fn.name}: "
                        "D2H belongs in the fetch stage, not the "
                        "compiled program"))
                elif dotted and dotted.startswith("jax.debug."):
                    out.append(mod.finding(
                        self.name, node,
                        f"{dotted} inside device fn {fn.name}: debug "
                        "callbacks serialize the hot path; gate or "
                        "remove"))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in ("float", "int") \
                        and node.args \
                        and not isinstance(node.args[0], ast.Constant) \
                        and not (isinstance(node.args[0], ast.Name)
                                 and node.args[0].id.isupper()):
                    out.append(mod.finding(
                        self.name, node,
                        f"{node.func.id}() on a traced value inside "
                        f"device fn {fn.name}: implicit host sync; use "
                        "jnp dtype casts"))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in numpy_aliases:
                out.append(mod.finding(
                    self.name, node,
                    f"np.{node.attr} inside device fn {fn.name}: numpy "
                    "runs on host; use jnp"))
        return out
