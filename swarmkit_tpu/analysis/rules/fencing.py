"""epoch-fencing: every leader-path proposal carries a leadership epoch.

PR 5 made split-brain safety hang on a convention: a proposal minted
under reign N must be rejected if reign N+1 has started, which only
works when every proposal API *threads the epoch through*.  Three
mechanical checks keep the convention from rotting:

* call sites: every call to ``propose_async`` / ``bulk_update_tasks`` /
  ``commit_task_block`` must pass ``epoch=`` (or forward ``**kwargs``).
  A deliberate unfenced branch (the legacy-proposer compatibility path
  in the store) carries a per-line suppression with its justification;
* definitions: any function *named* ``propose`` / ``propose_async`` /
  ``bulk_update_tasks`` / ``commit_task_block`` must accept an
  ``epoch`` parameter (or ``**kwargs``) — a new proposer implementation
  cannot silently drop fencing support;
* the store's implicit pin: ``store.update(cb)`` deliberately has no
  epoch argument — it pins the epoch *internally* at commit start.
  This rule asserts that ``state/store.py``'s commit path
  (``_propose_and_commit``) still reads ``_proposer_epoch``, so the
  internal pin can't be refactored away unnoticed.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Checker, Finding, ModuleInfo, attr_tail, \
    has_epoch_argument, register

FENCED_CALLS = {"propose_async", "bulk_update_tasks", "commit_task_block"}
# bare `propose` is excluded: the name is shared with the CORE-level
# consensus append (RaftCore.propose(data) -> index), which fences one
# layer up at RaftNode/SimRaftProposer — exactly the APIs named here
FENCED_DEFS = FENCED_CALLS

STORE_MODULE = "swarmkit_tpu/state/store.py"
STORE_COMMIT_FN = "_propose_and_commit"
STORE_PIN = "_proposer_epoch"


def _accepts_epoch(fn: ast.FunctionDef) -> bool:
    args = fn.args
    names = [a.arg for a in args.args + args.kwonlyargs
             + getattr(args, "posonlyargs", [])]
    return "epoch" in names or args.kwarg is not None


@register
class EpochFencing(Checker):
    name = "epoch-fencing"
    description = ("proposals on leader paths must thread a leadership "
                   "epoch (propose_async/bulk_update_tasks/"
                   "commit_task_block; store.update pins internally)")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                tail = attr_tail(node.func)
                if tail in FENCED_CALLS and not has_epoch_argument(node):
                    out.append(mod.finding(
                        self.name, node,
                        f"{tail}() without epoch=: proposals must be "
                        "pinned to the leadership epoch they were "
                        "planned under (see docs/architecture.md, "
                        "leadership fencing)"))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in FENCED_DEFS \
                    and not _accepts_epoch(node):
                out.append(mod.finding(
                    self.name, node,
                    f"def {node.name}(...) does not accept an epoch "
                    "parameter: every proposal API must support fencing"))
        if mod.relpath == STORE_MODULE:
            out.extend(self._check_store_pin(mod))
        return out

    def _check_store_pin(self, mod: ModuleInfo) -> List[Finding]:
        """store.update has no epoch arg by design — the commit path must
        therefore pin the proposer epoch itself."""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == STORE_COMMIT_FN:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr == STORE_PIN:
                        return []
                    if isinstance(sub, ast.Name) and sub.id == STORE_PIN:
                        return []
                return [mod.finding(
                    self.name, node,
                    f"{STORE_COMMIT_FN} no longer reads {STORE_PIN}: "
                    "store.update() relies on it to pin proposals to "
                    "the epoch current at commit start")]
        return [Finding(
            rule=self.name, path=mod.relpath, line=1, col=0,
            message=f"{STORE_COMMIT_FN} not found: the store commit "
                    "path (which pins the leadership epoch) moved — "
                    "update this rule's anchor",
            code=mod.code_at(1))]
