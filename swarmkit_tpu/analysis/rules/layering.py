"""layering: the import-boundary matrix between packages.

The dependency discipline the tree grew into (and that keeps the device
path, the control plane, and the simulator separately testable):

* ``models`` and ``utils`` are the bottom: they import nothing above
  themselves (``utils`` may use ``models``);
* ``ops``/``parallel`` (the device path) never import the control plane
  (``manager``/``state``/``orchestrator``), the worker (``agent``), the
  I/O edge (``net``/``security``) or the simulator — device code sees
  only densified arrays and scheduler input structs;
* ``agent`` (worker side) never imports manager internals, control
  loops, or the device path — it talks to managers over the wire;
* ``sim`` drives the real control plane **in process** and touches
  production code only through the injected seams — it never imports
  the real I/O edge (``net``, ``security``);
* nothing in production imports ``sim`` — the simulator depends on the
  tree, never the reverse (``scripts/`` and ``bench.py`` are drivers
  and exempt).

The matrix is enforced on every ``import``/``from-import`` (including
function-local ones), with relative imports resolved against the
importing module's package.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import Checker, Finding, ModuleInfo, register

PACKAGES = {"models", "utils", "ops", "parallel", "agent", "sim", "state",
            "scheduler", "orchestrator", "manager", "obs", "net",
            "security", "analysis"}

#: importing package -> forbidden target packages
FORBIDDEN: Dict[str, Set[str]] = {
    "models": PACKAGES - {"models"},
    "utils": PACKAGES - {"utils", "models"},
    "ops": {"manager", "state", "orchestrator", "agent", "sim", "net",
            "security"},
    "parallel": {"manager", "state", "orchestrator", "agent", "sim",
                 "net", "security"},
    "agent": {"manager", "orchestrator", "scheduler", "ops", "parallel",
              "sim"},
    "sim": {"net", "security"},
    # the linter itself is pure stdlib-over-AST: it must never import the
    # tree it judges (no chicken-and-egg on a broken module)
    "analysis": PACKAGES - {"analysis"},
}

#: only the simulator (and external drivers) may import sim
SIM_IMPORTERS_EXEMPT = ("scripts/", "bench.py", "tests/")


def _resolve_relative(mod: ModuleInfo, node: ast.ImportFrom) -> Optional[str]:
    parts = mod.module.split(".")
    if mod.relpath.endswith("/__init__.py"):
        parts = parts + ["__init__"]
    if node.level >= len(parts):
        return node.module
    base = parts[:-node.level]
    return ".".join(base + ([node.module] if node.module else []))


def _target_package(dotted: str) -> Optional[str]:
    """First swarmkit_tpu-internal package segment of an import target,
    or None for stdlib/third-party/top-level modules."""
    parts = dotted.split(".")
    if parts[0] != "swarmkit_tpu" or len(parts) < 2:
        return None
    return parts[1] if parts[1] in PACKAGES else None


@register
class Layering(Checker):
    name = "layering"
    description = ("import-boundary matrix: models/utils at the bottom, "
                   "device path free of control plane, agent free of "
                   "manager internals, sim in-process only")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        exempt_from_sim = any(mod.relpath.startswith(p)
                              for p in SIM_IMPORTERS_EXEMPT)
        forbidden = FORBIDDEN.get(mod.package, set())
        for node in ast.walk(mod.tree):
            targets: List[str] = []
            if isinstance(node, ast.Import):
                targets = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = _resolve_relative(mod, node)
                    if base is None:
                        continue
                    # `from .. import store` imports members too
                    targets = [base] + [f"{base}.{a.name}"
                                        for a in node.names]
                elif node.module:
                    # `from swarmkit_tpu import sim` names the package in
                    # the imported MEMBERS, not in node.module — check
                    # both, or the from-form bypasses the whole matrix
                    targets = [node.module] + \
                        [f"{node.module}.{a.name}" for a in node.names
                         if a.name != "*"]
            else:
                continue
            for dotted in targets:
                pkg = _target_package(dotted)
                if pkg is None:
                    continue
                if pkg == "sim" and mod.package != "sim" \
                        and not exempt_from_sim:
                    out.append(mod.finding(
                        self.name, node,
                        f"import of {dotted}: production code must "
                        "never depend on the simulator (sim sits on "
                        "top of the tree)"))
                elif pkg in forbidden and pkg != mod.package:
                    out.append(mod.finding(
                        self.name, node,
                        f"{mod.package or 'top-level'} must not import "
                        f"{pkg} ({dotted}): violates the layering "
                        "matrix (see docs/architecture.md, static "
                        "analysis section)"))
        return out
