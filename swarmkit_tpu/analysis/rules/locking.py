"""lock-discipline: lock-order cycles and blocking work under hot locks.

Python has no ``-race`` detector, so the two deadlock shapes that bit
the reference (and that PRs 4-5 carefully designed around) are enforced
lexically from the AST:

* **lock-order cycles** — every ``with <obj>.<attr>:`` whose attribute
  looks like a lock contributes acquisition edges (outer -> inner,
  within one function scope) to a global graph; any cycle across the
  tree is flagged.  Today's sanctioned order is
  ``MemoryStore._update_lock -> MemoryStore._lock``.
* **blocking under the store locks** — the store *view* lock
  (``MemoryStore._lock``) is taken by every reader and by the raft
  apply path, so holding it across anything blocking (consensus waits,
  device dispatch, D2H fetches, sleeps) stalls the whole plane.  The
  *update* lock serializes writers THROUGH consensus by design — raft
  proposals under it are the commit path itself and are allowed — but
  device-side blocking (planner ``dispatch_group``/``fetch_group``,
  ``jax.device_get``, ``block_until_ready``, sleeps) under it would
  couple XLA latency into every writer, and is flagged.

Lexical scope is the limit: a callback defined under a lock but invoked
elsewhere is not charged to that lock (nested ``def``/``lambda`` reset
the held-lock stack), and manual ``.acquire()``/``.release()`` regions
are not tracked.  That is the same tradeoff ``go vet`` makes — catch
the shapes that appear in real diffs, mechanically, with zero runtime.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, register

_LOCK_ATTR_RE = re.compile(r"lock|mutex|_mu$", re.IGNORECASE)

#: lock name -> call tails that must not run while it is held.
#: Keys are ``Class.attr`` as produced by :func:`_lock_key`.
NO_BLOCK_UNDER: Dict[str, Set[str]] = {
    "MemoryStore._lock": {
        "propose", "propose_async", "wait_proposal", "fetch_group",
        "dispatch_group", "schedule_group", "device_get",
        "block_until_ready", "sleep", "read_barrier",
        "fanout_expand", "expand_events",
    },
    # read_barrier under the UPDATE lock deadlocks a follower outright:
    # the barrier waits for remote applies, and apply_store_actions
    # needs the update lock the waiter is holding.  (propose/wait under
    # it remain the sanctioned leader commit path.)  The GIL-released
    # native watch fan-out (fanout_expand / its expand_events wrapper,
    # ISSUE 13) is consumer-thread work by contract: under the WRITER
    # lock it would tax every committer with O(block) synthesis the
    # coalesced-event design exists to avoid.
    "MemoryStore._update_lock": {
        "fetch_group", "dispatch_group", "schedule_group",
        "device_get", "block_until_ready", "sleep", "read_barrier",
        "fanout_expand", "expand_events",
    },
}


def _lock_key(expr: ast.AST, cls: Optional[str]) -> Optional[str]:
    """``self._lock`` inside class C -> ``C._lock``; deeper chains keep
    their dotted suffix (``self._store._update_lock`` ->
    ``MemoryStore._update_lock`` is NOT inferred — cross-object locks
    keep the attribute path, e.g. ``_store._update_lock``)."""
    if not isinstance(expr, ast.Attribute) \
            or not _LOCK_ATTR_RE.search(expr.attr):
        return None
    parts: List[str] = [expr.attr]
    cur = expr.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    if cur.id == "self" and len(parts) == 1:
        return f"{cls or '?'}.{parts[0]}"
    if cur.id != "self":
        parts.append(cur.id)
    return ".".join(reversed(parts))


@register
class LockDiscipline(Checker):
    name = "lock-discipline"
    description = ("no lock-order cycles; no blocking raft/device calls "
                   "while the store locks are held")

    def __init__(self):
        # edge (outer, inner) -> first location seen
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # ------------------------------------------------------------ check
    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        self._out: List[Finding] = []
        self._mod = mod
        for node in mod.tree.body:
            self._visit(node, cls=None, held=[])
        return self._out

    def _visit(self, node: ast.AST, cls: Optional[str],
               held: List[str]) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                self._visit(child, cls=node.name, held=[])
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # new runtime scope: locks held at the definition site are
            # not held at call time
            body = node.body if not isinstance(node, ast.Lambda) \
                else [node.body]
            for child in body:
                self._visit(child, cls=cls, held=[])
            return
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                # context expressions evaluate under the locks already
                # held (including earlier items of this statement):
                # blocking calls there are violations too
                self._visit(item.context_expr, cls, held + acquired)
                key = _lock_key(item.context_expr, cls)
                if key is None:
                    continue
                # `with a, b:` acquires in order — a is held when b is
                # taken, so earlier items edge into later ones exactly
                # like lexical nesting
                for outer in held + acquired:
                    if outer != key:
                        self.edges.setdefault(
                            (outer, key),
                            (self._mod.relpath, item.context_expr.lineno))
                acquired.append(key)
            for child in node.body:
                self._visit(child, cls, held + acquired)
            return
        if isinstance(node, ast.Call):
            tail = attr_tail(node.func)
            if tail is not None:
                for lock in held:
                    banned = NO_BLOCK_UNDER.get(lock)
                    if banned and tail in banned:
                        self._out.append(self._mod.finding(
                            self.name, node,
                            f"{tail}() while holding {lock}: blocking "
                            "raft/device work under the store lock "
                            "stalls every reader and the raft apply "
                            "path — release first (see store.py commit "
                            "path for the sanctioned shape)"))
        for child in ast.iter_child_nodes(node):
            self._visit(child, cls, held)

    # --------------------------------------------------------- finalize
    def finalize(self) -> Iterable[Finding]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        out: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str],
                visiting: Set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cycle = tuple(sorted(path))
                    if cycle in seen_cycles:
                        continue
                    seen_cycles.add(cycle)
                    loc_path, loc_line = self.edges[(node, start)]
                    chain = " -> ".join(path + [start])
                    out.append(Finding(
                        rule=self.name, path=loc_path, line=loc_line,
                        col=0,
                        message=f"lock-order cycle: {chain}: two "
                                "threads taking these locks in opposite "
                                "orders deadlock",
                        code=""))
                elif nxt not in visiting:
                    dfs(start, nxt, path + [nxt], visiting | {nxt})

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out
