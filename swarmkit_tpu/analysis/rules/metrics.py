"""metric-hygiene: exposition-grammar checks at the call site.

Migrated from ``tests/test_flightrec.py``'s live-registry walk so it
runs over *source* — a metric name only ever emitted on a rare error
path gets checked on every lint run, not only when a test happens to
drive that path.  For every string literal (or f-string) passed to a
registry API (``counter``/``gauge``/``timer``/``get_counter``/
``get_gauge``/``observe_*``) the rule enforces the same grammar the
exposition endpoint guarantees:

* base name matches ``^swarm_[a-z0-9_]+$``;
* labels, when written literally, are ``key="value"`` pairs with
  sorted, duplicate-free keys (sorted keys make exposition strings
  stable, which the flight recorder's sha-stable dumps rely on);
* the number of *distinct literal labelsets* per base name stays under
  the cardinality bound — the static shadow of the runtime check (label
  values interpolated at runtime are each one labelset here; the live
  cardinality guard on real label values stays in tests);
* no *per-entity* label keys (``task``/``node``/``session``/... — see
  ``UNBOUNDED_LABEL_KEYS``): a counter or gauge keyed by a task or
  node id mints one series per entity and grows with cluster size, not
  with code.  Bounded domains — ``service``, ``tenant``, ``plane``,
  ``check`` — stay legal; per-entity detail belongs in task journeys
  and the flight recorder, not the metrics registry.

F-string label *values* are treated as opaque placeholders; f-string
fragments inside the base name must still produce a grammar-valid name
for any lowercase interpolation.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Checker, Finding, ModuleInfo, register

_BASE_RE = re.compile(r"^swarm_[a-z0-9_\x00]+$")
_LABEL_RE = re.compile(r'^[a-z_][a-z0-9_]*="[^"{},]*"$')
_PLACEHOLDER = "\x00"        # stands in for {interpolated} fragments
MAX_LABEL_CARDINALITY = 64

_REGISTRY_METHODS = {"counter", "gauge", "timer", "get_counter",
                     "get_gauge", "get_timer", "observe"}

#: label keys that identify one ENTITY per value: a series per task,
#: node, slot, or session is unbounded cardinality — it scales with the
#: cluster, not the codebase.  (service/tenant/plane/check are bounded
#: operator-facing domains and stay legal.)
UNBOUNDED_LABEL_KEYS = {
    "task", "task_id", "taskid",
    "node", "node_id", "nodeid",
    "slot", "container", "container_id",
    "session", "session_id", "agent", "agent_id",
}

#: receiver names that identify the metrics registry: calls on these get
#: the FULL grammar check, including the swarm_ namespace prefix (a call
#: on any other receiver is only checked when the name already claims
#: the swarm_ namespace — .timer()/.counter() are common method names)
_REGISTRY_RECEIVERS = {"registry", "metrics", "_metrics"}


def _receiver_is_registry(func: ast.Attribute) -> bool:
    cur = func.value
    while isinstance(cur, ast.Attribute):
        if cur.attr in _REGISTRY_RECEIVERS:
            return True
        cur = cur.value
    return isinstance(cur, ast.Name) and cur.id in _REGISTRY_RECEIVERS


def _literal_text(node: ast.AST) -> Optional[str]:
    """The static text of a str constant or f-string, with interpolated
    values replaced by a placeholder byte; None for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(_PLACEHOLDER)
        return "".join(parts)
    return None


@register
class MetricHygiene(Checker):
    name = "metric-hygiene"
    description = ("metric names match ^swarm_[a-z0-9_]+$ with sorted, "
                   "bounded-cardinality labels, checked at the source "
                   "call site")

    def __init__(self):
        self.labelsets: Dict[str, Set[str]] = {}
        self.base_locs: Dict[str, Tuple[str, int]] = {}

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRY_METHODS
                    and node.args):
                continue
            text = _literal_text(node.args[0])
            if text is None:
                continue
            if text.startswith(_PLACEHOLDER):
                # name begins with an interpolated fragment: the prefix
                # is unverifiable statically, like any other placeholder
                continue
            if not text.startswith("swarm_"):
                # a misprefixed name on the REAL registry is exactly the
                # namespace violation the old live-registry test caught
                if _receiver_is_registry(node.func):
                    shown = text.split("{")[0].replace(_PLACEHOLDER, "…")
                    out.append(mod.finding(
                        self.name, node,
                        f"metric name {shown!r} is outside the swarm_ "
                        "namespace: every exposed metric must match "
                        "^swarm_[a-z0-9_]+$"))
                continue
            out.extend(self._check_name(mod, node, text))
        return out

    def _check_name(self, mod: ModuleInfo, node: ast.AST,
                    text: str) -> List[Finding]:
        out: List[Finding] = []
        shown = text.replace(_PLACEHOLDER, "…")   # messages stay printable
        if "{" in text:
            base, rest = text.split("{", 1)
            if not rest.endswith("}"):
                out.append(mod.finding(
                    self.name, node,
                    f"metric {shown!r}: unterminated label block"))
                return out
            keys: List[str] = []
            for pair in rest[:-1].split(","):
                norm = pair.replace(_PLACEHOLDER, "x")
                if not _LABEL_RE.match(norm):
                    out.append(mod.finding(
                        self.name, node,
                        f"metric {shown!r}: label {norm!r} is not "
                        'key="value" with a lowercase key'))
                    continue
                key = pair.split("=", 1)[0]
                keys.append(key)
                if key in UNBOUNDED_LABEL_KEYS:
                    out.append(mod.finding(
                        self.name, node,
                        f"metric {shown!r}: label key {key!r} is "
                        "per-entity (one series per task/node/session "
                        "is unbounded cardinality) — aggregate, or "
                        "use a bounded key like service/tenant/plane"))
            if keys != sorted(keys):
                out.append(mod.finding(
                    self.name, node,
                    f"metric {shown!r}: label keys must be sorted for "
                    "stable exposition (flight-recorder dumps hash "
                    "these strings)"))
            if len(keys) != len(set(keys)):
                out.append(mod.finding(
                    self.name, node,
                    f"metric {shown!r}: duplicate label key"))
            self.labelsets.setdefault(base, set()).add(rest)
            self.base_locs.setdefault(base, (mod.relpath, node.lineno))
        else:
            base = text
        if not _BASE_RE.match(base.replace(_PLACEHOLDER, "x")):
            out.append(mod.finding(
                self.name, node,
                f"metric name {shown.split(chr(123))[0]!r} violates "
                "^swarm_[a-z0-9_]+$"))
        return out

    def finalize(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for base, sets in sorted(self.labelsets.items()):
            if len(sets) > MAX_LABEL_CARDINALITY:
                path, line = self.base_locs[base]
                out.append(Finding(
                    rule=self.name, path=path, line=line, col=0,
                    message=f"metric {base!r} has {len(sets)} distinct "
                            f"literal labelsets (> {MAX_LABEL_CARDINALITY})"
                            ": unbounded label?",
                    code=""))
        return out
