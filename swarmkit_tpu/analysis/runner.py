"""Tree walker + orchestration: parse, check, suppress, baseline.

``lint_tree`` is the one entry point (the CLI and the tier-1 test both
call it): collect sources, run every requested checker over each
module, drop per-line-suppressed findings, validate that suppressions
name real rules, then split what remains against the committed
baseline.  The result is clean (``ok``) only when there are no new
findings, no stale baseline entries, no unjustified baseline entries,
and no parse failures.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from .baseline import Baseline, BaselineEntry
from .core import ALL_RULES, Checker, Finding, ModuleInfo, checker_names, \
    make_checkers

#: what `scripts/swarmlint.py` (and the tier-1 test) lints by default
DEFAULT_ROOTS = ("swarmkit_tpu", "scripts", "bench.py")
DEFAULT_BASELINE = "swarmlint_baseline.json"

_SKIP_DIRS = {"__pycache__", ".git", "native", "build"}


@dataclass
class LintResult:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale: List[BaselineEntry] = field(default_factory=list)
    unjustified: List[BaselineEntry] = field(default_factory=list)
    suppressed: int = 0
    modules: List[str] = field(default_factory=list)
    rules: List[str] = field(default_factory=list)
    #: all unsuppressed findings before baseline split (for --write-baseline)
    raw: List[Finding] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale and not self.unjustified


def iter_source_files(repo_root: str,
                      roots: Iterable[str] = DEFAULT_ROOTS
                      ) -> List[str]:
    """Repo-relative paths of every .py file under the given roots."""
    out: List[str] = []
    for root in roots:
        abs_root = os.path.normpath(os.path.join(repo_root, root))
        if not os.path.exists(abs_root):
            # a typo'd root silently linting NOTHING would let the CI
            # gate pass vacuously — fail loudly instead
            raise FileNotFoundError(
                f"swarmlint root {root!r} does not exist under "
                f"{repo_root}")
        if os.path.isfile(abs_root):
            # normalize ('./bench.py', absolute paths) to the canonical
            # repo-relative form — rule whitelists and baseline entries
            # match on it
            out.append(os.path.relpath(abs_root, repo_root)
                       .replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def load_modules(repo_root: str, relpaths: Iterable[str]
                 ) -> (List[ModuleInfo], List[Finding]):
    mods: List[ModuleInfo] = []
    errors: List[Finding] = []
    for rel in relpaths:
        with open(os.path.join(repo_root, rel), encoding="utf-8") as f:
            source = f.read()
        try:
            mods.append(ModuleInfo.from_source(source, rel))
        except SyntaxError as e:
            errors.append(Finding(
                rule="parse-error", path=rel, line=e.lineno or 1, col=0,
                message=f"syntax error: {e.msg}", code=""))
    return mods, errors


def run_checkers(checkers: List[Checker], mods: List[ModuleInfo]
                 ) -> (List[Finding], int, List[Finding]):
    """-> (kept findings, suppressed count, bad-suppression findings)."""
    kept: List[Finding] = []
    suppressed = 0
    by_path = {m.relpath: m for m in mods}
    for mod in mods:
        for checker in checkers:
            for f in checker.check(mod):
                if mod.suppressed(f):
                    suppressed += 1
                else:
                    kept.append(f)
    for checker in checkers:
        for f in checker.finalize():
            mod = by_path.get(f.path)
            if mod is not None and mod.suppressed(f):
                suppressed += 1
            else:
                kept.append(f)

    # every suppression comment must name a real rule: a typo must be an
    # error, never a silent no-op
    known = set(checker_names()) | {ALL_RULES}
    bad: List[Finding] = []
    for mod in mods:
        for line, rules in sorted(mod.suppressions.items()):
            for r in sorted(rules - known):
                if line <= len(mod.lines) \
                        and "swarmlint" in mod.lines[line - 1]:
                    bad.append(Finding(
                        rule="bad-suppression", path=mod.relpath,
                        line=line, col=0,
                        message=f"suppression names unknown rule {r!r} "
                                f"(known: {', '.join(sorted(known))})",
                        code=mod.code_at(line)))
    return kept, suppressed, bad


def lint_tree(repo_root: str,
              roots: Iterable[str] = DEFAULT_ROOTS,
              rules: Optional[Iterable[str]] = None,
              baseline_path: Optional[str] = DEFAULT_BASELINE
              ) -> LintResult:
    from . import rules as _rules  # noqa: F401  (registration side effect)

    checkers = make_checkers(rules)
    relpaths = iter_source_files(repo_root, roots)
    mods, parse_errors = load_modules(repo_root, relpaths)
    findings, suppressed, bad = run_checkers(checkers, mods)
    findings = sorted(findings + parse_errors + bad,
                      key=lambda f: (f.path, f.line, f.rule))

    result = LintResult(suppressed=suppressed,
                        modules=[m.relpath for m in mods],
                        rules=[c.name for c in checkers],
                        raw=findings)
    if baseline_path is not None:
        full = Baseline.load(os.path.join(repo_root, baseline_path)
                             if not os.path.isabs(baseline_path)
                             else baseline_path)
        # a subtree / rule-subset run judges only the entries it could
        # have re-observed: out-of-scope entries are neither matched nor
        # stale (the full default run still ratchets everything)
        bl = Baseline(_in_scope(full.entries, result))
        result.new, result.baselined, result.stale = bl.split(findings)
        result.unjustified = bl.unjustified()
    else:
        result.new = findings
    return result


#: rules the runner itself emits, always active regardless of --rules
_META_RULES = {"parse-error", "bad-suppression"}


def _in_scope(entries: List[BaselineEntry], result: LintResult
              ) -> List[BaselineEntry]:
    scanned = set(result.modules)
    active = set(result.rules) | _META_RULES
    return [e for e in entries
            if e.path in scanned and e.rule in active]


def write_baseline(repo_root: str, result: LintResult,
                   baseline_path: str = DEFAULT_BASELINE,
                   justification: str = "TODO: justify or fix") -> int:
    """Regenerate the baseline from the current raw findings, keeping
    the justification of entries that still match.  One entry PER
    occurrence (matching is count-aware).  Entries OUTSIDE the run's
    scope (files not scanned / rules not active) are preserved verbatim
    — a subtree --write-baseline must never destroy the rest of the
    grandfather list.  New entries get the TODO placeholder, which
    ``Baseline.unjustified`` deliberately still FAILS: regenerating
    never yields a green run until a human justifies each new line.
    Returns the total entry count."""
    path = baseline_path if os.path.isabs(baseline_path) \
        else os.path.join(repo_root, baseline_path)
    old_entries = Baseline.load(path).entries
    in_scope = _in_scope(old_entries, result)
    kept_out = [e for e in old_entries if e not in in_scope]
    # key -> queue of old justifications, consumed one per occurrence
    old_just: dict = {}
    for e in in_scope:
        old_just.setdefault(e.key(), []).append(e.justification)
    entries = list(kept_out)
    for f in result.raw:
        queued = old_just.get(f.key())
        entries.append(BaselineEntry(
            rule=f.rule, path=f.path, code=f.code,
            justification=queued.pop(0) if queued else justification))
    bl = Baseline(entries)
    bl.save(path)
    return len(bl.entries)
