"""swarmctl-equivalent operator CLI over the control API.

Reference: swarmd/cmd/swarmctl (service/node/task/secret/config/cluster
subcommands).

``run_command(argv, api)`` parses and executes one command against a
ControlAPI and returns the rendered output — the same surface the
reference's cobra commands offer, minus the network hop (the gRPC client
slots in where ``api`` is passed).  ``main()`` runs a self-contained
single-node cluster for demos: swarmd-style bootstrap with an in-process
manager, a fake executor agent, and an interactive prompt.
"""

from __future__ import annotations

import argparse
import shlex
import sys
from typing import List, Optional

from .manager.controlapi import APIError, ControlAPI
from .models.specs import ContainerSpec, SecretSpec, ConfigSpec, ServiceSpec
from .models.types import (
    Annotations, NodeAvailability, TaskState, UpdateConfig, UpdateOrder,
)
from .models import ReplicatedService, ServiceMode, TaskSpec


def _fmt_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="swarmctl", add_help=True)
    sub = p.add_subparsers(dest="noun", required=True)

    svc = sub.add_parser("service").add_subparsers(dest="verb",
                                                  required=True)
    create = svc.add_parser("create")
    create.add_argument("--name", required=True)
    create.add_argument("--image", required=True)
    create.add_argument("--replicas", type=int, default=None)
    create.add_argument("--mode", choices=["replicated", "global"],
                        default="replicated")
    create.add_argument("--constraint", action="append", default=[])
    create.add_argument("--env", action="append", default=[],
                        metavar="KEY=VALUE")
    create.add_argument("--label", action="append", default=[],
                        metavar="KEY=VALUE")
    create.add_argument("--publish", action="append", default=[],
                        metavar="PUBLISHED:TARGET[/PROTO]",
                        help="publish a port (e.g. 8080:80 or 53:53/udp)")
    create.add_argument("--network", action="append", default=[],
                        help="attach to a network by name or id")
    create.add_argument("--secret", action="append", default=[],
                        metavar="NAME[:TARGET]")
    create.add_argument("--config", action="append", default=[],
                        metavar="NAME[:TARGET]")
    create.add_argument("--restart-condition",
                        choices=["none", "on-failure", "any"], default=None)
    create.add_argument("--restart-delay", type=float, default=None)
    create.add_argument("--restart-max-attempts", type=int, default=None)
    create.add_argument("--csi-volume", action="append", default=[],
                        metavar="SOURCE:TARGET",
                        help="mount a CSI volume (source = volume name or "
                             "group:<group>) at TARGET in the container")
    svc.add_parser("ls")
    inspect = svc.add_parser("inspect")
    inspect.add_argument("service")
    scale = svc.add_parser("scale")
    scale.add_argument("target")  # name=replicas
    supdate = svc.add_parser("update")
    supdate.add_argument("service")
    supdate.add_argument("--image", default="")
    supdate.add_argument("--replicas", type=int, default=None)
    supdate.add_argument("--update-parallelism", type=int, default=None)
    supdate.add_argument("--update-delay", type=float, default=None)
    supdate.add_argument("--update-order",
                         choices=["stop-first", "start-first"],
                         default=None)
    supdate.add_argument("--constraint", action="append", default=None,
                         help="replace placement constraints")
    rm = svc.add_parser("rm")
    rm.add_argument("service")
    logs = svc.add_parser("logs")
    logs.add_argument("service")
    logs.add_argument("--duration", type=float, default=2.0,
                      help="seconds to collect live log output for")
    logs.add_argument("--tail", type=int, default=-1,
                      help="last N history messages per task "
                      "(-1 = all retained, 0 = none)")
    logs.add_argument("--since", type=float, default=0.0,
                      help="only history at/after this unix time")
    logs.add_argument("--no-follow", action="store_true",
                      help="print retained history and exit")

    node = sub.add_parser("node").add_subparsers(dest="verb", required=True)
    node.add_parser("ls")
    drain = node.add_parser("drain")
    drain.add_argument("node")
    activate = node.add_parser("activate")
    activate.add_argument("node")
    npause = node.add_parser("pause")
    npause.add_argument("node")
    promote = node.add_parser("promote")
    promote.add_argument("node")
    demote = node.add_parser("demote")
    demote.add_argument("node")
    nrm = node.add_parser("rm")
    nrm.add_argument("node")
    nrm.add_argument("--force", action="store_true")
    ninspect = node.add_parser("inspect")
    ninspect.add_argument("node")

    task = sub.add_parser("task").add_subparsers(dest="verb", required=True)
    tls = task.add_parser("ls")
    tls.add_argument("--service", default="")
    tinspect = task.add_parser("inspect")
    tinspect.add_argument("task")
    trm = task.add_parser("rm")
    trm.add_argument("task")

    secret = sub.add_parser("secret").add_subparsers(dest="verb",
                                                     required=True)
    screate = secret.add_parser("create")
    screate.add_argument("name")
    screate.add_argument("data")
    secret.add_parser("ls")
    srm = secret.add_parser("rm")
    srm.add_argument("secret")
    sinsp = secret.add_parser("inspect")
    sinsp.add_argument("secret")

    config = sub.add_parser("config").add_subparsers(dest="verb",
                                                     required=True)
    ccreate = config.add_parser("create")
    ccreate.add_argument("name")
    ccreate.add_argument("data")
    config.add_parser("ls")
    crm = config.add_parser("rm")
    crm.add_argument("config")
    cinsp = config.add_parser("inspect")
    cinsp.add_argument("config")

    network = sub.add_parser("network").add_subparsers(dest="verb",
                                                       required=True)
    ncreate = network.add_parser("create")
    ncreate.add_argument("name")
    ncreate.add_argument("--driver", default="overlay")
    ncreate.add_argument("--subnet", default="")
    network.add_parser("ls")
    ninspect = network.add_parser("inspect")
    ninspect.add_argument("network")
    netrm = network.add_parser("rm")
    netrm.add_argument("network")

    volume = sub.add_parser("volume").add_subparsers(dest="verb",
                                                     required=True)
    vcreate = volume.add_parser("create")
    vcreate.add_argument("name")
    vcreate.add_argument("--driver", required=True)
    vcreate.add_argument("--group", default="")
    vcreate.add_argument("--sharing", default="none",
                         choices=["none", "readonly", "onewriter", "all"])
    vcreate.add_argument("--scope", default="single",
                         choices=["single", "multi"])
    volume.add_parser("ls")
    vinspect = volume.add_parser("inspect")
    vinspect.add_argument("volume")
    vdrain = volume.add_parser("drain")
    vdrain.add_argument("volume")
    vrm = volume.add_parser("rm")
    vrm.add_argument("volume")
    vrm.add_argument("--force", action="store_true")

    cluster = sub.add_parser("cluster").add_subparsers(dest="verb",
                                                       required=True)
    cluster.add_parser("ls")
    cluster.add_parser("inspect")
    rotate = cluster.add_parser("rotate-token")
    rotate.add_argument("role", choices=["worker", "manager"])
    cluster.add_parser("rotate-ca")
    autolock = cluster.add_parser("autolock")
    autolock.add_argument("mode", choices=["on", "off"])
    cluster.add_parser("unlock-key")
    cupdate = cluster.add_parser("update")
    cupdate.add_argument("--heartbeat-period", type=float, default=None,
                         help="dispatcher heartbeat period, seconds")
    cupdate.add_argument("--cert-expiry", type=float, default=None,
                         help="node certificate validity, seconds")
    cupdate.add_argument("--task-history-limit", type=int, default=None,
                         help="retained terminal tasks per slot")
    extca = cluster.add_parser("external-ca")
    extca.add_argument("urls", nargs="*",
                       help="CFSSL signer URLs; none = local signing")
    health = cluster.add_parser("health")
    health.add_argument("--service", default="")

    ext = sub.add_parser("extension").add_subparsers(dest="verb",
                                                     required=True)
    ecreate = ext.add_parser("create")
    ecreate.add_argument("name")
    ecreate.add_argument("--description", default="")
    ext.add_parser("ls")
    erm = ext.add_parser("rm")
    erm.add_argument("extension")

    res = sub.add_parser("resource").add_subparsers(dest="verb",
                                                    required=True)
    rcreate = res.add_parser("create")
    rcreate.add_argument("name")
    rcreate.add_argument("kind")
    rcreate.add_argument("--payload", default="")
    rls = res.add_parser("ls")
    rls.add_argument("--kind", default="")
    rrm = res.add_parser("rm")
    rrm.add_argument("resource")
    return p


def _resolve(items, ident, what):
    for obj in items:
        if obj.id == ident or obj.id.startswith(ident):
            return obj
        name = getattr(obj.spec.annotations, "name", "")
        if name == ident:
            return obj
    raise APIError(f"{what} {ident} not found")


def _update_node_spec(api, ident: str, mutate):
    """Read-modify-write a node spec with a bounded retry: agents write
    node status/description concurrently, so a freshly read version can
    be stale by the time the update lands (SequenceConflict semantics).
    Real operators should not have to hand-retry a role or availability
    flip."""
    import time as _time
    last = None
    for _ in range(10):
        n = _resolve(api.list_nodes(), ident, "node")
        spec = n.spec.copy()
        mutate(spec)
        try:
            api.update_node(n.id, n.meta.version.index, spec)
            return n
        except APIError as e:
            if "stale version" not in str(e):
                raise
            last = e
            _time.sleep(0.05)
    raise last


def _resolve_task(api, ident: str):
    """Task lookup by id or unique id prefix (tasks have no names);
    ambiguous prefixes error rather than picking an arbitrary match —
    `task rm` is destructive."""
    if not ident:
        raise APIError("task id required")
    matches = [t for t in api.list_tasks()
               if t.id == ident or t.id.startswith(ident)]
    if not matches:
        raise APIError(f"task {ident} not found")
    if len(matches) > 1 and not any(t.id == ident for t in matches):
        raise APIError(
            f"task prefix {ident} is ambiguous "
            f"({len(matches)} matches)")
    return next((t for t in matches if t.id == ident), matches[0])


def run_command(argv: List[str], api: ControlAPI) -> str:
    """Execute one CLI command; returns rendered output, raises APIError."""
    args = _build_parser().parse_args(argv)

    if args.noun == "service":
        if args.verb == "create":
            # reference: swarmctl service create flag surface
            # (swarmd/cmd/swarmctl/service/flagparser)
            spec = ServiceSpec(
                annotations=Annotations(name=args.name),
                task=TaskSpec(container=ContainerSpec(image=args.image)))
            if args.mode == "global":
                if args.replicas is not None:
                    raise APIError(
                        "--replicas conflicts with --mode global")
                spec.mode = ServiceMode.GLOBAL
            else:
                spec.mode = ServiceMode.REPLICATED
                spec.replicated = ReplicatedService(
                    replicas=1 if args.replicas is None
                    else args.replicas)
            if args.constraint:
                spec.task.placement.constraints = list(args.constraint)
            if args.env:
                for e in args.env:
                    if "=" not in e:
                        raise APIError("--env must be KEY=VALUE")
                spec.task.container.env = list(args.env)
            if args.label:
                labels = {}
                for kv in args.label:
                    k, sep, v = kv.partition("=")
                    if not sep or not k:
                        raise APIError("--label must be KEY=VALUE")
                    labels[k] = v
                spec.annotations.labels = labels
            if args.publish:
                from .models.types import (
                    EndpointSpec, PortConfig, PortProtocol,
                )
                protos = {"tcp": PortProtocol.TCP, "udp": PortProtocol.UDP,
                          "sctp": PortProtocol.SCTP}
                ports = []
                for p in args.publish:
                    spec_part, _, proto = p.partition("/")
                    pub, sep, target = spec_part.partition(":")
                    if not sep or not pub.isdigit() \
                            or not target.isdigit() \
                            or not 1 <= int(pub) <= 65535 \
                            or not 1 <= int(target) <= 65535 \
                            or (proto or "tcp") not in protos:
                        raise APIError(
                            "--publish must be PUBLISHED:TARGET[/PROTO] "
                            "with ports in 1-65535")
                    ports.append(PortConfig(
                        protocol=protos[proto or "tcp"],
                        target_port=int(target),
                        published_port=int(pub)))
                spec.endpoint = EndpointSpec(ports=ports)
            if args.network:
                from .models.types import NetworkAttachmentConfig
                nets = api.list_networks()
                for ref in args.network:
                    n = _resolve(nets, ref, "network")
                    # the allocator reads task-level attachments (VIPs
                    # and per-task addresses key on spec.task.networks)
                    spec.task.networks.append(
                        NetworkAttachmentConfig(target=n.id))
            if args.secret:
                from .models.types import SecretReference
                known = api.list_secrets()
                for ref in args.secret:
                    name, _, target = ref.partition(":")
                    s = _resolve(known, name, "secret")
                    real = s.spec.annotations.name
                    spec.task.container.secrets.append(SecretReference(
                        secret_id=s.id, secret_name=real,
                        target=target or real))
            if args.config:
                from .models.types import ConfigReference
                known = api.list_configs()
                for ref in args.config:
                    name, _, target = ref.partition(":")
                    c = _resolve(known, name, "config")
                    real = c.spec.annotations.name
                    spec.task.container.configs.append(ConfigReference(
                        config_id=c.id, config_name=real,
                        target=target or real))
            if (args.restart_condition is not None
                    or args.restart_delay is not None
                    or args.restart_max_attempts is not None):
                from .models.types import RestartCondition
                rp = spec.task.restart
                if args.restart_condition is not None:
                    rp.condition = {
                        "none": RestartCondition.NONE,
                        "on-failure": RestartCondition.ON_FAILURE,
                        "any": RestartCondition.ANY,
                    }[args.restart_condition]
                if args.restart_delay is not None:
                    rp.delay = args.restart_delay
                if args.restart_max_attempts is not None:
                    rp.max_attempts = args.restart_max_attempts
            if args.csi_volume:
                from .models.types import Mount, MountType
                for m in args.csi_volume:
                    source, sep, target = m.partition(":")
                    if not sep or not source or not target:
                        raise APIError(
                            "--csi-volume must be SOURCE:TARGET")
                    spec.task.container.mounts.append(Mount(
                        type=MountType.CSI, source=source, target=target))
            service = api.create_service(spec)
            return service.id
        if args.verb == "ls":
            services = api.list_services()
            # running/desired counts via the ListServiceStatuses helper
            # (reference: swarmctl service ls REPLICAS column)
            statuses = {}
            lister = getattr(api, "list_service_statuses", None)
            if lister is not None:
                statuses = {st["service_id"]: st
                            for st in lister([s.id for s in services])}
            rows = []
            for s in services:
                st = statuses.get(s.id)
                if st is not None:
                    replicas = (f"{st['running_tasks']}/"
                                f"{st['desired_tasks']}")
                elif s.spec.replicated:
                    replicas = str(s.spec.replicated.replicas)
                else:
                    replicas = "-"
                image = (s.spec.task.container.image
                         if s.spec.task.container else "-")
                rows.append([s.id[:12], s.spec.annotations.name,
                             s.spec.mode.name.lower(), replicas, image])
            return _fmt_table(["ID", "NAME", "MODE", "REPLICAS", "IMAGE"],
                              rows)
        if args.verb == "inspect":
            s = _resolve(api.list_services(), args.service, "service")
            tasks = api.list_tasks(service_id=s.id)
            lines = [f"ID\t\t: {s.id}",
                     f"Name\t\t: {s.spec.annotations.name}",
                     f"Mode\t\t: {s.spec.mode.name.lower()}",
                     f"Tasks\t\t: {len(tasks)}"]
            return "\n".join(lines)
        if args.verb == "scale":
            name, _, replicas = args.target.partition("=")
            if not replicas.isdigit():
                raise APIError("scale target must be <service>=<replicas>")
            s = _resolve(api.list_services(), name, "service")
            if s.spec.mode != ServiceMode.REPLICATED:
                raise APIError(
                    "scale only applies to replicated services")
            spec = s.spec.copy()
            spec.replicated = ReplicatedService(replicas=int(replicas))
            api.update_service(s.id, s.meta.version.index, spec)
            return f"{s.spec.annotations.name} scaled to {replicas}"
        if args.verb == "update":
            # reference: swarmctl service update — spec changes roll out
            # through the update supervisor (parallelism/delay/order from
            # spec.update; see orchestrator/update.py)
            s = _resolve(api.list_services(), args.service, "service")
            spec = s.spec.copy()
            if args.image:
                if spec.task.container is None:
                    raise APIError("service has no container spec")
                spec.task.container.image = args.image
            if args.replicas is not None:
                if spec.mode != ServiceMode.REPLICATED:
                    raise APIError(
                        "--replicas only applies to replicated services")
                spec.replicated = ReplicatedService(replicas=args.replicas)
            if args.constraint is not None:
                spec.task.placement.constraints = list(args.constraint)
            if (args.update_parallelism is not None
                    or args.update_delay is not None
                    or args.update_order is not None):
                uc = spec.update.copy() if spec.update else UpdateConfig()
                if args.update_parallelism is not None:
                    uc.parallelism = args.update_parallelism
                if args.update_delay is not None:
                    uc.delay = args.update_delay
                if args.update_order is not None:
                    uc.order = (UpdateOrder.START_FIRST
                                if args.update_order == "start-first"
                                else UpdateOrder.STOP_FIRST)
                spec.update = uc
            api.update_service(s.id, s.meta.version.index, spec)
            return f"{s.spec.annotations.name} updated"
        if args.verb == "rm":
            s = _resolve(api.list_services(), args.service, "service")
            api.remove_service(s.id)
            return s.id
        if args.verb == "logs":
            # live log collection through the control surface, so it
            # works identically in-process and over TCP (reference:
            # swarmctl service logs over the log broker)
            s = _resolve(api.list_services(), args.service, "service")
            lines = []
            for msg in api.collect_logs(s.id, duration=args.duration,
                                        tail=args.tail, since=args.since,
                                        follow=not args.no_follow):
                text = msg["data"].decode("utf-8", "replace").rstrip()
                for line in text.splitlines():
                    lines.append(
                        f"{s.spec.annotations.name}"
                        f".{msg['task_id'][:8]}@{msg['node_id'][:8]}"
                        f" | {line}")
            return "\n".join(lines)

    if args.noun == "node":
        if args.verb == "ls":
            rows = []
            for n in api.list_nodes():
                rows.append([
                    n.id[:12], n.spec.annotations.name or
                    (n.description.hostname if n.description else ""),
                    n.status.state.name,
                    n.spec.availability.name.lower(),
                    "manager" if n.spec.desired_role else "worker"])
            return _fmt_table(
                ["ID", "NAME", "STATUS", "AVAILABILITY", "ROLE"], rows)
        if args.verb in ("drain", "activate", "pause"):
            # reference: swarmctl node drain/activate/pause (availability
            # flips; PAUSE keeps running tasks but blocks new placements —
            # the scheduler's ReadyFilter requires ACTIVE)
            avail = {
                "drain": NodeAvailability.DRAIN,
                "activate": NodeAvailability.ACTIVE,
                "pause": NodeAvailability.PAUSE,
            }[args.verb]

            def set_avail(spec):
                spec.availability = avail
            n = _update_node_spec(api, args.node, set_avail)
            return f"{n.id} " + {"drain": "drained", "activate": "activated",
                                 "pause": "paused"}[args.verb]
        if args.verb in ("promote", "demote"):
            # reference: swarmctl node promote/demote (flips
            # spec.desired_role; the role manager reconciles raft
            # membership and the node's CA renewal picks up the role)
            from .models.types import NodeRole
            role = (NodeRole.MANAGER if args.verb == "promote"
                    else NodeRole.WORKER)

            def set_role(spec):
                spec.desired_role = role
            n = _update_node_spec(api, args.node, set_role)
            return f"{n.id} " + ("promoted" if args.verb == "promote"
                                 else "demoted")
        if args.verb == "rm":
            n = _resolve(api.list_nodes(), args.node, "node")
            api.remove_node(n.id, force=args.force)
            return n.id
        if args.verb == "inspect":
            n = _resolve(api.list_nodes(), args.node, "node")
            d = n.description
            res = d.resources if d and d.resources else None
            lines = [
                f"ID: {n.id}",
                f"Name: {n.spec.annotations.name or (d.hostname if d else '')}",
                f"Hostname: {d.hostname if d else ''}",
                f"Status: {n.status.state.name}",
                f"Availability: {n.spec.availability.name.lower()}",
                "Role: " + ("manager" if n.spec.desired_role else "worker"),
            ]
            if d and d.platform:
                lines.append(
                    f"Platform: {d.platform.os}/{d.platform.architecture}")
            if res:
                lines.append(
                    f"Resources: {res.nano_cpus / 1e9:g} CPUs / "
                    f"{res.memory_bytes >> 20} MiB")
            if n.spec.annotations.labels:
                lines.append("Labels: " + ", ".join(
                    f"{k}={v}"
                    for k, v in sorted(n.spec.annotations.labels.items())))
            return "\n".join(lines)

    if args.noun == "task":
        if args.verb == "inspect":
            # reference: swarmctl task inspect (task/inspect.go)
            t = _resolve_task(api, args.task)
            lines = [
                f"ID: {t.id}",
                f"Service: {t.service_annotations.name or t.service_id}",
                f"Slot: {t.slot}",
                f"Node: {t.node_id or '-'}",
                f"Status: {t.status.state.name}",
                f"Desired: {t.desired_state.name}",
            ]
            if t.status.message:
                lines.append(f"Message: {t.status.message}")
            if t.status.err:
                lines.append(f"Err: {t.status.err}")
            if t.spec.container is not None:
                lines.append(f"Image: {t.spec.container.image}")
            if t.networks:
                addrs = [a for n in t.networks for a in n.addresses]
                if addrs:
                    lines.append("Addresses: " + ", ".join(addrs))
            return "\n".join(lines)
        if args.verb == "rm":
            # reference: Control.RemoveTask (controlapi task.go) — an
            # operator escape hatch for stuck/historic tasks
            t = _resolve_task(api, args.task)
            api.remove_task(t.id)
            return t.id
        tasks = api.list_tasks()
        if args.service:
            s = _resolve(api.list_services(), args.service, "service")
            tasks = api.list_tasks(service_id=s.id)
        rows = []
        for t in sorted(tasks, key=lambda t: (t.service_id, t.slot)):
            rows.append([
                t.id[:12],
                f"{t.service_annotations.name or t.service_id[:8]}.{t.slot}",
                t.status.state.name,
                t.desired_state.name,
                t.node_id[:12] if t.node_id else "-"])
        return _fmt_table(
            ["ID", "TASK", "STATUS", "DESIRED", "NODE"], rows)

    if args.noun == "secret":
        if args.verb == "create":
            secret = api.create_secret(SecretSpec(
                annotations=Annotations(name=args.name),
                data=args.data.encode()))
            return secret.id
        if args.verb == "ls":
            rows = [[s.id[:12], s.spec.annotations.name]
                    for s in api.list_secrets()]
            return _fmt_table(["ID", "NAME"], rows)
        if args.verb == "rm":
            s = _resolve(api.list_secrets(), args.secret, "secret")
            api.remove_secret(s.id)
            return s.id
        if args.verb == "inspect":
            # reference: swarmctl secret inspect — metadata only, the
            # payload never leaves the manager (secret.go ListSecrets
            # strips Spec.Data)
            s = _resolve(api.list_secrets(), args.secret, "secret")
            return "\n".join([
                f"ID: {s.id}",
                f"Name: {s.spec.annotations.name}",
                f"Created: {s.meta.created_at}",
                f"Version: {s.meta.version.index}"])

    if args.noun == "network":
        from .models.specs import NetworkSpec
        from .models.types import Driver, IPAMConfig, IPAMOptions
        if args.verb == "create":
            ipam = (IPAMOptions(configs=[IPAMConfig(subnet=args.subnet)])
                    if args.subnet else None)
            net = api.create_network(NetworkSpec(
                annotations=Annotations(name=args.name),
                driver_config=Driver(name=args.driver), ipam=ipam))
            return net.id
        if args.verb == "ls":
            rows = []
            for n in api.list_networks():
                driver = (n.spec.driver_config.name
                          if n.spec.driver_config else "-")
                subnets = ",".join(
                    c.subnet for c in (n.spec.ipam.configs
                                       if n.spec.ipam else []) if c.subnet)
                rows.append([n.id[:12], n.spec.annotations.name, driver,
                             subnets or "-"])
            return _fmt_table(["ID", "NAME", "DRIVER", "SUBNETS"], rows)
        if args.verb == "inspect":
            n = _resolve(api.list_networks(), args.network, "network")
            subnets = ",".join(
                c.subnet for c in (n.spec.ipam.configs
                                   if n.spec.ipam else []) if c.subnet)
            return "\n".join([
                f"ID\t\t: {n.id}",
                f"Name\t\t: {n.spec.annotations.name}",
                f"Driver\t\t: "
                f"{n.spec.driver_config.name if n.spec.driver_config else '-'}",
                f"Subnets\t\t: {subnets or '-'}"])
        if args.verb == "rm":
            n = _resolve(api.list_networks(), args.network, "network")
            api.remove_network(n.id)
            return n.id

    if args.noun == "volume":
        from .models.specs import VolumeSpec
        from .models.types import (
            Driver, VolumeAccessMode, VolumeAccessScope, VolumeSharing,
        )
        if args.verb == "create":
            vol = api.create_volume(VolumeSpec(
                annotations=Annotations(name=args.name),
                group=args.group,
                driver=Driver(name=args.driver),
                access_mode=VolumeAccessMode(
                    scope=(VolumeAccessScope.SINGLE_NODE
                           if args.scope == "single"
                           else VolumeAccessScope.MULTI_NODE),
                    sharing=VolumeSharing[args.sharing.upper()])))
            return vol.id
        if args.verb == "ls":
            rows = []
            for v in api.list_volumes():
                state = ("pending delete" if v.pending_delete
                         else ("created" if v.volume_info
                               and v.volume_info.volume_id else "pending"))
                rows.append([
                    v.id[:12], v.spec.annotations.name, v.spec.group or "-",
                    v.spec.driver.name if v.spec.driver else "-",
                    state, str(len(v.publish_status))])
            return _fmt_table(
                ["ID", "NAME", "GROUP", "DRIVER", "STATE", "PUBLISHED"],
                rows)
        if args.verb == "inspect":
            v = _resolve(api.list_volumes(), args.volume, "volume")
            pubs = ", ".join(
                f"{p.node_id[:8]}={p.state.name.lower()}"
                for p in v.publish_status) or "-"
            return "\n".join([
                f"ID\t\t: {v.id}",
                f"Name\t\t: {v.spec.annotations.name}",
                f"Group\t\t: {v.spec.group or '-'}",
                f"Driver\t\t: "
                f"{v.spec.driver.name if v.spec.driver else '-'}",
                f"VolumeID\t: "
                f"{v.volume_info.volume_id if v.volume_info else '-'}",
                f"Published\t: {pubs}"])
        if args.verb == "drain":
            # availability=DRAIN: the volume enforcer evicts users and the
            # CSI manager unpublishes (reference: VolumeAvailability)
            from .models.types import VolumeAvailability
            v = _resolve(api.list_volumes(), args.volume, "volume")
            spec = v.spec.copy()
            spec.availability = int(VolumeAvailability.DRAIN)
            api.update_volume(v.id, v.meta.version.index, spec)
            return f"{v.id} draining"
        if args.verb == "rm":
            v = _resolve(api.list_volumes(), args.volume, "volume")
            api.remove_volume(v.id, force=args.force)
            return v.id

    if args.noun == "cluster":
        if args.verb == "ls":
            # reference: swarmctl cluster ls (cluster/list.go)
            lister = getattr(api, "list_clusters", None)
            clusters = lister() if lister is not None \
                else [api.get_default_cluster()]
            rows = [[c.id[:12], c.spec.annotations.name,
                     f"{c.spec.ca_config.node_cert_expiry / 86400.0:g}d",
                     "on" if c.spec.encryption_config.auto_lock_managers
                     else "off"]
                    for c in clusters]
            return _fmt_table(["ID", "NAME", "CERT-EXPIRY", "AUTOLOCK"],
                              rows)
        c = api.get_default_cluster()
        if args.verb == "inspect":
            jt = c.root_ca.join_tokens if c.root_ca else None
            return "\n".join([
                f"ID\t\t: {c.id}",
                f"Name\t\t: {c.spec.annotations.name}",
                f"Worker token\t: {jt.worker if jt else '-'}",
                f"Manager token\t: {jt.manager if jt else '-'}"])
        if args.verb == "rotate-token":
            from .models.types import NodeRole
            token = api.rotate_join_token(
                NodeRole.MANAGER if args.role == "manager"
                else NodeRole.WORKER)
            return token
        if args.verb == "rotate-ca":
            digest = api.rotate_ca()
            return (f"root CA rotation started (new root {digest}); "
                    "nodes re-certify as they renew")
        if args.verb == "autolock":
            key = api.set_autolock(args.mode == "on")
            if args.mode == "on":
                return ("autolock enabled; unlock key (save it, shown "
                        f"once): {key}")
            return "autolock disabled"
        if args.verb == "unlock-key":
            key = api.get_unlock_key()
            return key or "autolock is not enabled"
        if args.verb == "update":
            # reference: swarmctl cluster update flags (dispatcher
            # heartbeat, CA cert expiry, orchestration history); all are
            # store-watched and take effect live
            c = api.get_default_cluster()
            spec = c.spec.copy()
            changed = []
            if args.heartbeat_period is not None:
                spec.dispatcher.heartbeat_period = args.heartbeat_period
                changed.append(
                    f"heartbeat-period={args.heartbeat_period:g}s")
            if args.cert_expiry is not None:
                spec.ca_config.node_cert_expiry = args.cert_expiry
                changed.append(f"cert-expiry={args.cert_expiry:g}s")
            if args.task_history_limit is not None:
                spec.orchestration.task_history_retention_limit = \
                    args.task_history_limit
                changed.append(
                    f"task-history-limit={args.task_history_limit}")
            if not changed:
                return "nothing to update"
            api.update_cluster(c.id, c.meta.version.index, spec)
            return "updated: " + ", ".join(changed)
        if args.verb == "external-ca":
            # reference: swarmctl cluster update --external-ca; signing
            # delegates to the CFSSL endpoint(s) (ca/external.go)
            c = api.get_default_cluster()
            spec = c.spec.copy()
            spec.ca_config.external_cas = list(args.urls)
            api.update_cluster(c.id, c.meta.version.index, spec)
            if args.urls:
                return "external CA signing: " + ", ".join(args.urls)
            return "external CA signing disabled (local root signs)"
        if args.verb == "health":
            health = getattr(api, "health", None)
            if health is None:
                raise APIError("health probing needs a manager-bound API")
            return health(args.service)

    if args.noun == "extension":
        if args.verb == "create":
            ext = api.create_extension(Annotations(name=args.name),
                                       args.description)
            return ext.id
        if args.verb == "ls":
            rows = [[e.id[:12], e.annotations.name, e.description or "-"]
                    for e in api.list_extensions()]
            return _fmt_table(["ID", "NAME", "DESCRIPTION"], rows)
        if args.verb == "rm":
            e = _resolve(api.list_extensions(), args.extension,
                         "extension")
            api.remove_extension(e.id)
            return e.id

    if args.noun == "resource":
        if args.verb == "create":
            r = api.create_resource(Annotations(name=args.name),
                                    args.kind, args.payload.encode())
            return r.id
        if args.verb == "ls":
            rows = [[r.id[:12], r.annotations.name, r.kind]
                    for r in api.list_resources(kind=args.kind)]
            return _fmt_table(["ID", "NAME", "KIND"], rows)
        if args.verb == "rm":
            r = _resolve(api.list_resources(), args.resource, "resource")
            api.remove_resource(r.id)
            return r.id

    if args.noun == "config":
        if args.verb == "create":
            config = api.create_config(ConfigSpec(
                annotations=Annotations(name=args.name),
                data=args.data.encode()))
            return config.id
        if args.verb == "ls":
            rows = [[c.id[:12], c.spec.annotations.name]
                    for c in api.list_configs()]
            return _fmt_table(["ID", "NAME"], rows)
        if args.verb == "rm":
            c = _resolve(api.list_configs(), args.config, "config")
            api.remove_config(c.id)
            return c.id
        if args.verb == "inspect":
            # reference: swarmctl config inspect — configs are not
            # sensitive, so the payload prints (config/inspect.go)
            c = _resolve(api.list_configs(), args.config, "config")
            return "\n".join([
                f"ID: {c.id}",
                f"Name: {c.spec.annotations.name}",
                f"Version: {c.meta.version.index}",
                "Data: " + c.spec.data.decode("utf-8", "replace")])

    raise APIError("unknown command")


def main() -> None:   # pragma: no cover - interactive demo entry
    """A self-contained single-node cluster with an interactive prompt
    (swarmd + swarmctl in one process)."""
    import tempfile

    from .agent.testutils import TestExecutor
    from .manager.dispatcher import Config_
    from .manager.manager import Manager
    from .node import Node

    manager = Manager(dispatcher_config=Config_(heartbeat_period=1.0))
    manager.run()
    node = Node(TestExecutor(hostname="local"),
                tempfile.mkdtemp(prefix="swarmkit-tpu-"))
    token = manager.root_ca.join_token(0)
    node.load_or_join(manager.ca_server, token)
    node.start(manager.dispatcher, store=manager.store, hostname="local")
    print("single-node cluster up; try: service create --name web "
          "--image nginx --replicas 3 | service ls | task ls | quit")
    try:
        while True:
            try:
                line = input("swarmctl> ").strip()
            except EOFError:
                break
            if not line:
                continue
            if line in ("quit", "exit"):
                break
            try:
                print(run_command(shlex.split(line), manager.control_api))
            except SystemExit:
                pass
            except APIError as e:
                print(f"error: {e}")
    finally:
        node.stop()
        manager.stop()


if __name__ == "__main__":   # pragma: no cover
    main()
