from .allocator import Allocator, PortAllocator
from .controlapi import ControlAPI
from .dispatcher import (
    AssignmentsMessage, AssignmentStream, DefaultConfig, Dispatcher,
)

__all__ = ["Allocator", "ControlAPI", "AssignmentsMessage", "AssignmentStream",
           "DefaultConfig", "Dispatcher", "PortAllocator"]
