from .allocator import Allocator, PortAllocator
from .dispatcher import (
    AssignmentsMessage, AssignmentStream, DefaultConfig, Dispatcher,
)

__all__ = ["Allocator", "AssignmentsMessage", "AssignmentStream",
           "DefaultConfig", "Dispatcher", "PortAllocator"]
