from .allocator import Allocator, PortAllocator
from .controlapi import ControlAPI
from .csi import CSIPlugin, InMemoryCSIPlugin, Manager as CSIManager
from .dispatcher import (
    AssignmentsMessage, AssignmentStream, DefaultConfig, Dispatcher,
)
from .keymanager import KeyManager
from .logbroker import LogBroker, LogMessage, LogSelector
from .manager import Manager
from .metrics import Collector
from .resourceapi import ResourceAPI
from .watchapi import WatchRequest, WatchServer

__all__ = ["Allocator", "AssignmentsMessage", "AssignmentStream",
           "CSIManager", "CSIPlugin", "Collector", "ControlAPI",
           "InMemoryCSIPlugin", "DefaultConfig", "Dispatcher",
           "KeyManager", "LogBroker", "LogMessage", "LogSelector",
           "Manager", "PortAllocator", "ResourceAPI", "WatchRequest", "WatchServer"]
