"""Allocator: moves tasks NEW → PENDING by allocating their resources.

Reference: manager/allocator/{allocator.go,network.go,portallocator.go}.

The reference's allocator runs a set of sub-allocators (today: network) that
each *vote* on a task; when every registered voter has approved, the task
moves to PENDING with message "pending task scheduling" (allocator.go:38-48,
network.go:770).  Network allocation itself (VIPs, overlay attachments) is a
pluggable driver that lives outside the core in the reference (libnetwork);
here the network layer is the ``Inert`` implementation plus real **ingress
port bookkeeping**: published ports are assigned from the dynamic range
30000-32767 when unspecified, and conflicts are rejected
(portallocator.go:201).

Service allocation materializes ``service.endpoint`` from the endpoint spec;
task allocation copies the service endpoint onto the task so the scheduler's
host-port filter sees published ports.
"""

from __future__ import annotations

import ipaddress
import logging
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..models.objects import Network, Service, Task
from ..models.types import (
    Endpoint, EndpointSpec, EndpointVIP, IPAMConfig, IPAMOptions,
    NetworkAttachment, PortConfig, PublishMode, TaskState, TaskStatus, now,
)
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, ByName, MemoryStore
from ..state.watch import Closed
from .netdriver import NetworkDriverRegistry

log = logging.getLogger("allocator")

ALLOCATED_STATUS_MESSAGE = "pending task scheduling"  # network.go:21
DYNAMIC_PORT_START = 30000  # portallocator.go (dynamicPortStart)
DYNAMIC_PORT_END = 32767


class PortAllocator:
    """Ingress published-port bookkeeping (reference: portallocator.go)."""

    def __init__(self) -> None:
        self._allocated: Set[Tuple[int, int]] = set()  # (protocol, port)
        self._next_dynamic = DYNAMIC_PORT_START

    def restore(self, endpoint: Optional[Endpoint]) -> None:
        if endpoint is None:
            return
        for p in endpoint.ports:
            if p.publish_mode == PublishMode.INGRESS and p.published_port:
                self._allocated.add((p.protocol, p.published_port))

    def release(self, endpoint: Optional[Endpoint]) -> None:
        if endpoint is None:
            return
        for p in endpoint.ports:
            if p.publish_mode == PublishMode.INGRESS and p.published_port:
                self._allocated.discard((p.protocol, p.published_port))

    def allocate(self, spec_ports: List[PortConfig]) -> List[PortConfig]:
        """Resolve a port list: keep user-specified ports (conflict =
        error), assign dynamic ports for unspecified ingress publishes."""
        resolved: List[PortConfig] = []
        taken: List[Tuple[int, int]] = []
        try:
            for p in spec_ports:
                if p.publish_mode != PublishMode.INGRESS:
                    resolved.append(p)
                    continue
                if p.published_port:
                    key = (p.protocol, p.published_port)
                    if key in self._allocated:
                        raise ValueError(
                            f"port '{p.published_port}' is already in use "
                            "by service")
                    self._allocated.add(key)
                    taken.append(key)
                    resolved.append(p)
                else:
                    port = self._find_dynamic(p.protocol)
                    key = (p.protocol, port)
                    self._allocated.add(key)
                    taken.append(key)
                    resolved.append(PortConfig(
                        name=p.name, protocol=p.protocol,
                        target_port=p.target_port, published_port=port,
                        publish_mode=p.publish_mode))
            return resolved
        except ValueError:
            for key in taken:
                self._allocated.discard(key)
            raise

    def _find_dynamic(self, protocol: int) -> int:
        for _ in range(DYNAMIC_PORT_END - DYNAMIC_PORT_START + 1):
            port = self._next_dynamic
            self._next_dynamic += 1
            if self._next_dynamic > DYNAMIC_PORT_END:
                self._next_dynamic = DYNAMIC_PORT_START
            if (protocol, port) not in self._allocated:
                return port
        raise ValueError("dynamic port space exhausted")



class IPAM:
    """Subnet + address allocator over the cluster's default address pool
    (reference: manager/allocator/cnmallocator + ipamapi default-addr-pool
    semantics: carve /subnet_size subnets out of the pool, hand out VIPs
    and per-task addresses from each network's subnet; .1 is the
    gateway)."""

    def __init__(self, pools: Optional[List[str]] = None,
                 subnet_size: int = 24):
        self.pools = [ipaddress.ip_network(p)
                      for p in (pools or ["10.0.0.0/8"])]
        self.subnet_size = subnet_size
        self.subnets: Dict[str, object] = {}      # network_id -> IPv4Network
        self._used_ips: Dict[str, set] = {}       # network_id -> {int, ...}

    # ------------------------------------------------------------- networks

    def allocate_network(self, net: Network) -> IPAMOptions:
        """Pick the network's subnet: the spec's explicit one when given,
        else the next free slice of the pool."""
        spec_ipam = getattr(net.spec, "ipam", None)
        subnet = None
        gateway = ""
        if spec_ipam and spec_ipam.configs:
            cfg = spec_ipam.configs[0]
            if cfg.subnet:
                subnet = ipaddress.ip_network(cfg.subnet)
                gateway = cfg.gateway
        taken = list(self.subnets.values())
        if subnet is not None:
            # explicit subnet: reject overlap with any registered network
            if any(subnet.overlaps(sn) for sn in taken):
                raise ValueError(
                    f"subnet {subnet} overlaps an allocated network")
        else:
            for pool in self.pools:
                for cand in pool.subnets(new_prefix=self.subnet_size):
                    if not any(cand.overlaps(sn) for sn in taken):
                        subnet = cand
                        break
                if subnet is not None:
                    break
            if subnet is None:
                raise ValueError("address pool exhausted")
        if not gateway:
            gateway = str(next(subnet.hosts()))
        self.subnets[net.id] = subnet
        used = self._used_ips.setdefault(net.id, set())
        used.add(int(ipaddress.ip_address(gateway)))
        return IPAMOptions(configs=[IPAMConfig(
            subnet=str(subnet), gateway=gateway)])

    def restore_network(self, net: Network) -> None:
        if net.ipam and net.ipam.configs and net.ipam.configs[0].subnet:
            cfg = net.ipam.configs[0]
            self.subnets[net.id] = ipaddress.ip_network(cfg.subnet)
            used = self._used_ips.setdefault(net.id, set())
            if cfg.gateway:
                used.add(int(ipaddress.ip_address(cfg.gateway)))

    def release_network(self, network_id: str) -> None:
        self.subnets.pop(network_id, None)
        self._used_ips.pop(network_id, None)

    # ------------------------------------------------------------ addresses

    def allocate_ip(self, network_id: str) -> str:
        """Next free address in the network's subnet, in CIDR form."""
        subnet = self.subnets.get(network_id)
        if subnet is None:
            raise ValueError(f"network {network_id} has no subnet")
        used = self._used_ips.setdefault(network_id, set())
        first = int(subnet.network_address) + 1
        last = int(subnet.broadcast_address) - 1
        for ip in range(first, last + 1):
            if ip not in used:
                used.add(ip)
                return (f"{ipaddress.ip_address(ip)}"
                        f"/{subnet.prefixlen}")
        raise ValueError(f"subnet {subnet} exhausted")

    def restore_ip(self, network_id: str, addr: str) -> None:
        if not addr:
            return
        used = self._used_ips.setdefault(network_id, set())
        ip = addr.split("/")[0]
        try:
            used.add(int(ipaddress.ip_address(ip)))
        except ValueError:
            pass

    def release_ip(self, network_id: str, addr: str) -> None:
        if not addr:
            return
        used = self._used_ips.get(network_id)
        if used is None:
            return
        try:
            used.discard(
                int(ipaddress.ip_address(addr.split("/")[0])))
        except ValueError:
            pass


class Allocator:
    """Event-loop allocator (reference: allocator.go:82 Run)."""

    def __init__(self, store: MemoryStore,
                 address_pools: Optional[List[str]] = None,
                 subnet_size: int = 24,
                 network_drivers: Optional[NetworkDriverRegistry] = None):
        self.store = store
        self.ports = PortAllocator()
        self.ipam = IPAM(address_pools, subnet_size)
        # pluggable network-driver seam (manager/netdriver.py): the
        # driver named by NetworkSpec.driver_config owns each network's
        # subnet + address lifecycle; the default wraps self.ipam (read
        # through a getter, so _resync's IPAM rebuild stays visible)
        self.net_drivers = network_drivers or NetworkDriverRegistry(
            lambda: self.ipam)
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending_tasks: Dict[str, Task] = {}
        self._pending_services: Dict[str, Service] = {}
        self._pending_networks: Dict[str, Network] = {}

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="allocator",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)

    def run(self) -> None:
        try:
            def init(tx):
                self._restore_ipam(tx)
                for s in tx.find(Service):
                    self.ports.restore(s.endpoint)
                for s in tx.find(Service):
                    if self._service_needs_allocation(s):
                        self._pending_services[s.id] = s
                for t in tx.find(Task):
                    if t.status.state == TaskState.NEW:
                        self._pending_tasks[t.id] = t

            # accepts_blocks: allocation triggers on NEW tasks and
            # deletes; assignment blocks are updates past PENDING
            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                self._tick()
                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, EventCommit):
                        self._tick()
                    elif isinstance(event, EventSnapshotRestore):
                        self._resync()
                    elif isinstance(event, Event):
                        self._handle_event(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _restore_ipam(self, tx) -> None:
        for net in tx.find(Network):
            if net.ipam is not None:
                self.net_drivers.for_network(net).restore_network(net)
            else:
                self._pending_networks[net.id] = net
        drv = self.net_drivers.for_id
        for s in tx.find(Service):
            if s.endpoint is not None:
                for vip in s.endpoint.virtual_ips:
                    drv(vip.network_id).restore_ip(vip.network_id,
                                                   vip.addr)
        for t in tx.find(Task):
            for att in t.networks:
                for addr in att.addresses:
                    drv(att.network_id).restore_ip(att.network_id, addr)

    def _resync(self) -> None:
        self._pending_tasks.clear()
        self._pending_services.clear()
        self._pending_networks.clear()
        self.ports = PortAllocator()
        self.ipam = IPAM([str(p) for p in self.ipam.pools],
                         self.ipam.subnet_size)
        # driver bindings rebuild from the fresh view below (the default
        # driver reads self.ipam through its getter, so the instance
        # swap above is already visible to it)
        self.net_drivers.reset_bindings()

        def init(tx):
            self._restore_ipam(tx)
            for s in tx.find(Service):
                self.ports.restore(s.endpoint)
                if self._service_needs_allocation(s):
                    self._pending_services[s.id] = s
            for t in tx.find(Task):
                if t.status.state == TaskState.NEW:
                    self._pending_tasks[t.id] = t

        self.store.view(init)
        self._tick()

    # ----------------------------------------------------------- event intake

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Task):
            if ev.action == "delete":
                self._pending_tasks.pop(obj.id, None)
                for att in obj.networks:
                    for addr in att.addresses:
                        self.net_drivers.for_id(att.network_id) \
                            .release_ip(att.network_id, addr)
            elif obj.status.state == TaskState.NEW:
                self._pending_tasks[obj.id] = obj
        elif isinstance(obj, Service):
            if ev.action == "delete":
                self.ports.release(obj.endpoint)
                if obj.endpoint is not None:
                    for vip in obj.endpoint.virtual_ips:
                        self.net_drivers.for_id(vip.network_id) \
                            .release_ip(vip.network_id, vip.addr)
                self._pending_services.pop(obj.id, None)
            elif self._service_needs_allocation(obj):
                self._pending_services[obj.id] = obj
        elif isinstance(obj, Network):
            if ev.action == "delete":
                self.net_drivers.release_binding(obj.id) \
                    .release_network(obj.id)
                self._pending_networks.pop(obj.id, None)
            elif obj.ipam is None:
                self._pending_networks[obj.id] = obj

    @staticmethod
    def _service_needs_allocation(s: Service) -> bool:
        spec_ep = s.spec.endpoint
        have_vips = {v.network_id for v in (s.endpoint.virtual_ips
                                            if s.endpoint else [])}
        if s.spec.task.networks or have_vips:
            # target may be a name; distinct-count suffices for the needs
            # check (exact resolution happens at allocation time) — and a
            # spec with NO networks must shed any lingering VIPs
            want = {c.target for c in s.spec.task.networks}
            if len(have_vips) != len(want):
                return True
        if s.endpoint is None:
            return spec_ep is not None
        spec_ports = list(spec_ep.ports) if spec_ep else []
        have_ports = s.endpoint.ports
        if len(spec_ports) != len(have_ports):
            return True
        have_exact = {(p.protocol, p.target_port, p.publish_mode,
                       p.published_port) for p in have_ports}
        have_any = {(p.protocol, p.target_port, p.publish_mode)
                    for p in have_ports}
        for p in spec_ports:
            if p.published_port:
                # user-specified port: the endpoint must carry exactly it
                if (p.protocol, p.target_port, p.publish_mode,
                        p.published_port) not in have_exact:
                    return True
            else:
                # dynamic port: any allocated published port satisfies it
                if (p.protocol, p.target_port,
                        p.publish_mode) not in have_any:
                    return True
        return False

    # ----------------------------------------------------------------- ticks

    def _tick(self) -> None:
        if self._pending_networks:
            networks, self._pending_networks = self._pending_networks, {}
            self._allocate_networks(networks)
        if self._pending_services:
            services, self._pending_services = self._pending_services, {}
            self._allocate_services(services)
        if self._pending_tasks:
            tasks, self._pending_tasks = self._pending_tasks, {}
            self._allocate_tasks(tasks)

    def _allocate_networks(self, networks: Dict[str, Network]) -> None:
        def cb(batch: Batch) -> None:
            for network in networks.values():
                def one(tx, network=network):
                    cur = tx.get(Network, network.id)
                    if cur is None or cur.ipam is not None:
                        return
                    cur = cur.copy()
                    cfg = getattr(cur.spec, "driver_config", None)
                    if cfg and cfg.name \
                            and not self.net_drivers.known(cfg.name):
                        log.warning("network %s names unknown driver "
                                    "%r; using the default IPAM",
                                    network.id, cfg.name)
                    try:
                        cur.ipam = self.net_drivers.for_network(cur) \
                            .allocate_network(cur)
                    except ValueError as e:
                        log.warning("network %s allocation failed: %s",
                                    network.id, e)
                        return
                    tx.update(cur)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("network allocation failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("network allocation batch failed")

    def _resolve_network_ids(self, tx, attachment_configs):
        """Resolve attachment targets (id or name) to allocated network
        ids; returns None if any referenced network has no subnet yet (the
        commit event for its allocation re-triggers the caller)."""
        ids = []
        for cfg in attachment_configs:
            net = tx.get(Network, cfg.target)
            if net is None:
                found = tx.find(Network, ByName(cfg.target))
                net = found[0] if found else None
            if net is None:
                log.warning("unknown network %r referenced", cfg.target)
                return None
            if net.ipam is None:
                return None   # subnet not carved yet
            ids.append(net.id)
        return ids

    def _allocate_services(self, services: Dict[str, Service]) -> None:
        def cb(batch: Batch) -> None:
            for service in services.values():
                def one(tx, service=service):
                    cur = tx.get(Service, service.id)
                    if cur is None or not self._service_needs_allocation(cur):
                        return
                    cur = cur.copy()
                    old_endpoint = cur.endpoint
                    spec_ep = cur.spec.endpoint
                    # release this service's own ports first so keeping a
                    # port across a spec change doesn't self-conflict;
                    # restore them if the new allocation fails
                    self.ports.release(old_endpoint)
                    try:
                        ports = self.ports.allocate(
                            list(spec_ep.ports) if spec_ep else [])
                    except ValueError as e:
                        self.ports.restore(old_endpoint)
                        log.warning("service %s port allocation failed: %s",
                                    service.id, e)
                        return
                    def unwind_ports():
                        # the freshly allocated ports must not stay
                        # registered when we requeue, or retries
                        # self-conflict on fixed ports / leak dynamics
                        self.ports.release(Endpoint(ports=ports))
                        self.ports.restore(old_endpoint)

                    # virtual IPs on every attached network (reference:
                    # allocator/network.go allocateVIPs; VIP mode only).
                    # Duplicate spec entries resolve to one VIP.
                    net_ids = self._resolve_network_ids(
                        tx, cur.spec.task.networks)
                    if net_ids is None and cur.spec.task.networks:
                        unwind_ports()
                        self._pending_services[cur.id] = cur
                        return
                    net_ids = list(dict.fromkeys(net_ids or []))
                    vips = []
                    fresh = []
                    old_vips = {v.network_id: v
                                for v in (old_endpoint.virtual_ips
                                          if old_endpoint else [])}
                    drv = self.net_drivers.for_id
                    try:
                        for nid in net_ids:
                            if nid in old_vips:
                                vips.append(old_vips.pop(nid))
                                continue
                            # VIP row kept even for addressing-free
                            # drivers (addr ""): the needs-allocation
                            # check counts VIPs per network id
                            vip = EndpointVIP(
                                network_id=nid,
                                addr=drv(nid).allocate_ip(nid))
                            vips.append(vip)
                            fresh.append(vip)
                    except ValueError as e:
                        # exhausted subnet: requeue WITHOUT writing a
                        # partial endpoint (a partial write re-triggers
                        # allocation on its own commit — a hot loop)
                        for vip in fresh:
                            drv(vip.network_id).release_ip(
                                vip.network_id, vip.addr)
                        unwind_ports()
                        log.warning("service %s VIP allocation failed: "
                                    "%s", cur.id, e)
                        return
                    for stale in old_vips.values():
                        drv(stale.network_id).release_ip(
                            stale.network_id, stale.addr)
                    if old_endpoint is not None and not old_vips and \
                            [(p.protocol, p.target_port, p.published_port,
                              p.publish_mode) for p in ports] == \
                            [(p.protocol, p.target_port, p.published_port,
                              p.publish_mode)
                             for p in old_endpoint.ports] and \
                            {(v.network_id, v.addr) for v in vips} == \
                            {(v.network_id, v.addr)
                             for v in old_endpoint.virtual_ips}:
                        # nothing actually changed (e.g. the intake
                        # count-check misfires on duplicate name+id
                        # targets): writing an identical endpoint would
                        # re-trigger allocation on its own commit forever
                        return
                    cur.endpoint = Endpoint(
                        spec=spec_ep.copy() if spec_ep else EndpointSpec(),
                        ports=ports, virtual_ips=vips)
                    tx.update(cur)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("service allocation failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("service allocation batch failed")

    def _allocate_tasks(self, tasks: Dict[str, Task]) -> None:
        def cb(batch: Batch) -> None:
            for task in tasks.values():
                def one(tx, task=task):
                    t = tx.get(Task, task.id)
                    if t is None or t.status.state != TaskState.NEW:
                        return
                    t = t.copy()
                    # propagate the service's allocated endpoint so the
                    # scheduler's host-port filter and the agent see ports
                    if t.service_id:
                        service = tx.get(Service, t.service_id)
                        if service is not None:
                            if self._service_needs_allocation(service):
                                # wait for service allocation first; the
                                # commit event will re-trigger us
                                self._pending_tasks[t.id] = t
                                return
                            if service.endpoint is not None:
                                t.endpoint = service.endpoint.copy()
                    # per-task addresses on each attached network
                    # (reference: allocator/network.go allocateTask)
                    net_cfgs = t.spec.networks
                    if net_cfgs and not t.networks:
                        net_ids = self._resolve_network_ids(tx, net_cfgs)
                        if net_ids is None:
                            self._pending_tasks[t.id] = t
                            return
                        pairs = list({nid: (nid, cfg) for nid, cfg in
                                      zip(net_ids, net_cfgs)}.values())
                        attachments = []
                        drv = self.net_drivers.for_id
                        try:
                            for nid, cfg in pairs:
                                addr = drv(nid).allocate_ip(nid)
                                attachments.append(NetworkAttachment(
                                    network_id=nid,
                                    addresses=[addr] if addr else [],
                                    aliases=list(cfg.aliases)))
                        except ValueError as e:
                            for att in attachments:
                                for a in att.addresses:
                                    drv(att.network_id).release_ip(
                                        att.network_id, a)
                            log.warning("task %s address allocation "
                                        "failed: %s", t.id, e)
                            return
                        t.networks = attachments
                    t.status = TaskStatus(
                        state=TaskState.PENDING, timestamp=now(),
                        message=ALLOCATED_STATUS_MESSAGE)
                    tx.update(t)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("task allocation failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("task allocation batch failed")
