"""Allocator: moves tasks NEW → PENDING by allocating their resources.

Reference: manager/allocator/{allocator.go,network.go,portallocator.go}.

The reference's allocator runs a set of sub-allocators (today: network) that
each *vote* on a task; when every registered voter has approved, the task
moves to PENDING with message "pending task scheduling" (allocator.go:38-48,
network.go:770).  Network allocation itself (VIPs, overlay attachments) is a
pluggable driver that lives outside the core in the reference (libnetwork);
here the network layer is the ``Inert`` implementation plus real **ingress
port bookkeeping**: published ports are assigned from the dynamic range
30000-32767 when unspecified, and conflicts are rejected
(portallocator.go:201).

Service allocation materializes ``service.endpoint`` from the endpoint spec;
task allocation copies the service endpoint onto the task so the scheduler's
host-port filter sees published ports.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..models.objects import Network, Service, Task
from ..models.types import (
    Endpoint, PortConfig, PublishMode, TaskState, TaskStatus, now,
)
from ..state.events import Event, EventCommit, EventSnapshotRestore
from ..state.store import Batch, MemoryStore
from ..state.watch import Closed

log = logging.getLogger("allocator")

ALLOCATED_STATUS_MESSAGE = "pending task scheduling"  # network.go:21
DYNAMIC_PORT_START = 30000  # portallocator.go (dynamicPortStart)
DYNAMIC_PORT_END = 32767


class PortAllocator:
    """Ingress published-port bookkeeping (reference: portallocator.go)."""

    def __init__(self) -> None:
        self._allocated: Set[Tuple[int, int]] = set()  # (protocol, port)
        self._next_dynamic = DYNAMIC_PORT_START

    def restore(self, endpoint: Optional[Endpoint]) -> None:
        if endpoint is None:
            return
        for p in endpoint.ports:
            if p.publish_mode == PublishMode.INGRESS and p.published_port:
                self._allocated.add((p.protocol, p.published_port))

    def release(self, endpoint: Optional[Endpoint]) -> None:
        if endpoint is None:
            return
        for p in endpoint.ports:
            if p.publish_mode == PublishMode.INGRESS and p.published_port:
                self._allocated.discard((p.protocol, p.published_port))

    def allocate(self, spec_ports: List[PortConfig]) -> List[PortConfig]:
        """Resolve a port list: keep user-specified ports (conflict =
        error), assign dynamic ports for unspecified ingress publishes."""
        resolved: List[PortConfig] = []
        taken: List[Tuple[int, int]] = []
        try:
            for p in spec_ports:
                if p.publish_mode != PublishMode.INGRESS:
                    resolved.append(p)
                    continue
                if p.published_port:
                    key = (p.protocol, p.published_port)
                    if key in self._allocated:
                        raise ValueError(
                            f"port '{p.published_port}' is already in use "
                            "by service")
                    self._allocated.add(key)
                    taken.append(key)
                    resolved.append(p)
                else:
                    port = self._find_dynamic(p.protocol)
                    key = (p.protocol, port)
                    self._allocated.add(key)
                    taken.append(key)
                    resolved.append(PortConfig(
                        name=p.name, protocol=p.protocol,
                        target_port=p.target_port, published_port=port,
                        publish_mode=p.publish_mode))
            return resolved
        except ValueError:
            for key in taken:
                self._allocated.discard(key)
            raise

    def _find_dynamic(self, protocol: int) -> int:
        for _ in range(DYNAMIC_PORT_END - DYNAMIC_PORT_START + 1):
            port = self._next_dynamic
            self._next_dynamic += 1
            if self._next_dynamic > DYNAMIC_PORT_END:
                self._next_dynamic = DYNAMIC_PORT_START
            if (protocol, port) not in self._allocated:
                return port
        raise ValueError("dynamic port space exhausted")


class Allocator:
    """Event-loop allocator (reference: allocator.go:82 Run)."""

    def __init__(self, store: MemoryStore):
        self.store = store
        self.ports = PortAllocator()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending_tasks: Dict[str, Task] = {}
        self._pending_services: Dict[str, Service] = {}

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="allocator",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=10)

    def run(self) -> None:
        try:
            def init(tx):
                for s in tx.find(Service):
                    self.ports.restore(s.endpoint)
                for s in tx.find(Service):
                    if self._service_needs_allocation(s):
                        self._pending_services[s.id] = s
                for t in tx.find(Task):
                    if t.status.state == TaskState.NEW:
                        self._pending_tasks[t.id] = t

            _, sub = self.store.view_and_watch(init)
            try:
                self._tick()
                while not self._stop.is_set():
                    try:
                        event = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(event, EventCommit):
                        self._tick()
                    elif isinstance(event, EventSnapshotRestore):
                        self._resync()
                    elif isinstance(event, Event):
                        self._handle_event(event)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _resync(self) -> None:
        self._pending_tasks.clear()
        self._pending_services.clear()
        self.ports = PortAllocator()

        def init(tx):
            for s in tx.find(Service):
                self.ports.restore(s.endpoint)
                if self._service_needs_allocation(s):
                    self._pending_services[s.id] = s
            for t in tx.find(Task):
                if t.status.state == TaskState.NEW:
                    self._pending_tasks[t.id] = t

        self.store.view(init)
        self._tick()

    # ----------------------------------------------------------- event intake

    def _handle_event(self, ev: Event) -> None:
        obj = ev.obj
        if isinstance(obj, Task):
            if ev.action == "delete":
                self._pending_tasks.pop(obj.id, None)
            elif obj.status.state == TaskState.NEW:
                self._pending_tasks[obj.id] = obj
        elif isinstance(obj, Service):
            if ev.action == "delete":
                self.ports.release(obj.endpoint)
                self._pending_services.pop(obj.id, None)
            elif self._service_needs_allocation(obj):
                self._pending_services[obj.id] = obj

    @staticmethod
    def _service_needs_allocation(s: Service) -> bool:
        spec_ep = s.spec.endpoint
        if s.endpoint is None:
            return spec_ep is not None
        spec_ports = list(spec_ep.ports) if spec_ep else []
        have_ports = s.endpoint.ports
        if len(spec_ports) != len(have_ports):
            return True
        have_exact = {(p.protocol, p.target_port, p.publish_mode,
                       p.published_port) for p in have_ports}
        have_any = {(p.protocol, p.target_port, p.publish_mode)
                    for p in have_ports}
        for p in spec_ports:
            if p.published_port:
                # user-specified port: the endpoint must carry exactly it
                if (p.protocol, p.target_port, p.publish_mode,
                        p.published_port) not in have_exact:
                    return True
            else:
                # dynamic port: any allocated published port satisfies it
                if (p.protocol, p.target_port,
                        p.publish_mode) not in have_any:
                    return True
        return False

    # ----------------------------------------------------------------- ticks

    def _tick(self) -> None:
        if self._pending_services:
            services, self._pending_services = self._pending_services, {}
            self._allocate_services(services)
        if self._pending_tasks:
            tasks, self._pending_tasks = self._pending_tasks, {}
            self._allocate_tasks(tasks)

    def _allocate_services(self, services: Dict[str, Service]) -> None:
        def cb(batch: Batch) -> None:
            for service in services.values():
                def one(tx, service=service):
                    cur = tx.get(Service, service.id)
                    if cur is None or not self._service_needs_allocation(cur):
                        return
                    cur = cur.copy()
                    old_endpoint = cur.endpoint
                    spec_ep = cur.spec.endpoint
                    # release this service's own ports first so keeping a
                    # port across a spec change doesn't self-conflict;
                    # restore them if the new allocation fails
                    self.ports.release(old_endpoint)
                    try:
                        ports = self.ports.allocate(
                            list(spec_ep.ports) if spec_ep else [])
                    except ValueError as e:
                        self.ports.restore(old_endpoint)
                        log.warning("service %s port allocation failed: %s",
                                    service.id, e)
                        return
                    cur.endpoint = Endpoint(
                        spec=spec_ep.copy() if spec_ep else None,
                        ports=ports)
                    tx.update(cur)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("service allocation failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("service allocation batch failed")

    def _allocate_tasks(self, tasks: Dict[str, Task]) -> None:
        def cb(batch: Batch) -> None:
            for task in tasks.values():
                def one(tx, task=task):
                    t = tx.get(Task, task.id)
                    if t is None or t.status.state != TaskState.NEW:
                        return
                    t = t.copy()
                    # propagate the service's allocated endpoint so the
                    # scheduler's host-port filter and the agent see ports
                    if t.service_id:
                        service = tx.get(Service, t.service_id)
                        if service is not None:
                            if self._service_needs_allocation(service):
                                # wait for service allocation first; the
                                # commit event will re-trigger us
                                self._pending_tasks[t.id] = t
                                return
                            if service.endpoint is not None:
                                t.endpoint = service.endpoint.copy()
                    t.status = TaskStatus(
                        state=TaskState.PENDING, timestamp=now(),
                        message=ALLOCATED_STATUS_MESSAGE)
                    tx.update(t)
                try:
                    batch.update(one)
                except Exception:
                    log.exception("task allocation failed")

        try:
            self.store.batch(cb)
        except Exception:
            log.exception("task allocation batch failed")
