"""Control API: user-facing validated CRUD for every cluster object.

Reference: manager/controlapi/{service,node,secret,config,network,cluster}.go.

Host-callable server object (a gRPC layer can wrap it 1:1).  Validation
messages match the reference byte-for-byte where tests assert on them.
Errors carry gRPC-style codes via exception types: InvalidArgument /
NotFound / AlreadyExists / FailedPrecondition.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from ..models.objects import (
    Cluster, Config, Extension, Network, Node, Resource, Secret, Service,
    Task, Volume,
)
from ..models.specs import (
    ConfigSpec, NetworkSpec, NodeSpec, SecretSpec, ServiceMode, ServiceSpec,
    VolumeSpec,
)
from ..models.types import (
    EndpointResolutionMode, NodeRole, PublishMode, TaskState, Version, now,
)
from ..scheduler import constraint as constraint_mod
from ..scheduler import strategy as strategy_mod
from ..state.store import (
    AlreadyExists as StoreExists, ByKind, ByName, ByNamePrefix,
    ByReferencedSecret, ByReferencedConfig, MemoryStore, NameConflict,
    NotFound as StoreNotFound, SequenceConflict,
)
from ..utils import new_id


class APIError(Exception):
    code = "unknown"


class InvalidArgument(APIError):
    code = "invalid_argument"


class NotFound(APIError):
    code = "not_found"


class AlreadyExists(APIError):
    code = "already_exists"


class FailedPrecondition(APIError):
    code = "failed_precondition"


# reference: manager/controlapi/common.go isValidDNSName
_DNS_NAME = re.compile(r"^[a-zA-Z0-9](?:[-a-zA-Z0-9]*[a-zA-Z0-9])?$")
_SECRET_NAME = re.compile(r"^[a-zA-Z0-9]+(?:[a-zA-Z0-9-_.]*[a-zA-Z0-9])?$")

MAX_SECRET_SIZE = 500 * 1024  # reference: api/validation/secrets.go


def _validate_annotations(ann) -> None:
    if not ann.name:
        raise InvalidArgument("meta: name must be provided")
    if not _DNS_NAME.match(ann.name):
        raise InvalidArgument("name must be valid as a DNS name component")
    if len(ann.name) > 63:
        raise InvalidArgument("name must be 63 characters or fewer")


def _stripped_secret(secret):
    """API-response projection of a secret: the payload never leaves the
    manager — every secret-returning endpoint redacts through this one
    point (reference: secret.go:44,87,143,175)."""
    s = secret.copy()
    s.spec.data = b""
    return s


def _redacted_cluster(cluster):
    """API-response projection of a cluster: signing keys and unlock
    keys never leave the manager (reference: controlapi/cluster.go:252
    redactClusters — strips Spec.CAConfig.SigningCAKey/SigningCACert,
    RootCA.CAKey, RootRotation.CAKey, and omits UnlockKeys and
    NetworkBootstrapKeys; join tokens stay — they're operator-facing)."""
    c = cluster.copy()
    c.spec.ca_config.signing_ca_key = b""
    c.spec.ca_config.signing_ca_cert = b""
    if c.root_ca is not None:
        c.root_ca.ca_key = b""
        c.root_ca.rotation_ca_key = b""
    c.unlock_keys = []
    c.network_bootstrap_keys = []
    return c


def _validate_secret_annotations(ann) -> None:
    if not ann.name:
        raise InvalidArgument("name must be provided")
    if len(ann.name) > 64 or not _SECRET_NAME.match(ann.name):
        raise InvalidArgument(
            "invalid name, only 64 [a-zA-Z0-9-_.] characters allowed, "
            "and the start and end character must be [a-zA-Z0-9]")


def _validate_resources(r) -> None:
    if r is None:
        return
    if r.nano_cpus != 0 and r.nano_cpus < 1e6:
        raise InvalidArgument(
            f"invalid cpu value {r.nano_cpus / 1e9:g}: "
            f"Must be at least {1e6 / 1e9:g}")
    if r.memory_bytes != 0 and r.memory_bytes < 4 * 1024 * 1024:
        raise InvalidArgument(
            f"invalid memory value {r.memory_bytes}: Must be at least 4MiB")


def _validate_task_spec(task_spec) -> None:
    if task_spec.resources is not None:
        _validate_resources(task_spec.resources.limits)
        _validate_resources(task_spec.resources.reservations)
    rp = task_spec.restart
    if rp is not None:
        if rp.delay < 0:
            raise InvalidArgument("TaskSpec: restart-delay cannot be negative")
        if rp.window < 0:
            raise InvalidArgument(
                "TaskSpec: restart-window cannot be negative")
    placement = task_spec.placement
    if placement is not None and placement.constraints:
        try:
            constraint_mod.parse(placement.constraints)
        except constraint_mod.InvalidConstraint as e:
            raise InvalidArgument(str(e))
    if placement is not None:
        name = (placement.strategy or "").lower()
        if name and strategy_mod.resolve(name) is None:
            raise InvalidArgument(
                f"Placement: unknown placement_strategy {name!r} "
                f"(known: {', '.join(sorted(strategy_mod.REGISTRY))})")
        for key, val in (placement.strategy_weights or {}).items():
            if key not in strategy_mod.WEIGHT_KEYS:
                raise InvalidArgument(
                    f"Placement: unknown strategy weight {key!r} "
                    f"(known: {', '.join(strategy_mod.WEIGHT_KEYS)})")
            if not isinstance(val, int) or isinstance(val, bool) \
                    or not 0 <= val <= strategy_mod.W_CLAMP:
                raise InvalidArgument(
                    f"Placement: strategy weight {key!r} must be an "
                    f"integer in [0, {strategy_mod.W_CLAMP}]")
        gang = placement.gang
        if gang is not None:
            if not isinstance(gang.min_size, int) \
                    or isinstance(gang.min_size, bool) \
                    or gang.min_size < 0:
                raise InvalidArgument(
                    "Placement: gang min_size must be a non-negative "
                    "integer")
    c = task_spec.container
    if c is None and task_spec.generic_runtime is None \
            and task_spec.attachment is None:
        raise InvalidArgument("TaskSpec: missing runtime")
    if c is not None:
        if not c.image:
            raise InvalidArgument(
                "ContainerSpec: image reference must be provided")
        mounts = {}
        for m in c.mounts:
            if m.target in mounts:
                raise InvalidArgument(
                    f"ContainerSpec: duplicate mount point: {m.target}")
            mounts[m.target] = m
        targets = {}
        for ref in c.secrets:
            if not ref.secret_id or not ref.secret_name:
                raise InvalidArgument("malformed secret reference")
            if not ref.target:
                raise InvalidArgument(
                    "malformed secret reference, no target provided")
            prev = targets.get(ref.target)
            if prev is not None:
                raise InvalidArgument(
                    f"secret references '{prev}' and '{ref.secret_name}' "
                    f"have a conflicting target: '{ref.target}'")
            targets[ref.target] = ref.secret_name
        targets = {}
        for ref in c.configs:
            if not ref.config_id or not ref.config_name:
                raise InvalidArgument("malformed config reference")
            if not ref.target:
                raise InvalidArgument(
                    "malformed config reference, no target provided")
            prev = targets.get(ref.target)
            if prev is not None:
                raise InvalidArgument(
                    f"config references '{prev}' and '{ref.config_name}' "
                    f"have a conflicting target: '{ref.target}'")
            targets[ref.target] = ref.config_name


def _validate_update(uc) -> None:
    if uc is None:
        return
    if uc.parallelism < 0:
        raise InvalidArgument(
            "TaskSpec: update-parallelism cannot be negative")
    if uc.delay < 0:
        raise InvalidArgument("TaskSpec: update-delay cannot be negative")
    if uc.monitor < 0:
        raise InvalidArgument("TaskSpec: update-monitor cannot be negative")
    if uc.max_failure_ratio < 0 or uc.max_failure_ratio > 1:
        raise InvalidArgument(
            "TaskSpec: update-maxfailureratio cannot be less than 0 "
            "or bigger than 1")


def _validate_endpoint_spec(ep_spec) -> None:
    if ep_spec is None:
        return
    port_set = set()
    for p in ep_spec.ports:
        if p.publish_mode == PublishMode.INGRESS \
                and ep_spec.mode == EndpointResolutionMode.DNSRR \
                and p.published_port:
            raise InvalidArgument(
                "EndpointSpec: port published with ingress mode can't be "
                "used with dnsrr mode")
        key = (p.protocol, p.target_port, p.published_port)
        if key in port_set:
            raise InvalidArgument(
                "EndpointSpec: duplicate published ports provided")
        port_set.add(key)


def _validate_mode(spec: ServiceSpec) -> None:
    if spec.mode == ServiceMode.REPLICATED:
        if spec.replicated is not None and spec.replicated.replicas < 0:
            raise InvalidArgument("Number of replicas must be non-negative")
        if spec.task.restart is not None:
            pass
    elif spec.mode in (ServiceMode.REPLICATED_JOB, ServiceMode.GLOBAL_JOB):
        if spec.update is not None:
            raise InvalidArgument(
                "job-mode services cannot have update options")


def _normalized_service_spec(spec: ServiceSpec) -> ServiceSpec:
    """Private normalized copy of a validated spec.  REPLICATED_JOB
    defaults max_concurrent to total_completions (like the docker CLI)
    so DesiredTasks can report MaxConcurrent directly, matching
    reference ListServiceStatuses (controlapi/service.go:1086).
    Applied on create AND update so stored specs are always normalized."""
    spec = spec.copy()
    if spec.mode == ServiceMode.REPLICATED_JOB \
            and spec.replicated_job is not None \
            and not spec.replicated_job.max_concurrent:
        spec.replicated_job.max_concurrent = \
            spec.replicated_job.total_completions
    return spec


def validate_service_spec(spec: Optional[ServiceSpec]) -> None:
    """reference: service.go:527 validateServiceSpec."""
    if spec is None:
        raise InvalidArgument("invalid argument")
    _validate_annotations(spec.annotations)
    _validate_task_spec(spec.task)
    _validate_mode(spec)
    if spec.mode not in (ServiceMode.REPLICATED_JOB, ServiceMode.GLOBAL_JOB):
        _validate_update(spec.update)
    _validate_endpoint_spec(spec.endpoint)
    # pipeline DAG edges: local shape checks here; the cross-service
    # cycle walk needs the store (ControlAPI._check_dependency_cycles)
    name = spec.annotations.name
    for dep in spec.depends_on or []:
        if not dep:
            raise InvalidArgument(
                "ServiceSpec: depends_on entries must be non-empty "
                "service names")
        if dep == name:
            raise InvalidArgument(
                f'ServiceSpec: service "{name}" cannot depend on itself')
    if spec.on_upstream_failure not in ("", "halt", "rollback"):
        raise InvalidArgument(
            f"ServiceSpec: unknown on_upstream_failure "
            f"{spec.on_upstream_failure!r} (known: halt, rollback)")


class ControlAPI:
    def __init__(self, store: MemoryStore):
        self.store = store

    # ------------------------------------------------------------- services

    def _check_port_conflicts(self, spec: ServiceSpec,
                              service_id: str) -> None:
        """reference: service.go:570 checkPortConflicts."""
        if spec.endpoint is None:
            return
        ingress, host = set(), set()
        for p in spec.endpoint.ports:
            if not p.published_port:
                continue
            key = (p.protocol, p.published_port)
            if p.publish_mode == PublishMode.INGRESS:
                ingress.add(key)
            elif p.publish_mode == PublishMode.HOST:
                host.add(key)
        if not ingress and not host:
            return

        def in_use(p, service):
            if not p.published_port:
                return
            key = (p.protocol, p.published_port)
            name = service.spec.annotations.name
            if p.publish_mode == PublishMode.HOST:
                if key in ingress:
                    raise InvalidArgument(
                        f"port '{p.published_port}' is already in use by "
                        f"service '{name}' ({service.id}) as a "
                        "host-published port")
            elif p.publish_mode == PublishMode.INGRESS:
                if key in ingress or key in host:
                    raise InvalidArgument(
                        f"port '{p.published_port}' is already in use by "
                        f"service '{name}' ({service.id}) as an ingress "
                        "port")

        for service in self.store.view(lambda tx: tx.find(Service)):
            if service_id and service.id == service_id:
                continue
            if service.spec.endpoint is not None:
                for p in service.spec.endpoint.ports:
                    in_use(p, service)
            if service.endpoint is not None:
                for p in service.endpoint.ports:
                    in_use(p, service)

    def _check_dependency_cycles(self, spec: ServiceSpec,
                                 service_id: str) -> None:
        """Reject a depends_on edge set that would close a cycle through
        the existing services — pipeline DAGs must stay acyclic
        (orchestrator/pipeline.py walks them assuming so).  Edges to
        not-yet-created services are allowed (forward references; the
        gate fails safe while the upstream is absent)."""
        if not spec.depends_on:
            return
        edges: Dict[str, List[str]] = {}
        for service in self.store.view(lambda tx: tx.find(Service)):
            if service_id and service.id == service_id:
                continue
            edges[service.spec.annotations.name] = \
                list(service.spec.depends_on or [])
        name = spec.annotations.name
        edges[name] = list(spec.depends_on)
        path: List[str] = []
        on_path = set()

        def visit(n: str) -> None:
            if n in on_path:
                cycle = path[path.index(n):] + [n]
                raise InvalidArgument(
                    "ServiceSpec: depends_on cycle: "
                    + " -> ".join(cycle))
            if n not in edges:
                return
            path.append(n)
            on_path.add(n)
            for up in edges[n]:
                visit(up)
            on_path.discard(n)
            path.pop()

        visit(name)

    def _check_secret_existence(self, tx, spec: ServiceSpec) -> None:
        c = spec.task.container
        if c is None:
            return
        failed = []
        for ref in c.secrets:
            secret = tx.get(Secret, ref.secret_id)
            if secret is None or \
                    secret.spec.annotations.name != ref.secret_name:
                failed.append(ref.secret_name)
        if failed:
            word = "secret" if len(failed) == 1 else "secrets"
            raise InvalidArgument(f"{word} not found: {', '.join(failed)}")

    def _check_config_existence(self, tx, spec: ServiceSpec) -> None:
        c = spec.task.container
        if c is None:
            return
        failed = []
        for ref in c.configs:
            config = tx.get(Config, ref.config_id)
            if config is None or \
                    config.spec.annotations.name != ref.config_name:
                failed.append(ref.config_name)
        if failed:
            word = "config" if len(failed) == 1 else "configs"
            raise InvalidArgument(f"{word} not found: {', '.join(failed)}")

    def create_service(self, spec: ServiceSpec) -> Service:
        """reference: service.go:727 CreateService."""
        validate_service_spec(spec)
        self._check_port_conflicts(spec, "")
        self._check_dependency_cycles(spec, "")
        spec = _normalized_service_spec(spec)
        service = Service(id=new_id(), spec=spec,
                          spec_version=Version(index=1))

        def cb(tx):
            self._check_secret_existence(tx, spec)
            self._check_config_existence(tx, spec)
            tx.create(service)

        try:
            self.store.update(cb)
        except NameConflict:
            raise AlreadyExists(
                f"service {spec.annotations.name} already exists")
        return self.store.view(lambda tx: tx.get(Service, service.id))

    def get_service(self, service_id: str) -> Service:
        s = self.store.view(lambda tx: tx.get(Service, service_id))
        if s is None:
            raise NotFound(f"service {service_id} not found")
        return s

    def update_service(self, service_id: str, version: int,
                       spec: ServiceSpec, rollback: bool = False) -> Service:
        """reference: service.go:817 UpdateService."""
        validate_service_spec(spec)
        self._check_port_conflicts(spec, service_id)
        self._check_dependency_cycles(spec, service_id)

        def cb(tx):
            service = tx.get(Service, service_id)
            if service is None:
                raise NotFound(f"service {service_id} not found")
            if spec.annotations.name != service.spec.annotations.name:
                raise InvalidArgument("renaming services is not supported")
            if spec.mode != service.spec.mode:
                raise InvalidArgument("service mode change is not allowed")
            self._check_secret_existence(tx, spec)
            self._check_config_existence(tx, spec)
            service = service.copy()
            service.meta.version.index = version
            service.previous_spec = service.spec
            service.previous_spec_version = service.spec_version
            service.spec = _normalized_service_spec(spec)
            service.spec_version = Version(index=self.store.version + 1)
            service.update_status = None
            tx.update(service)
            return service

        try:
            updated = self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))
        return self.store.view(lambda tx: tx.get(Service, updated.id))

    def remove_service(self, service_id: str) -> None:
        def cb(tx):
            if tx.get(Service, service_id) is None:
                raise NotFound(f"service {service_id} not found")
            tx.delete(Service, service_id)

        self.store.update(cb)

    def resume_pipeline(self, service_id: str) -> Service:
        """Operator restart for a halted pipeline stage (the sticky
        halt's one legitimate exit): flips the verdict back to
        "waiting" and resets the poison ledger of the stage AND its
        direct upstreams, stamping ``resumed_at`` so every failure
        observed at/before the resume is forgiven — the poison the
        operator just fixed cannot re-trip the threshold.  Replicas
        zeroed by a rollback halt are NOT restored (rescale
        explicitly); an upstream stage that is itself halted must be
        resumed separately, bottom-up."""
        from ..models.objects import PipelineStatus

        def cb(tx):
            svc = tx.get(Service, service_id)
            if svc is None:
                raise NotFound(f"service {service_id} not found")
            if not svc.spec.depends_on:
                raise FailedPrecondition(
                    f"service {service_id} is not a pipeline stage")
            st = svc.pipeline_status
            state = st.state if st is not None else "waiting"
            if state != "halted":
                raise FailedPrecondition(
                    f'pipeline stage {service_id} is not halted '
                    f'(state "{state}")')
            stamp = now()
            svc = svc.copy()
            svc.pipeline_status = PipelineStatus(
                state="waiting", reason="", updated_at=stamp,
                failed_ids=[], resumed_at=stamp)
            tx.update(svc)
            for dep in svc.spec.depends_on:
                for up in tx.find(Service, ByName(dep)):
                    up = up.copy()
                    up_st = up.pipeline_status
                    up.pipeline_status = PipelineStatus(
                        state=up_st.state if up_st else "waiting",
                        reason=up_st.reason if up_st else "",
                        updated_at=stamp, failed_ids=[],
                        resumed_at=stamp)
                    tx.update(up)

        self.store.update(cb)
        return self.store.view(lambda tx: tx.get(Service, service_id))

    def list_services(self, name_prefix: str = "") -> List[Service]:
        from ..state.store import All, ByNamePrefix
        by = ByNamePrefix(name_prefix) if name_prefix else All()
        return self.store.view(lambda tx: tx.find(Service, by))

    def list_service_statuses(self, service_ids: List[str]) -> List[dict]:
        """Per-service desired/running(/completed) task counts — the
        `service ls` helper (reference: manager/controlapi/service.go:1047
        ListServiceStatuses).  Unknown service ids return zeroed statuses,
        matching the reference; deleted services with surviving tasks
        count 0 desired."""
        from ..models import ServiceMode, TaskState
        from ..state.store import ByService

        def cb(tx):
            out = []
            for sid in service_ids:
                status = {"service_id": sid, "desired_tasks": 0,
                          "running_tasks": 0, "completed_tasks": 0}
                out.append(status)
                svc = tx.get(Service, sid)
                global_ = False
                job_iteration = None
                if svc is not None:
                    mode = svc.spec.mode
                    if mode == ServiceMode.REPLICATED:
                        status["desired_tasks"] = (
                            svc.spec.replicated.replicas
                            if svc.spec.replicated else 1)
                    elif mode == ServiceMode.REPLICATED_JOB:
                        # MaxConcurrent alone, matching reference
                        # ListServiceStatuses (controlapi/service.go);
                        # total_completions is not a desired-slot count
                        job = svc.spec.replicated_job
                        status["desired_tasks"] = (
                            job.max_concurrent if job else 0)
                    else:
                        global_ = True
                    if svc.job_status is not None:
                        job_iteration = svc.job_status.job_iteration.index
                for t in tx.find(Task, ByService(sid)):
                    if job_iteration is not None:
                        if (t.job_iteration is None
                                or t.job_iteration.index != job_iteration):
                            continue
                        if t.status.state == TaskState.COMPLETE:
                            status["completed_tasks"] += 1
                    if t.status.state == TaskState.RUNNING:
                        status["running_tasks"] += 1
                    if global_ and t.desired_state == TaskState.RUNNING:
                        status["desired_tasks"] += 1
                    if (global_
                            and t.status.state != TaskState.COMPLETE
                            and t.desired_state == TaskState.COMPLETE):
                        status["desired_tasks"] += 1
            return out

        return self.store.view(cb)

    # ---------------------------------------------------------------- nodes

    def get_node(self, node_id: str) -> Node:
        n = self.store.view(lambda tx: tx.get(Node, node_id))
        if n is None:
            raise NotFound(f"node {node_id} not found")
        return n

    def list_nodes(self) -> List[Node]:
        return self.store.view(lambda tx: tx.find(Node))

    def update_node(self, node_id: str, version: int,
                    spec: NodeSpec) -> Node:
        """reference: node.go:203 UpdateNode."""
        def cb(tx):
            node = tx.get(Node, node_id)
            if node is None:
                raise NotFound(f"node {node_id} not found")
            if spec.desired_role != node.spec.desired_role \
                    and node.spec.desired_role == NodeRole.MANAGER:
                managers = [n for n in tx.find(Node)
                            if n.spec.desired_role == NodeRole.MANAGER]
                if len(managers) <= 1:
                    raise FailedPrecondition(
                        "attempting to demote the last manager of the swarm")
            node = node.copy()
            node.meta.version.index = version
            node.spec = spec.copy()
            tx.update(node)
            return node

        try:
            updated = self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))
        return self.store.view(lambda tx: tx.get(Node, updated.id))

    def remove_node(self, node_id: str, force: bool = False) -> None:
        """reference: node.go:294 RemoveNode."""
        from ..models.types import NodeState

        def cb(tx):
            node = tx.get(Node, node_id)
            if node is None:
                raise NotFound(f"node {node_id} not found")
            if not force:
                if node.spec.desired_role == NodeRole.MANAGER:
                    raise FailedPrecondition(
                        f"node {node_id} is a cluster manager and is a "
                        "member of the raft cluster. It must be demoted to "
                        "worker before removal")
                if node.status.state != NodeState.DOWN:
                    raise FailedPrecondition(
                        f"node {node_id} is not down and can't be removed")
            tx.delete(Node, node_id)

        self.store.update(cb)

    # --------------------------------------------------------------- secrets

    def create_secret(self, spec: SecretSpec) -> Secret:
        _validate_secret_annotations(spec.annotations)
        if spec.driver is not None and spec.driver.name:
            # driver-backed secrets carry no payload — the value comes
            # from the provider plugin at assignment time
            # (reference: secret.go:251 validateSecretSpec driver branch)
            if spec.data:
                raise InvalidArgument(
                    "driver-backed secrets must not carry data")
        elif not spec.data or len(spec.data) >= MAX_SECRET_SIZE:
            raise InvalidArgument(
                f"secret data must be larger than 0 and less than "
                f"{MAX_SECRET_SIZE} bytes")
        secret = Secret(id=new_id(), spec=spec.copy())
        try:
            self.store.update(lambda tx: tx.create(secret))
        except NameConflict:
            raise AlreadyExists(
                f"secret {spec.annotations.name} already exists")
        return _stripped_secret(
            self.store.view(lambda tx: tx.get(Secret, secret.id)))

    def get_secret(self, secret_id: str) -> Secret:
        s = self.store.view(lambda tx: tx.get(Secret, secret_id))
        if s is None:
            raise NotFound(f"secret {secret_id} not found")
        return _stripped_secret(s)

    def update_secret(self, secret_id: str, version: int,
                      spec: SecretSpec) -> Secret:
        def cb(tx):
            secret = tx.get(Secret, secret_id)
            if secret is None:
                raise NotFound(f"secret {secret_id} not found")
            if spec.annotations.name != secret.spec.annotations.name \
                    or (spec.data and spec.data != secret.spec.data):
                raise InvalidArgument("only updates to Labels are allowed")
            secret = secret.copy()
            secret.meta.version.index = version
            secret.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(secret)
            return secret

        try:
            return _stripped_secret(self.store.update(cb))
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))

    def remove_secret(self, secret_id: str) -> None:
        def check(tx):
            secret = tx.get(Secret, secret_id)
            if secret is None:
                raise NotFound(f"secret {secret_id} not found")
            return secret, tx.find(Task, ByReferencedSecret(secret_id))

        secret, tasks = self.store.view(check)
        services = sorted({t.service_annotations.name for t in tasks
                           if t.service_id})
        if services:
            word = "service" if len(services) == 1 else "services"
            raise InvalidArgument(
                f"secret '{secret.spec.annotations.name}' is in use by the "
                f"following {word}: {', '.join(services)}")

        def cb(tx):
            if tx.get(Secret, secret_id) is None:
                raise NotFound(f"secret {secret_id} not found")
            tx.delete(Secret, secret_id)

        self.store.update(cb)

    def list_secrets(self) -> List[Secret]:
        secrets = self.store.view(lambda tx: tx.find(Secret))
        return [_stripped_secret(s) for s in secrets]

    # --------------------------------------------------------------- configs

    def create_config(self, spec: ConfigSpec) -> Config:
        _validate_secret_annotations(spec.annotations)
        if not spec.data or len(spec.data) >= MAX_SECRET_SIZE:
            raise InvalidArgument(
                f"config data must be larger than 0 and less than "
                f"{MAX_SECRET_SIZE} bytes")
        config = Config(id=new_id(), spec=spec.copy())
        try:
            self.store.update(lambda tx: tx.create(config))
        except NameConflict:
            raise AlreadyExists(
                f"config {spec.annotations.name} already exists")
        return self.store.view(lambda tx: tx.get(Config, config.id))

    def get_config(self, config_id: str) -> Config:
        c = self.store.view(lambda tx: tx.get(Config, config_id))
        if c is None:
            raise NotFound(f"config {config_id} not found")
        return c

    def update_config(self, config_id: str, version: int,
                      spec: ConfigSpec) -> Config:
        def cb(tx):
            config = tx.get(Config, config_id)
            if config is None:
                raise NotFound(f"config {config_id} not found")
            if spec.annotations.name != config.spec.annotations.name \
                    or (spec.data and spec.data != config.spec.data):
                raise InvalidArgument("only updates to Labels are allowed")
            config = config.copy()
            config.meta.version.index = version
            config.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(config)
            return config

        try:
            return self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))

    def remove_config(self, config_id: str) -> None:
        def check(tx):
            config = tx.get(Config, config_id)
            if config is None:
                raise NotFound(f"config {config_id} not found")
            return config, tx.find(Task, ByReferencedConfig(config_id))

        config, tasks = self.store.view(check)
        services = sorted({t.service_annotations.name for t in tasks
                           if t.service_id})
        if services:
            word = "service" if len(services) == 1 else "services"
            raise InvalidArgument(
                f"config '{config.spec.annotations.name}' is in use by the "
                f"following {word}: {', '.join(services)}")

        def cb(tx):
            if tx.get(Config, config_id) is None:
                raise NotFound(f"config {config_id} not found")
            tx.delete(Config, config_id)

        self.store.update(cb)

    def list_configs(self) -> List[Config]:
        return self.store.view(lambda tx: tx.find(Config))

    # -------------------------------------------------------------- networks

    def create_network(self, spec: NetworkSpec) -> Network:
        _validate_annotations(spec.annotations)
        network = Network(id=new_id(), spec=spec.copy())
        try:
            self.store.update(lambda tx: tx.create(network))
        except NameConflict:
            raise AlreadyExists(
                f"network {spec.annotations.name} already exists")
        return self.store.view(lambda tx: tx.get(Network, network.id))

    def get_network(self, network_id: str) -> Network:
        n = self.store.view(lambda tx: tx.get(Network, network_id))
        if n is None:
            raise NotFound(f"network {network_id} not found")
        return n

    def remove_network(self, network_id: str) -> None:
        from ..state.store import ByReferencedNetwork

        def check(tx):
            network = tx.get(Network, network_id)
            if network is None:
                raise NotFound(f"network {network_id} not found")
            return tx.find(Service, ByReferencedNetwork(network_id))

        services = self.store.view(check)
        if services:
            raise FailedPrecondition(
                f"network {network_id} is in use by service "
                f"{services[0].id}")

        def cb(tx):
            if tx.get(Network, network_id) is None:
                raise NotFound(f"network {network_id} not found")
            tx.delete(Network, network_id)

        self.store.update(cb)

    def list_networks(self) -> List[Network]:
        return self.store.view(lambda tx: tx.find(Network))

    # --------------------------------------------------------------- cluster

    def get_cluster(self, cluster_id: str) -> Cluster:
        c = self.store.view(lambda tx: tx.get(Cluster, cluster_id))
        if c is None:
            raise NotFound(f"cluster {cluster_id} not found")
        return _redacted_cluster(c)

    def list_clusters(self) -> List[Cluster]:
        """reference: manager/controlapi/cluster.go ListClusters."""
        return [_redacted_cluster(c)
                for c in self.store.view(lambda tx: tx.find(Cluster))]

    def get_default_cluster(self) -> Cluster:
        return _redacted_cluster(self._default_cluster_raw())

    def _default_cluster_raw(self) -> Cluster:
        """Unredacted default cluster, for in-process callers that need
        key material (autolock, unlock-key); never served over the wire."""
        clusters = self.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))
        if not clusters:
            raise NotFound("default cluster not found")
        return clusters[0]

    def update_cluster(self, cluster_id: str, version: int, spec) -> Cluster:
        def cb(tx):
            cluster = tx.get(Cluster, cluster_id)
            if cluster is None:
                raise NotFound(f"cluster {cluster_id} not found")
            cluster = cluster.copy()
            cluster.meta.version.index = version
            new_spec = spec.copy()
            # redacted inspect→update round trips blank the signing CA
            # material; empty means "keep current", never "clear"
            # (reference: controlapi/cluster.go redaction note)
            if not new_spec.ca_config.signing_ca_key:
                new_spec.ca_config.signing_ca_key = \
                    cluster.spec.ca_config.signing_ca_key
            if not new_spec.ca_config.signing_ca_cert:
                new_spec.ca_config.signing_ca_cert = \
                    cluster.spec.ca_config.signing_ca_cert
            cluster.spec = new_spec
            tx.update(cluster)
            return cluster

        try:
            return self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))

    # ---------------------------------------------------------------- volumes

    def create_volume(self, spec: VolumeSpec) -> Volume:
        """reference: manager/controlapi/volume.go:15 CreateVolume."""
        if spec is None:
            raise InvalidArgument("spec must not be nil")
        if spec.driver is None or not spec.driver.name:
            raise InvalidArgument("driver must be specified")
        if not spec.annotations.name:
            raise InvalidArgument("meta: name must be provided")
        if spec.access_mode is None:
            raise InvalidArgument("AccessMode must not be nil")
        volume = Volume(id=new_id(), spec=spec.copy())

        def cb(tx):
            # report ALL missing secrets, not just the first
            # (volume.go:41-60)
            missing = [sid for sid in volume.spec.secrets.values()
                       if tx.get(Secret, sid) is None]
            if missing:
                noun = "secret" if len(missing) == 1 else "secrets"
                raise InvalidArgument(
                    f"{noun} not found: {', '.join(missing)}")
            tx.create(volume)

        try:
            self.store.update(cb)
        except NameConflict:
            raise AlreadyExists(
                f"volume {spec.annotations.name} already exists")
        return self.get_volume(volume.id)

    def get_volume(self, volume_id: str) -> Volume:
        v = self.store.view(lambda tx: tx.get(Volume, volume_id))
        if v is None:
            raise NotFound(f"volume {volume_id} not found")
        return v

    def update_volume(self, volume_id: str, version: int,
                      spec: VolumeSpec) -> Volume:
        """Only labels and availability are mutable
        (reference: volume.go:73 UpdateVolume)."""
        def cb(tx):
            v = tx.get(Volume, volume_id)
            if v is None:
                raise NotFound(f"volume {volume_id} not found")
            old = v.spec
            if spec.annotations.name != old.annotations.name:
                raise InvalidArgument("Name cannot be updated")
            if spec.group != old.group:
                raise InvalidArgument("Group cannot be updated")
            if spec.accessibility_requirements != \
                    old.accessibility_requirements:
                raise InvalidArgument(
                    "AccessibilityRequirements cannot be updated")
            if spec.driver != old.driver:
                raise InvalidArgument("Driver cannot be updated")
            if spec.access_mode != old.access_mode:
                raise InvalidArgument("AccessMode cannot be updated")
            if spec.secrets != old.secrets:
                raise InvalidArgument("Secrets cannot be updated")
            if (spec.capacity_min, spec.capacity_max) != \
                    (old.capacity_min, old.capacity_max):
                raise InvalidArgument("CapacityRange cannot be updated")
            v = v.copy()
            # replace only the mutable fields, never the whole spec
            v.spec.annotations.labels = dict(spec.annotations.labels)
            v.spec.availability = spec.availability
            v.meta.version.index = version
            tx.update(v)
            return tx.get(Volume, volume_id)

        try:
            return self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))

    def list_volumes(self, name_prefix: str = "") -> List[Volume]:
        by = ByNamePrefix(name_prefix) if name_prefix else None
        return self.store.view(
            lambda tx: tx.find(Volume, by) if by else tx.find(Volume))

    def remove_volume(self, volume_id: str, force: bool = False) -> None:
        """Mark for deletion (the CSI manager deletes plugin-side first);
        force deletes outright (reference: volume.go:240 RemoveVolume)."""
        def cb(tx):
            v = tx.get(Volume, volume_id)
            if v is None:
                raise NotFound(f"volume {volume_id} not found")
            if force:
                tx.delete(Volume, volume_id)
                return
            if v.publish_status:
                raise FailedPrecondition("volume is still in use")
            v = v.copy()
            v.pending_delete = True
            tx.update(v)

        self.store.update(cb)

    # ------------------------------------------------------------- extensions

    def create_extension(self, annotations, description: str = ""
                         ) -> Extension:
        """reference: manager/controlapi/extension.go:20 CreateExtension."""
        if annotations is None or not annotations.name:
            raise InvalidArgument("extension name must be provided")
        ext = Extension(id=new_id(), annotations=annotations.copy(),
                        description=description)
        try:
            self.store.update(lambda tx: tx.create(ext))
        except NameConflict:
            raise AlreadyExists(
                f"extension {annotations.name} already exists")
        return self.store.view(lambda tx: tx.get(Extension, ext.id))

    def get_extension(self, extension_id: str) -> Extension:
        if not extension_id:
            raise InvalidArgument("extension ID must be provided")
        e = self.store.view(lambda tx: tx.get(Extension, extension_id))
        if e is None:
            raise NotFound(f"extension {extension_id} not found")
        return e

    def list_extensions(self) -> List[Extension]:
        return self.store.view(lambda tx: tx.find(Extension))

    def remove_extension(self, extension_id: str) -> None:
        """Refuses while resources of this kind exist
        (reference: extension.go:76 RemoveExtension)."""
        if not extension_id:
            raise InvalidArgument("extension ID must be provided")

        def cb(tx):
            ext = tx.get(Extension, extension_id)
            if ext is None:
                raise NotFound(
                    f"could not find extension {extension_id}")
            in_use = tx.find(Resource, ByKind(ext.annotations.name))
            if in_use:
                names = ", ".join(
                    r.annotations.name for r in in_use[:10])
                raise InvalidArgument(
                    f"extension {ext.annotations.name} is in use by "
                    f"resources: {names}")
            tx.delete(Extension, extension_id)

        self.store.update(cb)

    # -------------------------------------------------------------- resources

    def create_resource(self, annotations, kind: str,
                        payload: bytes = b"") -> Resource:
        """reference: manager/controlapi/resource.go:20 CreateResource."""
        if annotations is None or not annotations.name:
            raise InvalidArgument("Resource must have a name")
        if not kind:
            raise InvalidArgument("Resource must belong to an Extension")

        res = Resource(id=new_id(), annotations=annotations.copy(),
                       kind=kind, payload=payload)

        def cb(tx):
            # kind must name a registered extension (store.ErrNoKind)
            exts = tx.find(Extension, ByName(kind))
            if not exts:
                raise InvalidArgument(f"Kind {kind} is not registered")
            tx.create(res)

        try:
            self.store.update(cb)
        except NameConflict:
            raise AlreadyExists(
                f"A resource with name {annotations.name} already exists")
        return self.store.view(lambda tx: tx.get(Resource, res.id))

    def get_resource(self, resource_id: str) -> Resource:
        if not resource_id:
            raise InvalidArgument("resource ID must be present")
        r = self.store.view(lambda tx: tx.get(Resource, resource_id))
        if r is None:
            raise NotFound(f"resource {resource_id} not found")
        return r

    def update_resource(self, resource_id: str, version: int,
                        annotations=None,
                        payload: Optional[bytes] = None) -> Resource:
        """Annotations (same name) and payload are mutable
        (reference: resource.go:190 UpdateResource)."""
        def cb(tx):
            r = tx.get(Resource, resource_id)
            if r is None:
                raise NotFound(f"resource {resource_id} not found")
            r = r.copy()
            if annotations is not None:
                if annotations.name != r.annotations.name:
                    raise InvalidArgument("Name cannot be updated")
                r.annotations = annotations.copy()
            if payload is not None:
                r.payload = payload
            r.meta.version.index = version
            tx.update(r)
            return tx.get(Resource, resource_id)

        try:
            return self.store.update(cb)
        except SequenceConflict as e:
            raise FailedPrecondition(str(e))

    def list_resources(self, kind: str = "") -> List[Resource]:
        by = ByKind(kind) if kind else None
        return self.store.view(
            lambda tx: tx.find(Resource, by) if by else tx.find(Resource))

    def remove_resource(self, resource_id: str) -> None:
        if not resource_id:
            raise InvalidArgument("resource ID must be present")

        def cb(tx):
            if tx.get(Resource, resource_id) is None:
                raise NotFound(f"resource {resource_id} not found")
            tx.delete(Resource, resource_id)

        self.store.update(cb)

    # -------------------------------------------------------- token rotation

    def rotate_join_token(self, role) -> str:
        """Rotate the worker/manager join token: new role secret in the
        CA plus the updated token persisted on the cluster object
        (reference: controlapi/cluster.go UpdateCluster w/ rotation flags).
        Requires a manager-bound API (``root_ca`` set)."""
        from ..models.types import JoinTokens
        ca = getattr(self, "root_ca", None)
        if ca is None:
            raise APIError("join-token rotation requires the manager CA")
        role = NodeRole(role)
        token = ca.rotate_join_token(role)

        def cb(tx):
            clusters = tx.find(Cluster, ByName("default"))
            if not clusters:
                raise NotFound("default cluster not found")
            cluster = clusters[0].copy()
            if cluster.root_ca is None:
                raise FailedPrecondition("cluster has no trust root state")
            jt = cluster.root_ca.join_tokens or JoinTokens()
            if role == NodeRole.WORKER:
                jt.worker = token
            else:
                jt.manager = token
            cluster.root_ca.join_tokens = jt
            tx.update(cluster)

        self.store.update(cb)
        return token

    # --------------------------------------------------------------- autolock

    def set_autolock(self, enabled: bool) -> str:
        """Enable/disable manager autolock (reference:
        manager.go:116-120 UnlockKey + controlapi cluster update with
        AutoLockManagers).  Enabling mints an unlock key, stores it in
        the replicated cluster object (sealed at rest by the raft DEK),
        and returns it — managers seal their local key material under it
        and refuse to serve after a restart until unlocked."""
        import os as _os

        # the unlock key is cryptographic key material: it must come
        # from the OS CSPRNG, never a seeded/simulated source
        # swarmlint: disable=determinism-seam
        key = _os.urandom(32).hex() if enabled else ""

        def cb(tx):
            clusters = tx.find(Cluster, ByName("default"))
            if not clusters:
                raise NotFound("default cluster not found")
            cluster = clusters[0].copy()
            cluster.spec.encryption_config.auto_lock_managers = enabled
            from ..models.types import EncryptionKey
            cluster.unlock_keys = (
                [EncryptionKey(subsystem="manager", key=key.encode())]
                if enabled else [])
            tx.update(cluster)

        self.store.update(cb)
        return key

    def get_unlock_key(self) -> str:
        """Current unlock key ('' when autolock is off) — operator-only
        (reference: controlapi GetUnlockKey)."""
        cluster = self._default_cluster_raw()
        for ek in cluster.unlock_keys:
            if ek.subsystem == "manager":
                return ek.key.decode()
        return ""

    # ------------------------------------------------------------ CA rotation

    def rotate_ca(self) -> str:
        """Begin a root CA rotation: mint a new root, cross-sign it with
        the old one, switch issuance to the new key, and persist the
        rotation state; the manager's reconciler finalizes once every
        node's cert chains to the new root (reference:
        controlapi/ca_rotation.go newRootRotationObject +
        ca/reconciler.go).  Returns the new root's digest."""
        ca = getattr(self, "root_ca", None)
        if ca is None:
            raise APIError("CA rotation requires the manager CA")
        if ca.rotation is not None:
            raise FailedPrecondition("a root rotation is already running")
        new_key, new_cert, cross = ca.begin_rotation()

        def cb(tx):
            clusters = tx.find(Cluster, ByName("default"))
            if not clusters:
                raise NotFound("default cluster not found")
            cluster = clusters[0].copy()
            state = cluster.root_ca
            if state is None:
                raise FailedPrecondition("cluster has no trust root state")
            state.root_rotation_in_progress = True
            state.rotation_ca_key = new_key
            state.rotation_ca_cert = new_cert
            state.cross_signed_ca_cert = cross
            state.last_forced_rotation += 1
            tx.update(cluster)

        try:
            self.store.update(cb)
        except Exception:
            ca.rotation = None   # roll back the in-memory switch
            raise
        from ..security.ca import cert_digest
        return cert_digest(new_cert)

    # ----------------------------------------------------------------- tasks

    def get_task(self, task_id: str) -> Task:
        t = self.store.view(lambda tx: tx.get(Task, task_id))
        if t is None:
            raise NotFound(f"task {task_id} not found")
        return t

    def collect_logs(self, service_id: str, duration: float = 2.0,
                     tail: int = -1, since: float = 0.0,
                     follow: bool = True, streams=None) -> List[dict]:
        """Collect log output for a service (reference: swarmctl service
        logs over the log broker, api/logbroker.proto
        LogSubscriptionOptions).  History replays per tail/since; with
        ``follow`` live output is then collected for up to ``duration``
        seconds.  Returns [{task_id, node_id, stream, data(bytes)}], in
        arrival order.  Only meaningful on the leader (the broker agents
        publish to); bounded so one call can't pin a server thread.  The
        collection deadline reads the models.types.now() seam, so a
        simulated control API follows logs in virtual time."""
        from ..models.types import now as _now

        broker = getattr(self, "log_broker", None)
        if broker is None:
            raise APIError("log broker unavailable on this manager")
        from .logbroker import LogSelector, LogSubscriptionOptions
        duration = min(max(duration, 0.0), 30.0)
        stream = broker.subscribe_logs(
            LogSelector(service_ids=[service_id]),
            options=LogSubscriptionOptions(
                streams=list(streams or []), follow=follow,
                tail=tail, since=since))
        out: List[dict] = []
        try:
            # history backlog is pre-buffered at subscribe time: drain it
            # fully BEFORE the live-collection window starts, so a short
            # duration can never truncate the tail/since replay.  Bounded
            # by the backlog size snapshotted at subscribe — with follow
            # a producer outpacing the 10ms poll must not extend this
            # phase past the replay (live output belongs to the
            # duration-bounded window below)
            remaining = getattr(stream, "backlog_count", 0) \
                if follow else None
            while remaining is None or remaining > 0:
                try:
                    msg = stream.get(timeout=0.01)
                except Exception:   # empty (timeout) or closed (no follow)
                    break
                if remaining is not None:
                    remaining -= 1
                out.append({"task_id": msg.task_id,
                            "node_id": msg.node_id,
                            "stream": msg.stream, "data": msg.data})
            deadline = _now() + duration
            while follow and _now() < deadline:
                try:
                    msg = stream.get(timeout=max(
                        0.05, deadline - _now()))
                except TimeoutError:
                    break
                except Exception:      # broker closed mid-collection
                    break
                out.append({"task_id": msg.task_id,
                            "node_id": msg.node_id,
                            "stream": msg.stream, "data": msg.data})
        finally:
            try:
                stream.close()
            except Exception:
                pass
        return out

    def list_tasks(self, service_id: str = "", node_id: str = "") -> List[Task]:
        from ..state.store import All, ByNode, ByService
        if service_id:
            by = ByService(service_id)
        elif node_id:
            by = ByNode(node_id)
        else:
            by = All()
        return self.store.view(lambda tx: tx.find(Task, by))

    def remove_task(self, task_id: str) -> None:
        def cb(tx):
            if tx.get(Task, task_id) is None:
                raise NotFound(f"task {task_id} not found")
            tx.delete(Task, task_id)

        self.store.update(cb)
