"""CSI manager: cluster-side volume lifecycle against storage plugins.

Reference: manager/csi/{manager.go,plugin.go,convert.go}.

Watches volume objects and drives them through the controller-side CSI
lifecycle with retry/backoff (utils/volumequeue):

* created volume, no ``volume_info``    → plugin.create_volume
* publish_status PENDING_PUBLISH        → plugin.controller_publish
* publish_status PENDING_UNPUBLISH      → plugin.controller_unpublish
* pending_delete with no publishes      → plugin.delete_volume + remove

The plugin interface mirrors the CSI controller RPCs; tests use the
in-memory fake (reference: manager/csi/fakes_test.go).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from ..models.objects import Volume, VolumeInfo
from ..models.types import VolumePublishStatus
from ..state.events import Event
from ..state.store import MemoryStore
from ..state.watch import Closed
from ..utils import new_id
from ..utils.volumequeue import VolumeQueue

log = logging.getLogger("csi")


class CSIPlugin:
    """Controller-side plugin surface (reference: plugin.go / CSI spec)."""

    def create_volume(self, volume: Volume) -> VolumeInfo:
        raise NotImplementedError

    def delete_volume(self, volume: Volume) -> None:
        raise NotImplementedError

    def controller_publish(self, volume: Volume,
                           node_id: str) -> Dict[str, str]:
        """Returns the publish context."""
        raise NotImplementedError

    def controller_unpublish(self, volume: Volume, node_id: str) -> None:
        raise NotImplementedError


class InMemoryCSIPlugin(CSIPlugin):
    """Test/dev plugin (reference: fakes_test.go)."""

    def __init__(self, name: str = "inmem"):
        self.name = name
        self.volumes: Dict[str, dict] = {}
        self.published: Dict[str, set] = {}
        self.fail_next: Optional[str] = None

    def _maybe_fail(self, op: str) -> None:
        if self.fail_next == op:
            self.fail_next = None
            raise RuntimeError(f"induced {op} failure")

    def create_volume(self, volume: Volume) -> VolumeInfo:
        self._maybe_fail("create")
        vid = f"csi-{new_id()[:8]}"
        self.volumes[vid] = {"name": volume.spec.annotations.name}
        self.published[vid] = set()
        return VolumeInfo(volume_id=vid, capacity_bytes=volume.spec.capacity_min)

    def delete_volume(self, volume: Volume) -> None:
        self._maybe_fail("delete")
        vid = volume.volume_info.volume_id if volume.volume_info else ""
        self.volumes.pop(vid, None)
        self.published.pop(vid, None)

    def controller_publish(self, volume: Volume,
                           node_id: str) -> Dict[str, str]:
        self._maybe_fail("publish")
        vid = volume.volume_info.volume_id
        self.published.setdefault(vid, set()).add(node_id)
        return {"device": f"/dev/{vid}"}

    def controller_unpublish(self, volume: Volume, node_id: str) -> None:
        self._maybe_fail("unpublish")
        vid = volume.volume_info.volume_id
        self.published.get(vid, set()).discard(node_id)


class Manager:
    """reference: manager/csi/manager.go:31."""

    def __init__(self, store: MemoryStore,
                 plugins: Optional[Dict[str, CSIPlugin]] = None):
        self.store = store
        self.plugins = plugins or {}
        self.queue = VolumeQueue()
        self._stop = threading.Event()
        self._threads = []

    def register_plugin(self, name: str, plugin: CSIPlugin) -> None:
        self.plugins[name] = plugin

    def start(self) -> None:
        for target, name in ((self._watch_loop, "csi-watch"),
                             (self._work_loop, "csi-work")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        for t in self._threads:
            t.join(timeout=5)

    # ----------------------------------------------------------------- loops

    def _watch_loop(self) -> None:
        def pred(ev):
            return isinstance(ev, Event) and isinstance(ev.obj, Volume)

        def init(tx):
            for v in tx.find(Volume):
                self.queue.enqueue(v.id)

        _, sub = self.store.view_and_watch(init, predicate=pred,
                                           accepts_blocks=True)
        try:
            while not self._stop.is_set():
                try:
                    ev = sub.get(timeout=0.2)
                except TimeoutError:
                    continue
                except Closed:
                    return
                if ev.action != "delete":
                    self.queue.enqueue(ev.obj.id)
        finally:
            self.store.queue.unsubscribe(sub)

    def _work_loop(self) -> None:
        while not self._stop.is_set():
            volume_id = self.queue.wait(timeout=0.5)
            if volume_id is None:
                continue
            try:
                done = self._process(volume_id)
                if done:
                    self.queue.forget(volume_id)
                else:
                    # more pending steps, no failure: immediate pass
                    self.queue.enqueue(volume_id)
            except Exception:
                log.exception("processing volume %s failed", volume_id)
                self.queue.enqueue(volume_id, retry=True)

    # ------------------------------------------------------------ processing

    def _plugin_for(self, volume: Volume) -> Optional[CSIPlugin]:
        name = volume.spec.driver.name if volume.spec.driver else ""
        return self.plugins.get(name)

    def _process(self, volume_id: str) -> bool:
        """One reconciliation step; returns True when nothing is pending."""
        volume = self.store.raw_get(Volume, volume_id)
        if volume is None:
            return True
        plugin = self._plugin_for(volume)
        if plugin is None:
            log.warning("no CSI plugin %r for volume %s",
                        volume.spec.driver.name if volume.spec.driver
                        else "", volume_id)
            return True  # nothing we can do; don't spin

        # 1. deletion of a never-created volume needs no backend call
        if volume.pending_delete and (volume.volume_info is None
                                      or not volume.volume_info.volume_id):
            def drop(tx):
                if tx.get(Volume, volume_id) is not None:
                    tx.delete(Volume, volume_id)

            self.store.update(drop)
            return True

        # 2. creation
        if volume.volume_info is None or not volume.volume_info.volume_id:
            info = plugin.create_volume(volume)

            def set_info(tx):
                cur = tx.get(Volume, volume_id)
                if cur is None or cur.volume_info:
                    return
                cur = cur.copy()
                cur.volume_info = info
                tx.update(cur)

            self.store.update(set_info)
            return False  # re-check for publishes next pass

        # 3. deletion
        if volume.pending_delete and not volume.publish_status:
            plugin.delete_volume(volume)

            def delete(tx):
                if tx.get(Volume, volume_id) is not None:
                    tx.delete(Volume, volume_id)

            self.store.update(delete)
            return True

        # 4. publish / unpublish transitions
        changed = False
        for status in volume.publish_status:
            if status.state == VolumePublishStatus.State.PENDING_PUBLISH:
                context = plugin.controller_publish(volume, status.node_id)

                def publish(tx, node_id=status.node_id, context=context):
                    cur = tx.get(Volume, volume_id)
                    if cur is None:
                        return
                    cur = cur.copy()
                    for ps in cur.publish_status:
                        if ps.node_id == node_id and ps.state == \
                                VolumePublishStatus.State.PENDING_PUBLISH:
                            ps.state = VolumePublishStatus.State.PUBLISHED
                            ps.publish_context = dict(context)
                    tx.update(cur)

                self.store.update(publish)
                changed = True
            elif status.state == \
                    VolumePublishStatus.State.PENDING_UNPUBLISH:
                plugin.controller_unpublish(volume, status.node_id)

                def unpublish(tx, node_id=status.node_id):
                    cur = tx.get(Volume, volume_id)
                    if cur is None:
                        return
                    cur = cur.copy()
                    cur.publish_status = [
                        ps for ps in cur.publish_status
                        if not (ps.node_id == node_id and ps.state ==
                                VolumePublishStatus.State
                                .PENDING_UNPUBLISH)]
                    tx.update(cur)

                self.store.update(unpublish)
                changed = True
        if changed:
            return False  # re-check (e.g. deletion may now be unblocked)
        return True
