"""Deallocator: graceful teardown of user-facing objects.

Reference: manager/deallocator/deallocator.go:33 — waits for services
marked ``pending_delete`` to fully shut down (no tasks left), then
deletes the service record and deallocates service-level resources
(networks also marked ``pending_delete`` that no other service still
references).  Like the reference, this is the one place pending-delete
services/networks are ever actually removed.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

from ..models.objects import Network, Service, Task
from ..state.events import Event
from ..state.store import ByService, MemoryStore
from ..state.watch import Closed

log = logging.getLogger("deallocator")


class Deallocator:
    def __init__(self, store: MemoryStore):
        self.store = store
        # services shutting down -> remaining task count
        self._services: Dict[str, int] = {}
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run,
                                        name="deallocator", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=5)

    def run(self) -> None:
        try:
            def pred(ev):
                return isinstance(ev, Event) and isinstance(
                    ev.obj, (Service, Network, Task))

            def init(tx):
                # task counts for pending-delete services come from the
                # SAME transaction that anchors the subscription, so
                # task-delete events queued behind the snapshot can't
                # double-count against a stale view
                services = tx.find(Service)
                counts = {s.id: len(tx.find(Task, ByService(s.id)))
                          for s in services if s.pending_delete}
                return services, tx.find(Network), counts

            (services, networks, counts), sub = self.store.view_and_watch(
                init, predicate=pred, accepts_blocks=True)
            try:
                for s in services:
                    if not s.pending_delete:
                        continue
                    if counts.get(s.id, 0) == 0:
                        self._deallocate_service(s)
                    else:
                        self._services[s.id] = counts[s.id]
                for n in networks:
                    self._process_network(n)
                while not self._stop.is_set():
                    try:
                        ev = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if ev is None:
                        continue
                    obj = ev.obj
                    if isinstance(obj, Service):
                        if ev.action == "delete":
                            self._services.pop(obj.id, None)
                        else:
                            self._process_service(obj)
                    elif isinstance(obj, Network) \
                            and ev.action != "delete":
                        self._process_network(obj)
                    elif isinstance(obj, Task) and ev.action == "delete":
                        self._on_task_delete(obj.service_id)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    # ------------------------------------------------------------- services

    def _process_service(self, service: Service) -> None:
        """reference: deallocator.go:162 processService."""
        if not service.pending_delete:
            return
        tasks = self.store.view(
            lambda tx: tx.find(Task, ByService(service.id)))
        if not tasks:
            self._services.pop(service.id, None)
            self._deallocate_service(service)
        else:
            self._services[service.id] = len(tasks)

    def _on_task_delete(self, sid: str) -> None:
        """A tracked service lost a task: RECOUNT from the store rather
        than decrementing (events may replay adds/removes the tracked
        number never saw)."""
        if sid not in self._services:
            return
        remaining = len(self.store.view(
            lambda tx: tx.find(Task, ByService(sid))))
        if remaining > 0:
            self._services[sid] = remaining
            return
        del self._services[sid]
        svc = self.store.view(lambda tx: tx.get(Service, sid))
        if svc is not None and svc.pending_delete:
            self._deallocate_service(svc)

    def _deallocate_service(self, service: Service) -> None:
        """Delete the drained service, then any of its pending-delete
        networks no other service still uses
        (reference: deallocator.go:191 deallocateService)."""
        nets = [nc.target for nc in (service.spec.task.networks
                                     or service.spec.networks or [])]

        def cb(tx):
            if tx.get(Service, service.id) is not None:
                tx.delete(Service, service.id)
            for nid in nets:
                network = tx.get(Network, nid)
                if network is not None:
                    self._maybe_delete_network(
                        tx, network, ignore_service=service.id)

        try:
            self.store.update(cb)
            log.info("deallocated service %s", service.id[:8])
        except Exception:
            log.exception("deallocating service %s failed", service.id)

    # ------------------------------------------------------------- networks

    def _process_network(self, network: Network) -> None:
        """reference: deallocator.go:230 processNetwork (event path)."""
        if not network.pending_delete:
            return

        def cb(tx):
            cur = tx.get(Network, network.id)
            if cur is not None:
                self._maybe_delete_network(tx, cur)

        try:
            self.store.update(cb)
        except Exception:
            log.exception("deallocating network %s failed", network.id)

    @staticmethod
    def _maybe_delete_network(tx, network: Network,
                              ignore_service: str = "") -> None:
        if not network.pending_delete:
            return
        for s in tx.find(Service):
            if s.id == ignore_service:
                continue
            refs = [nc.target for nc in (s.spec.task.networks
                                         or s.spec.networks or [])]
            if network.id in refs:
                return   # still in use
        tx.delete(Network, network.id)
        log.info("deallocated network %s", network.id[:8])
