"""Dispatcher: the worker-facing control channel.

Reference: manager/dispatcher/dispatcher.go, assignments.go, nodes.go,
heartbeat/heartbeat.go.

Responsibilities (matching the reference):

* ``register``      — session creation for a known node; marks node READY
  (dispatcher.go:553).
* ``heartbeat``     — TTL refresh with ±epsilon jitter; expiry marks the
  node DOWN (dispatcher.go:1317, :29-34).
* ``open_assignments`` — a stream of COMPLETE + INCREMENTAL assignment
  diffs (tasks >= ASSIGNED on the node, plus referenced secrets/configs),
  batched 100ms / 100 modifications (dispatcher.go:1013, assignments.go).
* ``update_task_status`` — validated, batched status writeback; status only
  moves forward (dispatcher.go:607, :726).
* down-node tracking — nodes DOWN longer than ``orphan_timeout`` get their
  tasks moved to ORPHANED so resources free up (dispatcher.go:52, :1209).

Transport: in-process method calls shaped like the gRPC surface (register /
session stream / assignments stream / unary status updates) so a network
transport can wrap this object 1:1.  All timers (heartbeat TTLs, orphan
deadlines, status-update batching) run on one worker thread.
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..models.objects import Cluster, Config, Node, Secret, Task, Volume
from ..models.types import NodeState, NodeStatus, TaskState, TaskStatus, now
from ..obs import planes as _planes
from ..obs.journey import journeys as _journeys
from ..obs.trace import tracer
from ..state import serde as _serde
from ..state.events import Event, EventSnapshotRestore, EventTaskBlock
from ..state.store import Batch, ByNode, MemoryStore
from ..state.watch import Closed, Subscription
from ..utils import new_id
from ..utils.metrics import registry as _metrics

log = logging.getLogger("dispatcher")


@dataclass
class Config_:
    """reference: dispatcher.go:29-53 DefaultConfig."""

    heartbeat_period: float = 5.0
    heartbeat_epsilon: float = 0.5
    grace_multiplier: float = 3.0
    rate_limit_period: float = 8.0
    process_updates_interval: float = 0.100
    max_batch_items: int = 100
    assignment_batching_wait: float = 0.100
    modification_batch_limit: int = 100
    orphan_timeout: float = 24 * 3600.0
    # --- overload protection (backpressure plane).  All defaults keep
    # classic behavior; bounds opt in per deployment.
    #: hard admission bound on concurrent sessions; register() beyond it
    #: sheds with ErrOverloaded (counted, client retries under backoff)
    max_sessions: Optional[int] = None
    #: session count beyond which the heartbeat period stretches
    #: linearly (leader tells agents to slow down); 0 disables
    hb_stretch_start: int = 0
    #: cap on the stretch factor
    hb_stretch_max: float = 4.0
    #: bound on buffered task-status updates; an update batch that would
    #: overflow it is shed with ErrOverloaded (counted, client re-sends)
    max_pending_updates: Optional[int] = None
    #: per-node assignment-set bound on retained TERMINAL tasks; beyond
    #: it the oldest terminal entries are compacted out (counted) as
    #: explicit "remove" changes — memory stays O(assigned tasks)
    max_terminal_tasks: Optional[int] = None


DefaultConfig = Config_


class DispatcherError(Exception):
    #: wire error code (net/server.py passes it through verbatim, so the
    #: agent-side failover client can classify without importing manager)
    code = "dispatcher"


class ErrNodeNotFound(DispatcherError):
    code = "not_found"


class ErrSessionInvalid(DispatcherError):
    code = "session_invalid"


class ErrNodeNotRegistered(DispatcherError):
    code = "node_not_registered"


class ErrRateLimited(DispatcherError):
    """Node re-registered too often (reference: nodes.go:90
    CheckRateLimit — at most RATE_LIMIT_COUNT registrations per
    rate_limit_period)."""


class ErrOverloaded(DispatcherError):
    """Backpressure shed at the RPC edge: the dispatcher is at a
    configured bound (sessions or status buffer).  Degraded, never
    silently lossy — every shed is counted in ``swarm_plane_drops``
    and the client re-queues under its existing jittered backoff."""
    code = "overloaded"


RATE_LIMIT_COUNT = 3   # reference: nodes.go:14


@dataclass
class _RegisteredNode:
    node_id: str
    session_id: str
    deadline: float = 0.0
    #: end of the window PROMISED to the agent (stretched period ×
    #: grace) — an expiry firing before it is a premature expiration,
    #: the bug heartbeat-liveness-under-stretch exists to catch
    promised_until: float = 0.0
    registered_at: float = field(default_factory=now)
    attempts: int = 0
    streams: List["AssignmentStream"] = field(default_factory=list)


class AssignmentsMessage:
    """One batch of assignment changes (reference: api/dispatcher.proto)."""

    COMPLETE = "complete"
    INCREMENTAL = "incremental"

    __slots__ = ("type", "applies_to", "results_in", "changes")

    def __init__(self, type_, applies_to, results_in, changes):
        self.type = type_
        self.applies_to = applies_to
        self.results_in = results_in
        self.changes = changes  # list of (action, kind, obj)


def _note_assignment_ship(msg: "AssignmentsMessage") -> None:
    """Per-shipped-batch observability: the ``assigned_sent`` journey
    milestone for every task update in the batch (the one leader-local
    milestone — delivery is not replicated state) and the serialized
    size of the batch as the assignment-set bytes gauge."""
    nbytes = 0
    for change in msg.changes:
        action, kind, obj = change
        if kind == "task" and action == "update":
            _journeys.note_sent(obj.id)
        try:
            nbytes += len(_serde.dumps(obj))
        except Exception:
            pass   # unserializable stub: size stays an estimate
    _metrics.gauge("swarm_dispatcher_assignment_set_bytes",
                   float(nbytes))


class AssignmentStream:
    """Server-side push stream of AssignmentsMessage, one per Assignments
    call; a thread in the dispatcher feeds it."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._buf: List[AssignmentsMessage] = []
        self._cond = threading.Condition()
        self._closed = False
        self.error: Optional[Exception] = None

    def _push(self, msg: AssignmentsMessage) -> None:
        with self._cond:
            if self._closed:
                return
            self._buf.append(msg)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> AssignmentsMessage:
        with self._cond:
            if not self._buf and not self._closed:
                self._cond.wait(timeout)
            if self._buf:
                return self._buf.pop(0)
            if self._closed:
                raise Closed()
            raise TimeoutError()

    def close(self, error: Optional[Exception] = None) -> None:
        with self._cond:
            self.error = error
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed


class _AssignmentSet:
    """Tracks what a node currently knows and computes diffs
    (reference: assignments.go newAssignmentSet)."""

    def __init__(self, node_id: str, driver_provider=None,
                 terminal_bound: Optional[int] = None,
                 on_compact: Optional[Callable[[int], None]] = None):
        self.node_id = node_id
        self.driver_provider = driver_provider
        #: bound on retained terminal (> RUNNING) tasks; beyond it the
        #: oldest are compacted out as explicit "remove" changes so the
        #: set stays O(assigned tasks) under churn
        self.terminal_bound = terminal_bound
        self.on_compact = on_compact
        self.compactions = 0
        self._terminal: Dict[str, None] = {}   # insertion-ordered ids
        self.tasks: Dict[str, Task] = {}
        self.deps_use: Dict[Tuple[str, str], Set[str]] = {}  # (kind,id)->task ids
        self.changes: Dict[Tuple[str, str], tuple] = {}
        # driver-backed secrets marked DoNotReuse get task-specific ids
        # (reference: assignments.go assignSecret): (task_id, secret_id)
        # -> combined assignment id
        self._secret_alias: Dict[Tuple[str, str], str] = {}
        # tasks whose driver-secret fetch failed; the assignments loop
        # retries them on idle ticks until the provider recovers
        self.pending_secret_retry: Set[str] = set()

    # --- dependencies

    _DEP_TYPES = {"secret": Secret, "config": Config, "volume": Volume}

    def _task_deps(self, t: Task) -> List[Tuple[str, str]]:
        deps = []
        c = t.spec.container
        if c is not None:
            for ref in c.secrets:
                deps.append(("secret", ref.secret_id))
            for ref in c.configs:
                deps.append(("config", ref.config_id))
        # CSI volume attachments are worker dependencies too: the agent's
        # node-volumes manager stages/publishes them before the task runs
        # (reference: assignments.go volumes + agent/csi/volumes.go)
        for va in t.volumes:
            deps.append(("volume", va.id))
        return deps

    def _add_task_deps(self, tx, t: Task) -> None:
        for key in self._task_deps(t):
            kind, obj_id = key
            if kind == "secret":
                self._assign_secret(tx, t, obj_id)
                continue
            users = self.deps_use.setdefault(key, set())
            if not users:
                obj = tx.get(self._DEP_TYPES[kind], obj_id)
                if obj is not None:
                    self.changes[key] = ("update", kind, obj)
            users.add(t.id)

    def _assign_secret(self, tx, t: Task, secret_id: str) -> None:
        """Plain secrets ship the stored object; driver-backed secrets
        fetch their value from the provider plugin, and DoNotReuse values
        get a task-specific id so different tasks can receive different
        values (reference: assignments.go assignSecret + drivers/)."""
        alias = self._secret_alias.get((t.id, secret_id))
        if alias is not None:
            # re-add of a task whose task-specific secret already shipped
            self.deps_use.setdefault(("secret", alias), set()).add(t.id)
            return
        base_key = ("secret", secret_id)
        if self.deps_use.get(base_key):
            # already shipped under its own id (plain, or driver-fetched
            # reusable) — don't re-fetch the value per additional task
            self.deps_use[base_key].add(t.id)
            return
        obj = tx.get(Secret, secret_id)
        key = base_key
        if obj is not None and obj.spec.driver is not None \
                and obj.spec.driver.name:
            if self.driver_provider is None:
                log.warning("secret %s needs driver %r but no provider "
                            "is registered; assignment skipped",
                            secret_id[:8], obj.spec.driver.name)
                return
            try:
                d = self.driver_provider.new_secret_driver(obj.spec.driver)
                value, no_reuse = d.get(obj.spec, t)
            except Exception:
                # fetch errors skip the assignment; the assignments loop
                # retries on idle ticks, so the task (shipped without its
                # secret, hence PREPARING) recovers with the provider
                log.exception("fetching driver secret %s failed",
                              secret_id[:8])
                self.pending_secret_retry.add(t.id)
                return
            obj = obj.copy()
            obj.spec.data = value
            if no_reuse:
                combined = f"{secret_id}.{t.id}"
                obj.id = combined
                obj.internal = True
                self._secret_alias[(t.id, secret_id)] = combined
                key = ("secret", combined)
        users = self.deps_use.setdefault(key, set())
        if not users and obj is not None:
            self.changes[key] = ("update", "secret", obj)
        users.add(t.id)

    def retry_pending_secrets(self, tx) -> bool:
        """Re-attempt driver-secret fetches that failed earlier; returns
        True when a retry shipped something new."""
        n_before = len(self.changes)
        for tid in list(self.pending_secret_retry):
            self.pending_secret_retry.discard(tid)
            t = self.tasks.get(tid)
            if t is not None:
                self._add_task_deps(tx, t)
        return len(self.changes) > n_before

    def _release_task_deps(self, t: Task) -> bool:
        modified = False
        self.pending_secret_retry.discard(t.id)
        for key in self._task_deps(t):
            kind, obj_id = key
            if kind == "secret":
                alias = self._secret_alias.pop((t.id, obj_id), None)
                if alias is not None:
                    key = ("secret", alias)
            users = self.deps_use.get(key)
            if users is None:
                continue
            users.discard(t.id)
            if not users:
                del self.deps_use[key]
                kind, obj_id = key
                stub = self._DEP_TYPES[kind](id=obj_id)
                self.changes[key] = ("remove", kind, stub)
                modified = True
        return modified

    def update_volume(self, v: Volume) -> bool:
        """Forward updates of a tracked volume (publish context changes
        etc.) to the node (reference: assignments.go addOrUpdateVolume)."""
        if ("volume", v.id) not in self.deps_use:
            return False
        self.changes[("volume", v.id)] = ("update", "volume", v)
        return True

    # --- tasks

    def add_or_update_task(self, tx, t: Task) -> bool:
        # only tasks ASSIGNED or higher concern the agent
        if t.status.state < TaskState.ASSIGNED:
            return False
        old = self.tasks.get(t.id)
        if old is not None:
            # states <= ASSIGNED are manager-set and must always be sent;
            # above that, skip sends when nothing the agent cares about
            # changed (reference: assignments.go:268)
            if (t.status.state > TaskState.ASSIGNED
                    and old.desired_state == t.desired_state
                    and old.spec is t.spec
                    and old.node_id == t.node_id):
                self.tasks[t.id] = t
                if t.status.state > TaskState.RUNNING:
                    modified = self._release_task_deps(t)
                    return self._note_terminal(t) or modified
                return False
        elif t.status.state <= TaskState.RUNNING:
            self._add_task_deps(tx, t)
        self.tasks[t.id] = t
        self.changes[("task", t.id)] = ("update", "task", t)
        self._note_terminal(t)
        return True

    def _note_terminal(self, t: Task) -> bool:
        """Track terminal (> RUNNING) tasks in arrival order and compact
        the oldest beyond ``terminal_bound`` as explicit "remove"
        changes: the agent forgets them a little early (it would on the
        reaper's delete anyway) and set memory stays O(assigned tasks)
        under churn instead of O(task history)."""
        if t.status.state <= TaskState.RUNNING:
            return False
        self._terminal.setdefault(t.id, None)
        bound = self.terminal_bound
        if bound is None or len(self._terminal) <= bound:
            return False
        evicted = 0
        while len(self._terminal) > bound:
            tid = next(iter(self._terminal))
            del self._terminal[tid]
            old = self.tasks.pop(tid, None)
            if old is not None:
                self._release_task_deps(old)
                self.changes[("task", tid)] = ("remove", "task",
                                               Task(id=tid))
            evicted += 1
        self.compactions += evicted
        _metrics.counter("swarm_dispatcher_aset_compactions", evicted)
        if self.on_compact is not None:
            self.on_compact(evicted)
        return True

    def remove_task(self, t: Task) -> bool:
        self._terminal.pop(t.id, None)
        if t.id not in self.tasks:
            return False
        self.changes[("task", t.id)] = ("remove", "task", Task(id=t.id))
        del self.tasks[t.id]
        self._release_task_deps(t)
        return True

    def message(self, type_, applies_to, results_in) -> AssignmentsMessage:
        changes = list(self.changes.values())
        self.changes = {}
        return AssignmentsMessage(type_, applies_to, results_in, changes)


class BatchedAssignmentFanout:
    """Batched, threadless assignment fan-out (ISSUE 12 satellite,
    ROADMAP direction 3 residual).

    The classic ``open_assignments`` path runs one thread per node
    stream — fine for five agents, wrong for a thousand, and an
    autoscaler burst multiplies per-task sends.  This fan-out keeps ONE
    store subscription, routes events into per-node ``_AssignmentSet``
    diffs, and ``flush()`` (driven from ``process_deadlines`` — the
    worker thread in production, the control step in the sim) sends at
    most ceil(pending / modification_batch_limit) INCREMENTAL messages
    per node per flush: N task assignments to one node cost
    <= ceil(N/batch) sends, not N round-trips.

    Leader-gap discipline mirrors the status-flush re-queue machinery:
    diffs accumulate while a stream is down and the re-registered
    node's fresh ``open`` rebuilds a COMPLETE set from the store view —
    nothing lost, nothing duplicated (unit-tested across a gap in
    tests/test_autoscale.py).
    """

    def __init__(self, dispatcher: "Dispatcher"):
        self.d = dispatcher
        self._mu = threading.Lock()
        # serializes open() against flush(): open's COMPLETE snapshot
        # and its registration in _sets must be atomic w.r.t. a flush
        # draining the shared subscription, or an assignment committed
        # between the two is consumed for a node flush doesn't know yet
        # and lost forever
        self._drain_mu = threading.Lock()
        self._sets: Dict[str, _AssignmentSet] = {}
        self._streams: Dict[str, AssignmentStream] = {}
        self._seq: Dict[str, int] = {}
        self._applies: Dict[str, str] = {}
        self.stats = {"sends": 0, "complete_sends": 0, "compactions": 0}
        self._sub = dispatcher.store.queue.subscribe(
            lambda ev: isinstance(ev, EventTaskBlock)
            or (isinstance(ev, Event)
                and isinstance(ev.obj, (Task, Volume))),
            accepts_blocks=True)

    # ------------------------------------------------------------- streams

    def open(self, node_id: str, session_id: str) -> AssignmentStream:
        """Open (or re-open) a node's stream: full COMPLETE set from the
        current store view, then incremental batches via flush()."""
        self.d._check_session(node_id, session_id)
        stream = AssignmentStream(node_id)

        def _on_compact(n):
            self.stats["compactions"] += n

        aset = _AssignmentSet(node_id,
                              driver_provider=self.d.driver_provider,
                              terminal_bound=self.d.config
                              .max_terminal_tasks,
                              on_compact=_on_compact)
        with self._drain_mu:
            # session re-check + stream registration BEFORE any state
            # lands in the maps: a failure here must leak nothing
            with self.d._mu:
                rn = self.d._nodes.get(node_id)
                if rn is None or rn.session_id != session_id:
                    raise ErrSessionInvalid(node_id)
                rn.streams.append(stream)
            initial = self.d.store.view(
                lambda vx: list(vx.find(Task, ByNode(node_id))))
            tx = self.d.store.view()
            for t in initial:
                aset.add_or_update_task(tx, t)
            with self._mu:
                self._sets[node_id] = aset
                self._streams[node_id] = stream
                self._seq[node_id] = 0
                self._applies[node_id] = ""
            self._send(node_id, aset, stream,
                       AssignmentsMessage.COMPLETE)
            self.stats["complete_sends"] += 1
        return stream

    def _drop(self, node_id: str) -> None:
        with self._mu:
            self._sets.pop(node_id, None)
            self._streams.pop(node_id, None)
            self._seq.pop(node_id, None)
            self._applies.pop(node_id, None)

    def _send(self, node_id: str, aset: _AssignmentSet,
              stream: AssignmentStream, type_) -> None:
        """Send aset's pending changes as <= ceil(n/batch) messages."""
        limit = max(self.d.config.modification_batch_limit, 1)
        while True:
            if type_ == AssignmentsMessage.INCREMENTAL \
                    and not aset.changes:
                return
            chunk: Dict[tuple, tuple] = {}
            for key in list(aset.changes)[:limit]:
                chunk[key] = aset.changes.pop(key)
            self._seq[node_id] += 1
            results_in = str(self._seq[node_id])
            msg = AssignmentsMessage(type_, self._applies[node_id],
                                     results_in, list(chunk.values()))
            stream._push(msg)
            self._applies[node_id] = results_in
            self.stats["sends"] += 1
            _metrics.counter(
                f'swarm_dispatcher_assignments_sent{{type="{type_}"}}')
            _metrics.counter("swarm_dispatcher_assignment_changes",
                             len(msg.changes))
            _note_assignment_ship(msg)
            # a COMPLETE always goes out (even empty); its overflow (a
            # node with more assignments than one batch) continues as
            # incrementals
            type_ = AssignmentsMessage.INCREMENTAL

    # --------------------------------------------------------------- flush

    def flush(self) -> None:
        """Drain the shared subscription into the per-node sets, then
        one batched send pass.  ``_drain_mu`` serializes against
        ``open()`` so events for a node mid-registration are either in
        its COMPLETE snapshot or routed here — never silently consumed
        for an unknown node that registers a moment later."""
        t0 = time.perf_counter()
        with self._drain_mu:
            self._flush_locked()
        _planes.plane(_planes.DISPATCHER).note_busy(
            time.perf_counter() - t0)

    def _flush_locked(self) -> None:
        with self._mu:
            live = dict(self._sets)
        tx = None
        while True:
            ev = self._sub.poll()
            if ev is None:
                break
            if isinstance(ev, EventTaskBlock):
                per_node = ev.per_node()
                for node_id, aset in live.items():
                    items = per_node.get(node_id)
                    if not items:
                        continue
                    tx = tx if tx is not None else self.d.store.view()
                    for old, _ver in items:
                        t = self.d.store.raw_get(Task, old.id)
                        if t is not None:
                            aset.add_or_update_task(tx, t)
                continue
            obj = ev.obj
            if isinstance(obj, Volume):
                if ev.action != "delete":
                    for aset in live.values():
                        aset.update_volume(obj)
                continue
            aset = live.get(obj.node_id)
            if aset is None:
                continue
            if ev.action == "delete":
                aset.remove_task(obj)
            else:
                tx = tx if tx is not None else self.d.store.view()
                aset.add_or_update_task(tx, obj)
        for node_id, aset in live.items():
            stream = self._streams.get(node_id)
            if stream is None or stream.closed:
                self._drop(node_id)
                continue
            if aset.changes:
                self._send(node_id, aset, stream,
                           AssignmentsMessage.INCREMENTAL)

    def stop(self) -> None:
        with self._mu:
            streams = list(self._streams.values())
            self._sets.clear()
            self._streams.clear()
        for s in streams:
            s.close(DispatcherError("dispatcher stopped"))
        if self._sub is not None:
            try:
                self.d.store.queue.unsubscribe(self._sub)
            except Exception:
                pass
            self._sub = None


class Dispatcher:
    def __init__(self, store: MemoryStore,
                 config: Optional[Config_] = None,
                 driver_provider=None,
                 rng: Optional[random.Random] = None,
                 write_store=None,
                 shard_filter: Optional[Callable[[str], bool]] = None):
        self.store = store
        # FOLLOWER MODE (reads served off the local replicated store):
        # every read — session checks, assignment snapshots/streams,
        # status validation — stays on ``store``; every session-mutating
        # WRITE (node READY/DOWN, task status batches, orphan moves)
        # goes through ``write_store``, which a follower member points at
        # a leader-forwarding proxy.  Default (None) is leader mode:
        # reads and writes share one store, behavior unchanged.
        self.write_store = write_store if write_store is not None \
            else store
        # session sharding: with a filter, this dispatcher owns only the
        # nodes the filter accepts — markNodesUnknown grace deadlines are
        # limited to its shard so a restarted member cannot DOWN nodes
        # that re-registered with a different member
        self.shard_filter = shard_filter
        #: optional veto consulted when a registration-grace deadline
        #: fires for a node with no local session: return False when the
        #: node is known to hold a live session on ANOTHER member (the
        #: control plane tracks ownership), True to proceed marking DOWN
        self.reg_grace_check: Optional[Callable[[str], bool]] = None
        # heartbeat jitter source: injectable so the deterministic
        # simulator can seed it (production uses the module-level RNG)
        self._rng = rng or random
        # resolves SecretSpec.driver to provider plugins
        # (reference: manager/drivers/provider.go)
        self.driver_provider = driver_provider
        # private copy: cluster-spec reloads must not mutate the caller's
        # (e.g. the Manager's) config object, which seeds future
        # dispatchers on later leadership cycles
        self.config = dataclasses.replace(config) if config else Config_()
        # the configured default, restored when the spec unsets its value
        self._default_heartbeat = self.config.heartbeat_period
        self._mu = threading.Lock()
        self._nodes: Dict[str, _RegisteredNode] = {}
        self._down_nodes: Dict[str, float] = {}  # node_id -> down since
        self._task_updates: Dict[str, TaskStatus] = {}
        # (volume_id, node_id) pairs reported node-unpublished by agents
        # (reference: dispatcher.go:682 UpdateVolumeStatus)
        self._unpublished_volumes: Set[Tuple[str, str]] = set()
        self._node_updates: Dict[str, tuple] = {}  # id->(status, description)
        self._updates_lock = threading.Lock()
        self._heap: List = []    # (deadline, seq, kind, node_id)
        self._seq = 0
        self._running = False
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._streams_threads: List[threading.Thread] = []
        #: batched assignment fan-out (enable_batched_fanout): replaces
        #: the thread-per-stream assignments loop with one subscription
        #: + per-node batched flushes driven from process_deadlines
        self.fanout: Optional[BatchedAssignmentFanout] = None
        self.stats = {"heartbeats": 0, "expirations": 0,
                      "sheds": 0, "hb_stretches": 0,
                      "premature_expirations": 0}
        #: checker-sensitivity seam: with the seam off, the expiry
        #: deadline forgets the stretch the agent was PROMISED — the
        #: exact bug heartbeat-liveness-under-stretch exists to catch
        self.stretch_extends_deadline = True
        #: checker-sensitivity seam: with the seam off, admission sheds
        #: still happen but are NOT counted — silently lossy degradation,
        #: the exact bug overload-sheds-are-counted-and-recovered catches
        self.count_sheds = True
        # cached Timer references — no per-call registry lookup on the
        # flush/assignments paths (reset() resets these in place)
        self._flush_timer = _metrics.timer(
            "swarm_dispatcher_update_batch_latency")
        self._build_timer = _metrics.timer(
            "swarm_dispatcher_assignments_build")

        # dispatcher-plane saturation probe (obs/planes.py): session
        # count as its own gauge (a bounded per-shard scalar) and the
        # fan-out's pending-change backlog as the plane queue depth.
        # plane() is resolved per call — planes.reset() rebinds the
        # table.  Weakref: the probe must not pin a stopped dispatcher.
        # Co-resident dispatchers (HA tests): last constructed owns it.
        import weakref
        _ref = weakref.ref(self)

        def _disp_probe():
            d = _ref()
            if d is None:
                return {}
            with d._mu:
                sessions = float(len(d._nodes))
            _metrics.gauge("swarm_dispatcher_sessions", sessions)
            with d._updates_lock:
                pending = float(len(d._task_updates))
            _metrics.gauge("swarm_dispatcher_pending_updates", pending)
            depth = pending
            fan = d.fanout
            if fan is not None:
                with fan._mu:
                    depth += float(sum(len(s.changes)
                                       for s in fan._sets.values()))
            return {"depth": depth}
        _planes.plane(_planes.DISPATCHER).set_probe(_disp_probe)

    # ------------------------------------------------------------- lifecycle

    def run(self, start_worker: bool = True) -> None:
        """Start the dispatcher's timer/batching worker.

        ``start_worker=False`` brings the dispatcher fully up but runs no
        thread — the caller (the deterministic simulator) drives
        ``process_deadlines``/``_flush_updates`` itself under its clock."""
        with self._mu:
            if self._running:
                return
            self._running = True
            self._stop.clear()
            # cluster-spec changes (e.g. heartbeat period) take effect
            # live; the current spec applies at startup too (reference:
            # manager.go:801 watchForClusterChanges does an initial read)
            self._cluster_sub = self.store.queue.subscribe(
                lambda ev: isinstance(ev, EventSnapshotRestore)
                or (isinstance(ev, Event) and isinstance(ev.obj, Cluster)
                    and ev.action == "update"),
                accepts_blocks=True)   # blocks are never cluster events
            self._load_cluster_config()
            self._mark_nodes_unknown()
            if start_worker:
                self._worker = threading.Thread(
                    target=self._worker_loop, name="dispatcher",
                    daemon=True)
                self._worker.start()

    def _mark_nodes_unknown(self) -> None:
        """A fresh dispatcher (new leader) inherits store nodes that
        registered with the OLD leader's dispatcher: give each a
        registration grace window; whoever doesn't open a session by then
        is marked DOWN so its tasks heal elsewhere (reference:
        dispatcher.go markNodesUnknown on Run)."""
        try:
            nodes = self.store.view(lambda tx: tx.find(Node))
        except Exception:
            log.exception("markNodesUnknown scan failed")
            return
        grace = self._heartbeat_period() * self.config.grace_multiplier
        deadline = now() + grace
        # caller (start) already holds self._mu
        for n in nodes:
            if self.shard_filter is not None \
                    and not self.shard_filter(n.id):
                continue   # another member's session shard
            if n.status.state != NodeState.DOWN:
                self._push_deadline(deadline, "reg", n.id)

    def enable_batched_fanout(self) -> "BatchedAssignmentFanout":
        """Switch ``open_assignments`` to the batched, threadless
        fan-out (call after ``run``).  Idempotent."""
        if self.fanout is None:
            self.fanout = BatchedAssignmentFanout(self)
        return self.fanout

    def adopt_registration_grace(self, node_ids) -> None:
        """Adopt orphaned sessions (their owning member died): give each
        node a registration-grace window on THIS dispatcher; whoever does
        not re-register anywhere by then is marked DOWN so its tasks heal
        elsewhere (the follower-mode analogue of markNodesUnknown)."""
        grace = self._heartbeat_period() * self.config.grace_multiplier
        deadline = now() + grace
        with self._mu:
            for nid in node_ids:
                if nid not in self._nodes:
                    self._push_deadline(deadline, "reg", nid)

    def release_session(self, node_id: str, session_id: str) -> None:
        """Graceful session handoff: drop the session WITHOUT marking the
        node DOWN — the agent is re-registering with another member (e.g.
        draining consumers off a freshly promoted leader).  An unknown or
        mismatched session is a no-op (the handoff already happened)."""
        with self._mu:
            rn = self._nodes.get(node_id)
            if rn is None or rn.session_id != session_id:
                return
            del self._nodes[node_id]
        for stream in rn.streams:
            stream.close(ErrSessionInvalid("session released"))

    def stop(self, flush: bool = True) -> None:
        """``flush=False`` drops buffered status updates instead of
        writing them out — the deposed-leader teardown path: a fenced
        proposer would reject the flush anyway, and the successor's
        dispatcher re-learns task state from the agents' re-registration
        (fresh COMPLETE assignment sets)."""
        self._stop.set()
        with self._mu:
            self._running = False
            nodes = list(self._nodes.values())
            self._nodes.clear()
        for rn in nodes:
            for stream in rn.streams:
                stream.close(DispatcherError("dispatcher stopped"))
        if self._worker is not None:
            self._worker.join(timeout=5)
            self._worker = None
        if self.fanout is not None:
            self.fanout.stop()
            self.fanout = None
        if getattr(self, "_cluster_sub", None) is not None:
            self.store.queue.unsubscribe(self._cluster_sub)
            self._cluster_sub = None
        if flush:
            self._flush_updates()

    def _load_cluster_config(self) -> None:
        from ..state.store import ByName
        clusters = self.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))
        if clusters:
            self._apply_cluster_config(clusters[0])

    def _apply_cluster_config(self, cluster: Cluster) -> None:
        # spec value 0 means unset -> the configured default applies;
        # this holds on the initial read, live updates, AND snapshot
        # restores, and lets an operator RESET to the default by writing 0
        period = cluster.spec.dispatcher.heartbeat_period
        target = period if period > 0 else self._default_heartbeat
        if target != self.config.heartbeat_period:
            log.info("heartbeat period now %.1fs (cluster spec)", target)
            self.config.heartbeat_period = target

    # -------------------------------------------------------------- register

    def register(self, node_id: str,
                 description=None, addr: str = "") -> Tuple[str, float]:
        """Create a session; returns (session_id, heartbeat_period)
        (reference: dispatcher.go:553)."""
        if not self._running:
            raise DispatcherError("dispatcher is not running")
        node = self.store.raw_get(Node, node_id)
        if node is None:
            raise ErrNodeNotFound(node_id)
        maxs = self.config.max_sessions
        if maxs is not None and node_id not in self._nodes \
                and len(self._nodes) >= maxs:
            self._count_shed(1)
            raise ErrOverloaded(
                f"session bound {maxs} reached; node {node_id} shed")

        session_id = new_id()
        period = self._heartbeat_period()
        window = period if self.stretch_extends_deadline \
            else period / self._stretch_factor()
        with self._mu:
            old = self._nodes.get(node_id)
            attempts = 0
            if old is not None:
                # re-registration rate limit (reference: nodes.go:90
                # CheckRateLimit): attempts reset once the last
                # registration is older than the period, and carry over
                # across accepted re-registrations otherwise; period <= 0
                # disables the limit (reference tests set 0)
                if self.config.rate_limit_period > 0:
                    attempts = old.attempts
                    if now() - old.registered_at > \
                            self.config.rate_limit_period:
                        attempts = 0
                    attempts += 1
                    if attempts > RATE_LIMIT_COUNT:
                        # attempts stick but the window keeps aging from
                        # the last ACCEPTED registration (reference:
                        # nodes.go:94-101 — Registered is only stamped on
                        # success), so steady retries recover after one
                        # quiet period
                        old.attempts = attempts
                        raise ErrRateLimited(
                            f"node {node_id} exceeded rate limit count "
                            "of registrations")
                for stream in old.streams:
                    stream.close(ErrSessionInvalid("node re-registered"))
            rn = _RegisteredNode(node_id=node_id, session_id=session_id,
                                 attempts=attempts)
            rn.deadline = now() + window * self.config.grace_multiplier
            rn.promised_until = now() + period * \
                self.config.grace_multiplier
            self._nodes[node_id] = rn
            self._down_nodes.pop(node_id, None)
            self._push_deadline(rn.deadline, "hb", node_id)

        self._mark_node_ready(node_id, description, addr)
        log.info("worker %s registered", node_id)
        return session_id, period

    def _heartbeat_period(self) -> float:
        base = self.config.heartbeat_period
        jittered = base + self._rng.uniform(
            -self.config.heartbeat_epsilon, self.config.heartbeat_epsilon)
        stretch = self._stretch_factor()
        if stretch > 1.0:
            self.stats["hb_stretches"] += 1
            _metrics.counter("swarm_dispatcher_hb_stretches")
        return jittered * stretch

    def _stretch_factor(self) -> float:
        """Adaptive heartbeat stretching: beyond ``hb_stretch_start``
        sessions the advertised period grows linearly with load (capped
        at ``hb_stretch_max``) — the leader tells agents to slow down,
        so heartbeat arrival rate stays ~flat as sessions multiply.
        Lock-free read of len(_nodes); callers may hold ``_mu``."""
        start = self.config.hb_stretch_start
        if start <= 0:
            return 1.0
        sessions = len(self._nodes)
        if sessions <= start:
            return 1.0
        factor = min(self.config.hb_stretch_max,
                     sessions / float(start))
        _metrics.gauge("swarm_dispatcher_hb_stretch", factor)
        return factor

    def _count_shed(self, n: int) -> None:
        """Every admission shed is COUNTED before it is raised — the
        overload-sheds-are-counted-and-recovered invariant audits the
        client-observed sheds against exactly this ledger."""
        if not self.count_sheds:
            return   # sensitivity seam: shed silently (the bug)
        self.stats["sheds"] += n
        _metrics.counter("swarm_dispatcher_sheds", n)
        _planes.plane(_planes.DISPATCHER).drop(n)

    def publish_logs(self, node_id: str, session_id: str,
                     messages) -> None:
        """Agent-side log publishing passthrough to the log broker
        (reference: logbroker.go PublishLogs; the broker is attached by
        the Manager).  Session-gated like every other agent-facing
        method: expired/orphaned agents must not keep injecting logs."""
        with self._mu:
            rn = self._nodes.get(node_id)
            if rn is None:
                raise ErrNodeNotRegistered(node_id)
            if rn.session_id != session_id:
                raise ErrSessionInvalid(node_id)
        broker = getattr(self, "log_broker", None)
        if broker is None:
            return
        from .logbroker import LogMessage
        broker.publish_logs([
            LogMessage(task_id=m["task_id"], node_id=m["node_id"],
                       stream=m.get("stream", "stdout"),
                       data=m["data"] if isinstance(m["data"], bytes)
                       else m["data"].encode())
            for m in messages])

    def heartbeat(self, node_id: str, session_id: str) -> float:
        """TTL refresh; returns the next period
        (reference: dispatcher.go:1317)."""
        period = self._heartbeat_period()
        window = period if self.stretch_extends_deadline \
            else period / self._stretch_factor()
        with self._mu:
            rn = self._nodes.get(node_id)
            if rn is None:
                raise ErrNodeNotRegistered(node_id)
            if rn.session_id != session_id:
                raise ErrSessionInvalid(node_id)
            rn.deadline = now() + window * self.config.grace_multiplier
            rn.promised_until = now() + period * \
                self.config.grace_multiplier
            self._push_deadline(rn.deadline, "hb", node_id)
        self.stats["heartbeats"] += 1
        _metrics.counter("swarm_dispatcher_heartbeats")
        return period

    def _check_session(self, node_id: str, session_id: str) -> None:
        with self._mu:
            rn = self._nodes.get(node_id)
        if rn is None:
            raise ErrNodeNotRegistered(node_id)
        if rn.session_id != session_id:
            raise ErrSessionInvalid(node_id)

    # ------------------------------------------------------- node up/down

    def _mark_node_ready(self, node_id: str, description, addr: str) -> None:
        with self._updates_lock:
            self._node_updates[node_id] = (
                NodeStatus(state=NodeState.READY, addr=addr), description)
        # readiness must not wait for the batching interval: orchestrators
        # treat DOWN nodes as invalid (reference marks ready synchronously)
        self._flush_updates()

    def _mark_node_not_ready(self, node_id: str, message: str) -> None:
        """Heartbeat expiry or disconnect: node DOWN
        (reference: dispatcher.go:1253)."""
        self.stats["expirations"] += 1
        _metrics.counter("swarm_dispatcher_heartbeat_expirations")
        with self._mu:
            rn = self._nodes.pop(node_id, None)
            self._down_nodes[node_id] = now()
            self._push_deadline(now() + self.config.orphan_timeout,
                                "orphan", node_id)
        if rn is not None:
            for stream in rn.streams:
                stream.close(ErrSessionInvalid(message))
        with self._updates_lock:
            self._node_updates[node_id] = (
                NodeStatus(state=NodeState.DOWN, message=message), None)
        self._flush_updates()

    def _move_tasks_to_orphaned(self, node_id: str) -> None:
        """reference: dispatcher.go:1209."""
        tasks = self.store.view(lambda tx: tx.find(Task, ByNode(node_id)))

        def cb(batch: Batch) -> None:
            for t in tasks:
                if t.status.state >= TaskState.ORPHANED:
                    continue

                def one(tx, t=t):
                    cur = tx.get(Task, t.id)
                    if cur is None or cur.status.state >= TaskState.ORPHANED:
                        return
                    cur = cur.copy()
                    cur.status = TaskStatus(state=TaskState.ORPHANED,
                                            timestamp=now(),
                                            message="node unreachable")
                    tx.update(cur)
                batch.update(one)

        try:
            self.write_store.batch(cb)
        except Exception:
            log.exception("moving tasks to orphaned failed")

    # --------------------------------------------------------- status intake

    def update_task_status(self, node_id: str, session_id: str,
                           updates: List[Tuple[str, TaskStatus]]) -> None:
        """Batched agent status writeback (reference: dispatcher.go:607)."""
        self._check_session(node_id, session_id)
        valid: List[Tuple[str, TaskStatus]] = []
        for task_id, status in updates:
            t = self.store.raw_get(Task, task_id)
            if t is None:
                continue  # task may have been deleted
            if t.node_id != node_id:
                raise DispatcherError(
                    "cannot update a task not assigned this node")
            valid.append((task_id, status))
        bound = self.config.max_pending_updates
        shed = 0
        with self._updates_lock:
            # admission check at the RPC edge: a batch that would
            # overflow the buffer is shed WHOLE (newest-rejected ==
            # oldest-first retention: buffered updates, already
            # admitted, are never dropped to make room).  Updates
            # rewriting an already-buffered task don't grow the buffer
            # and always land.
            if bound is not None and valid:
                growth = sum(1 for task_id, _ in valid
                             if task_id not in self._task_updates)
                if growth and len(self._task_updates) + growth > bound:
                    shed = len(valid)
            if not shed:
                for task_id, status in valid:
                    self._task_updates[task_id] = status
            n = len(self._task_updates)
        if shed:
            self._count_shed(shed)
            raise ErrOverloaded(
                f"status buffer at bound {bound}: shed {shed} updates "
                f"from node {node_id}")
        if n >= self.config.max_batch_items:
            self._flush_updates()

    def update_volume_status(self, node_id: str, session_id: str,
                             updates) -> None:
        """Agents report node-side volume unpublish completion; the next
        batch moves those volumes from PENDING_NODE_UNPUBLISH to
        PENDING_UNPUBLISH so the CSI manager can controller-unpublish
        (reference: dispatcher.go:682 UpdateVolumeStatus).
        ``updates``: iterable of (volume_id, unpublished: bool)."""
        self._check_session(node_id, session_id)
        with self._updates_lock:
            for volume_id, unpublished in updates:
                if unpublished:
                    self._unpublished_volumes.add((volume_id, node_id))

    def _flush_updates(self) -> None:
        """reference: dispatcher.go:726 processUpdates."""
        with self._updates_lock:
            task_updates, self._task_updates = self._task_updates, {}
            node_updates, self._node_updates = self._node_updates, {}
            unpublished = self._unpublished_volumes
            self._unpublished_volumes = set()
        if not task_updates and not node_updates and not unpublished:
            return
        _metrics.counter("swarm_dispatcher_task_status_updates",
                         len(task_updates))
        _flush_t0 = time.perf_counter()

        def cb(batch: Batch) -> None:
            for task_id, status in task_updates.items():
                def one(tx, task_id=task_id, status=status):
                    t = tx.get(Task, task_id)
                    if t is None:
                        return
                    if t.status.state > status.state:
                        return  # invalid transition
                    if (t.status.state == status.state
                            and t.status.message == status.message
                            and t.status.err == status.err):
                        return
                    t = t.copy()
                    status = status.copy()
                    status.applied_at = now()
                    t.status = status
                    tx.update(t)
                batch.update(one)
            for node_id, (status, description) in node_updates.items():
                def one_n(tx, node_id=node_id, status=status,
                          description=description):
                    n = tx.get(Node, node_id)
                    if n is None:
                        return
                    n = n.copy()
                    if status is not None:
                        n.status.state = status.state
                        n.status.message = status.message
                        if status.addr:
                            n.status.addr = status.addr
                    if description is not None:
                        n.description = description
                    tx.update(n)
                batch.update(one_n)
            for volume_id, v_node in unpublished:
                def one_v(tx, volume_id=volume_id, v_node=v_node):
                    from ..models.types import VolumePublishStatus
                    v = tx.get(Volume, volume_id)
                    if v is None:
                        return
                    changed = requeue = False
                    v = v.copy()
                    for ps in v.publish_status:
                        if ps.node_id != v_node:
                            continue
                        if ps.state == (VolumePublishStatus.State
                                        .PENDING_NODE_UNPUBLISH):
                            ps.state = (VolumePublishStatus.State
                                        .PENDING_UNPUBLISH)
                            changed = True
                        elif ps.state == \
                                VolumePublishStatus.State.PUBLISHED:
                            # agent reported before the scheduler freed
                            # the volume: keep the report for a later
                            # flush instead of losing it
                            requeue = True
                    if requeue:
                        with self._updates_lock:
                            self._unpublished_volumes.add(
                                (volume_id, v_node))
                    if changed:
                        tx.update(v)
                batch.update(one_v)

        try:
            self.write_store.batch(cb)
        except Exception as e:
            from ..state.raft.node import NotLeader, ProposalDropped
            if isinstance(e, (DispatcherError, NotLeader,
                              ProposalDropped)):
                # forwarding gap (follower mode during a leaderless
                # window / a deposal mid-write): re-queue so the next
                # flush retries instead of losing the statuses.  Newest
                # wins: an update buffered since the pop supersedes the
                # failed one.
                log.warning("dispatcher update batch deferred "
                            "(no leader): re-queued")
                with self._updates_lock:
                    for task_id, status in task_updates.items():
                        self._task_updates.setdefault(task_id, status)
                    for node_id, pair in node_updates.items():
                        self._node_updates.setdefault(node_id, pair)
                    self._unpublished_volumes |= unpublished
            else:
                # anything else is a poisoned item or a store bug:
                # dropping it (with the trace) beats starving every
                # later batch on an eternal retry
                log.exception("dispatcher update batch failed")
        self._flush_timer.observe(time.perf_counter() - _flush_t0)

    # ------------------------------------------------------------ worker

    def _push_deadline(self, deadline: float, kind: str,
                       node_id: str) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (deadline, self._seq, kind, node_id))

    def _worker_loop(self) -> None:
        last_flush = now()
        while not self._stop.is_set():
            interval = self.config.process_updates_interval
            with self._mu:
                deadline = self._heap[0][0] if self._heap else None
            timeout = interval if deadline is None else \
                max(0.0, min(interval, deadline - now()))
            self._stop.wait(timeout=timeout)
            ts = now()
            self.process_deadlines(ts)
            if ts - last_flush >= interval:
                self._flush_updates()
                last_flush = ts

    def process_deadlines(self, ts: Optional[float] = None) -> None:
        """Fire every deadline (heartbeat TTL, registration grace, orphan
        timeout) due at ``ts``, and apply pending cluster-config events.
        Called by the worker thread each wakeup; the deterministic
        simulator calls it directly under virtual time instead of running
        the worker thread."""
        if ts is None:
            ts = now()
        # apply live cluster-config changes (and resync on restore)
        sub = getattr(self, "_cluster_sub", None)
        while sub is not None:
            ev = sub.poll()
            if ev is None:
                break
            if isinstance(ev, EventSnapshotRestore):
                self._load_cluster_config()
            else:
                self._apply_cluster_config(ev.obj)
        # heartbeat expirations + orphan deadlines
        while True:
            with self._mu:
                if not self._heap or self._heap[0][0] > ts:
                    break
                _, _, kind, node_id = heapq.heappop(self._heap)
                if kind == "hb":
                    rn = self._nodes.get(node_id)
                    expired = rn is not None and rn.deadline <= ts
                    if expired and rn.promised_until > ts:
                        # the node is being DOWNed INSIDE the window the
                        # dispatcher promised it (a stretch the deadline
                        # forgot) — the liveness invariant reads this
                        self.stats["premature_expirations"] += 1
                        _metrics.counter(
                            "swarm_dispatcher_premature_expirations")
                elif kind == "reg":
                    # registration grace after a leadership change; the
                    # ownership veto keeps a sharded dispatcher from
                    # DOWNing a node with a live session elsewhere
                    expired = node_id not in self._nodes \
                        and (self.reg_grace_check is None
                             or self.reg_grace_check(node_id))
                else:
                    down_since = self._down_nodes.get(node_id)
                    expired = (down_since is not None
                               and ts - down_since
                               >= self.config.orphan_timeout)
                    if expired:
                        del self._down_nodes[node_id]
            if kind == "hb" and expired:
                log.info("heartbeat expiration for worker %s", node_id)
                self._mark_node_not_ready(node_id, "heartbeat failure")
            elif kind == "reg" and expired:
                log.info("node %s never registered after leadership "
                         "change", node_id)
                self._mark_node_not_ready(
                    node_id, "node did not re-register after "
                    "leadership change")
            elif kind == "orphan" and expired:
                self._move_tasks_to_orphaned(node_id)
        if self.fanout is not None:
            self.fanout.flush()

    # ---------------------------------------------------------- assignments

    def open_assignments(self, node_id: str,
                         session_id: str) -> AssignmentStream:
        """Start an assignments stream for the node
        (reference: dispatcher.go:1013).  With the batched fan-out
        enabled there is no per-stream thread — diffs flow through the
        shared flush pass."""
        if self.fanout is not None:
            return self.fanout.open(node_id, session_id)
        self._check_session(node_id, session_id)
        stream = AssignmentStream(node_id)
        with self._mu:
            rn = self._nodes.get(node_id)
            if rn is None or rn.session_id != session_id:
                raise ErrSessionInvalid(node_id)
            rn.streams.append(stream)
        t = threading.Thread(
            target=self._assignments_loop, args=(stream, node_id, session_id),
            name=f"assignments-{node_id[:8]}", daemon=True)
        t.start()
        return stream

    def _assignments_loop(self, stream: AssignmentStream, node_id: str,
                          session_id: str) -> None:
        aset = _AssignmentSet(node_id,
                              driver_provider=self.driver_provider,
                              terminal_bound=self.config
                              .max_terminal_tasks)
        sequence = 0
        applies_to = ""

        def send(type_) -> None:
            nonlocal sequence, applies_to
            sequence += 1
            results_in = str(sequence)
            # diff build (assignments.go message assembly) + delivery
            t0 = time.perf_counter()
            with tracer.span("dispatcher.assignments_send", "dispatcher",
                             type=type_) as sp:
                msg = aset.message(type_, applies_to, results_in)
                if sp is not None:
                    sp.args["changes"] = len(msg.changes)
                stream._push(msg)
            self._build_timer.observe(time.perf_counter() - t0)
            _metrics.counter(
                f'swarm_dispatcher_assignments_sent{{type="{type_}"}}')
            _metrics.counter("swarm_dispatcher_assignment_changes",
                             len(msg.changes))
            _note_assignment_ship(msg)
            applies_to = results_in

        def pred(ev):
            if isinstance(ev, EventTaskBlock):
                # deliver every block; the session loop probes its own
                # node against the block's shared per-node grouping on
                # the CONSUMER thread — predicates run on the committing
                # writer's thread, which must stay O(1) per subscriber
                return True
            if not isinstance(ev, Event):
                return False
            if isinstance(ev.obj, Volume):
                return True   # filtered against tracked deps in the loop
            return (isinstance(ev.obj, Task)
                    and ev.obj.node_id == node_id)

        def init(tx):
            return list(tx.find(Task, ByNode(node_id)))

        try:
            initial, sub = self.store.view_and_watch(init, predicate=pred,
                                                     accepts_blocks=True)
        except Exception as e:
            stream.close(e)
            return
        # dependency assembly — including possibly-slow driver-secret
        # plugin fetches — runs OUTSIDE view_and_watch's init callback:
        # init holds the store's update lock, and a slow (or store-
        # calling) provider plugin must not stall or deadlock every
        # store write.  Events queued since the snapshot replay after
        # and re-adds are idempotent.
        tx0 = self.store.view()
        for t in initial:
            aset.add_or_update_task(tx0, t)
        try:
            send(AssignmentsMessage.COMPLETE)
            cfg = self.config
            while not stream.closed and not self._stop.is_set():
                try:
                    self._check_session(node_id, session_id)
                except DispatcherError as e:
                    stream.close(e)
                    return
                modifications = 0
                deadline = None
                while modifications < cfg.modification_batch_limit:
                    if stream.closed or self._stop.is_set():
                        return
                    timeout = 0.2 if deadline is None else \
                        max(0.0, min(0.2, deadline - now()))
                    try:
                        ev = sub.get(timeout=timeout) if timeout > 0 \
                            else None
                    except TimeoutError:
                        if deadline is None:
                            if aset.pending_secret_retry and \
                                    aset.retry_pending_secrets(
                                        self.store.view()):
                                modifications += 1
                                deadline = now() + \
                                    cfg.assignment_batching_wait
                            continue
                        ev = None
                    except Closed:
                        stream.close()
                        return
                    if ev is None:
                        if deadline is not None and now() >= deadline:
                            break
                        if aset.pending_secret_retry and \
                                aset.retry_pending_secrets(
                                    self.store.view()):
                            modifications += 1
                            deadline = now() + \
                                cfg.assignment_batching_wait
                        continue
                    if isinstance(ev, EventTaskBlock):
                        # scheduler block: only this node's slice matters;
                        # raw_get materializes each task lazily from the
                        # store overlay (the same object every reader sees)
                        tx = self.store.view()
                        modified = False
                        for old, _ver in ev.per_node().get(node_id, ()):
                            t = self.store.raw_get(Task, old.id)
                            if t is None:
                                continue
                            modified |= aset.add_or_update_task(tx, t)
                        if modified:
                            modifications += 1
                            deadline = now() + cfg.assignment_batching_wait
                        continue
                    t = ev.obj
                    if isinstance(t, Volume):
                        modified = (ev.action != "delete"
                                    and aset.update_volume(t))
                    elif ev.action == "delete":
                        modified = aset.remove_task(t)
                    else:
                        tx = self.store.view()
                        modified = aset.add_or_update_task(tx, t)
                    if modified:
                        modifications += 1
                        deadline = now() + cfg.assignment_batching_wait
                    if stream.closed or self._stop.is_set():
                        return
                if modifications > 0:
                    send(AssignmentsMessage.INCREMENTAL)
        finally:
            self.store.queue.unsubscribe(sub)
