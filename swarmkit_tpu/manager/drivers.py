"""Driver-backed secrets: fetch secret values from external provider
plugins at assignment time instead of the store payload.

Reference: manager/drivers/provider.go (DriverProvider) and secrets.go
(SecretDriver.Get posting a SecretsProviderRequest to the plugin's
``/SecretProvider.GetSecret`` endpoint).  Plugins register as
name -> endpoint URL (the reference resolves docker plugin sockets; the
wire payload is identical) or name -> callable for in-process providers.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Tuple, Union

log = logging.getLogger("drivers")

SECRETS_PROVIDER_API = "/SecretProvider.GetSecret"


class SecretProviderError(Exception):
    """The plugin failed, rejected the request, or is not registered."""


Plugin = Union[str, Callable[[dict], dict]]


class SecretDriver:
    """reference: drivers/secrets.go:21 SecretDriver."""

    def __init__(self, plugin: Plugin):
        self._plugin = plugin

    def get(self, spec, task) -> Tuple[bytes, bool]:
        """Fetch the secret value for one task; returns
        (value, do_not_reuse) (reference: secrets.go:34 Get)."""
        if spec is None:
            raise SecretProviderError("secret spec is nil")
        if task is None:
            raise SecretProviderError("task is nil")
        container = task.spec.container
        req = {
            "SecretName": spec.annotations.name,
            "SecretLabels": dict(spec.annotations.labels),
            "ServiceID": task.service_id,
            "ServiceName": task.service_annotations.name,
            "ServiceLabels": dict(task.service_annotations.labels),
            "TaskID": task.id,
            "TaskName": f"{task.service_annotations.name}.{task.slot}"
                        f".{task.id}",
            "TaskImage": container.image if container else "",
            "ServiceHostname": container.hostname if container else "",
            "NodeID": task.node_id,
        }
        resp = self._call(req)
        if resp.get("Err"):
            raise SecretProviderError(resp["Err"])
        value = resp.get("Value")
        if value is None:
            raise SecretProviderError(
                "secret provider returned no value")
        if isinstance(value, str):
            value = base64.b64decode(value)
        return value, bool(resp.get("DoNotReuse", False))

    def _call(self, req: dict) -> dict:
        if callable(self._plugin):
            return self._plugin(req)
        url = self._plugin.rstrip("/") + SECRETS_PROVIDER_API
        data = json.dumps(req).encode()
        http_req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(http_req, timeout=5) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise SecretProviderError(
                f"secret provider {url} failed: {e}") from e


class DriverProvider:
    """reference: drivers/provider.go:11 — resolves a spec Driver to a
    SecretDriver backed by a registered provider plugin."""

    def __init__(self, plugins: Optional[Dict[str, Plugin]] = None):
        self._plugins = dict(plugins or {})

    def register(self, name: str, plugin: Plugin) -> None:
        self._plugins[name] = plugin

    def new_secret_driver(self, driver) -> SecretDriver:
        if driver is None or not driver.name:
            raise SecretProviderError("driver specification is nil")
        plugin = self._plugins.get(driver.name)
        if plugin is None:
            raise SecretProviderError(
                f"plugin {driver.name!r} not found")
        return SecretDriver(plugin)
