"""Key manager: rotates cluster-wide dataplane encryption keys.

Reference: manager/keymanager/keymanager.go (:22-45 config, :124 rotateKey,
:173 Run).

Maintains one key per subsystem (gossip/IPSec-equivalents) in the cluster
object's ``network_bootstrap_keys``, keeping the last two keys per
subsystem (current + previous, so agents can roll over), stamped with a
lamport clock; rotates on a configurable period.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from ..models.objects import Cluster
from ..models.types import EncryptionKey
from ..state.store import ByName, MemoryStore

log = logging.getLogger("keymanager")

DEFAULT_KEY_LEN = 16
DEFAULT_ROTATION_INTERVAL = 12 * 3600.0   # reference: keymanager.go:30
SUBSYSTEM_GOSSIP = "networking:gossip"
SUBSYSTEM_IPSEC = "networking:ipsec"


@dataclass
class Config:
    cluster_name: str = "default"
    keylen: int = DEFAULT_KEY_LEN
    rotation_interval: float = DEFAULT_ROTATION_INTERVAL
    subsystems: List[str] = field(
        default_factory=lambda: [SUBSYSTEM_GOSSIP, SUBSYSTEM_IPSEC])


class KeyManager:
    def __init__(self, store: MemoryStore, config: Optional[Config] = None):
        self.store = store
        self.config = config or Config()
        self.keys: List[EncryptionKey] = []
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _new_key(self, subsystem: str, lamport: int) -> EncryptionKey:
        # overlay encryption keys are cryptographic material: they must
        # come from the OS CSPRNG, never a seeded/simulated source
        # swarmlint: disable=determinism-seam
        key = os.urandom(self.config.keylen)
        return EncryptionKey(subsystem=subsystem, algorithm=0,
                             key=key, lamport_time=lamport)

    def rotate_now(self) -> None:
        """One rotation pass (reference: rotateKey :124)."""
        def cb(tx):
            clusters = tx.find(Cluster, ByName(self.config.cluster_name))
            if not clusters:
                return
            cluster = clusters[0].copy()
            clock = cluster.encryption_key_lamport_clock + 1
            keys = list(cluster.network_bootstrap_keys)
            for subsys in self.config.subsystems:
                subsys_keys = [k for k in keys if k.subsystem == subsys]
                # keep only the newest old key + the fresh one
                subsys_keys.sort(key=lambda k: -k.lamport_time)
                keep = subsys_keys[:1]
                keys = [k for k in keys if k.subsystem != subsys]
                keys.extend(keep)
                keys.append(self._new_key(subsys, clock))
            cluster.network_bootstrap_keys = keys
            cluster.encryption_key_lamport_clock = clock
            tx.update(cluster)
            self.keys = keys

        try:
            self.store.update(cb)
        except Exception:
            log.exception("key rotation failed")

    def run(self) -> None:
        try:
            # ensure keys exist at startup
            def need_keys(tx):
                clusters = tx.find(Cluster, ByName(self.config.cluster_name))
                return bool(clusters) and \
                    not clusters[0].network_bootstrap_keys

            if self.store.view(need_keys):
                self.rotate_now()
            while not self._stop.wait(
                    timeout=self.config.rotation_interval):
                self.rotate_now()
        finally:
            self._done.set()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="keymanager",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=5)
