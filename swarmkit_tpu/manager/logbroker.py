"""Log broker: connects log subscribers (users) to log publishers (agents).

Reference: manager/logbroker/{broker.go,subscription.go}.

``subscribe_logs`` registers a selector (services/tasks/nodes) and returns
a stream; agents listening via ``listen_subscriptions`` are told which
tasks to start publishing for, and push messages through ``publish_logs``,
which the broker fans out to matching subscribers.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..models.objects import Task
from ..state.store import ByNode, ByService, MemoryStore
from ..state.watch import Closed, Queue, Subscription
from ..utils import new_id

log = logging.getLogger("logbroker")


@dataclass
class LogSelector:
    """reference: api/logbroker.proto LogSelector."""

    service_ids: List[str] = field(default_factory=list)
    task_ids: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)


@dataclass
class LogMessage:
    task_id: str
    node_id: str
    stream: str       # "stdout" | "stderr"
    data: bytes
    timestamp: float = 0.0


@dataclass
class LogSubscriptionOptions:
    """reference: api/logbroker.proto:26 LogSubscriptionOptions.

    ``tail``: <0 = whole history, 0 = no history (follow only), N>0 =
    last N messages per task.  ``since``: unix seconds; only messages
    stamped at/after it replay.  ``streams``: restrict to stdout/stderr.
    """

    streams: List[str] = field(default_factory=list)
    follow: bool = True
    tail: int = -1
    since: float = 0.0


@dataclass
class SubscriptionMessage:
    """Told to agents: start/stop publishing for these tasks."""

    id: str
    selector: LogSelector
    close: bool = False


class _LogSubscription:
    def __init__(self, broker: "LogBroker", selector: LogSelector,
                 options: LogSubscriptionOptions):
        self.id = new_id()
        self.broker = broker
        self.selector = selector
        self.options = options
        self.stream = Queue()
        self._sub = self.stream.subscribe()
        # number of history-replay messages queued at subscribe time;
        # consumers drain exactly this many before the live window so a
        # fast producer can't extend the drain phase unboundedly
        self.backlog_count = 0

    def matches(self, msg: LogMessage, task: Optional[Task]) -> bool:
        opts = self.options
        if opts.streams and msg.stream not in opts.streams:
            return False
        sel = self.selector
        if msg.task_id in sel.task_ids:
            return True
        if msg.node_id in sel.node_ids:
            return True
        if task is not None and task.service_id in sel.service_ids:
            return True
        return False

    def get(self, timeout: Optional[float] = None) -> LogMessage:
        return self._sub.get(timeout=timeout)

    def close(self) -> None:
        self.broker._remove_subscription(self)


class LogBroker:
    """reference: broker.go:52."""

    #: per-task history budget for tail/since replay (bytes of log data)
    HISTORY_BYTES_PER_TASK = 256 << 10

    def __init__(self, store: MemoryStore):
        self.store = store
        self._mu = threading.Lock()
        self._subscriptions: Dict[str, _LogSubscription] = {}
        self._listeners = Queue()   # agents following subscription changes
        # bounded per-task message history so tail/since subscriptions
        # can replay recent output.  The reference reads history from the
        # source (the container runtime's log storage, dockerexec
        # controller); here agents ship from task start and the broker
        # retains a byte-budgeted ring per task — same operator-visible
        # semantics within the budget, bounded memory on the manager
        self._history: Dict[str, List[LogMessage]] = {}
        self._history_bytes: Dict[str, int] = {}
        self._prune_tick = 0

    # ------------------------------------------------------------- consumers

    def subscribe_logs(self, selector: LogSelector,
                       follow: bool = True,
                       options: Optional[LogSubscriptionOptions] = None
                       ) -> _LogSubscription:
        """reference: broker.go:223 SubscribeLogs.  Holds the broker lock
        across backlog replay + registration so a concurrent
        publish_logs can neither be missed nor duplicated."""
        if options is None:
            options = LogSubscriptionOptions(follow=follow)
        sub = _LogSubscription(self, selector, options)
        with self._mu:
            backlog = self._backlog_locked(sub)
            sub.backlog_count = len(backlog)
            for msg in backlog:
                sub.stream.publish(msg)
            if options.follow:
                self._subscriptions[sub.id] = sub
            else:
                sub.stream.close()
        if options.follow:
            self._listeners.publish(SubscriptionMessage(sub.id, selector))
        return sub

    def _backlog_locked(self, sub: _LogSubscription) -> List[LogMessage]:
        """History replay per the subscription's options (tail/since/
        streams), grouped per task in arrival order."""
        opts = sub.options
        if opts.tail == 0:
            return []
        out: List[LogMessage] = []
        for task_id, msgs in self._history.items():
            task = self.store.raw_get(Task, task_id)
            picked = [m for m in msgs if sub.matches(m, task)
                      and (opts.since <= 0
                           or m.timestamp >= opts.since)]
            if opts.tail > 0:
                picked = picked[-opts.tail:]
            out.extend(picked)
        return out

    def _remove_subscription(self, sub: _LogSubscription) -> None:
        with self._mu:
            self._subscriptions.pop(sub.id, None)
        self._listeners.publish(
            SubscriptionMessage(sub.id, sub.selector, close=True))
        sub.stream.close()

    # -------------------------------------------------------------- agents

    def listen_subscriptions(self) -> Subscription:
        """Agents follow this to learn what to publish
        (reference: broker.go:305); current subscriptions are replayed."""
        listener = self._listeners.subscribe()
        with self._mu:
            current = list(self._subscriptions.values())
        for sub in current:
            listener._publish(SubscriptionMessage(sub.id, sub.selector))
        return listener

    def stop_listening(self, listener: Subscription) -> None:
        self._listeners.unsubscribe(listener)

    def publish_logs(self, messages: List[LogMessage]) -> None:
        """Agent-side ingest (reference: broker.go:379 PublishLogs)."""
        from ..models.types import now
        with self._mu:
            subs = list(self._subscriptions.values())
            for msg in messages:
                if not msg.timestamp:
                    msg.timestamp = now()
                ring = self._history.setdefault(msg.task_id, [])
                ring.append(msg)
                used = self._history_bytes.get(msg.task_id, 0) \
                    + len(msg.data)
                while used > self.HISTORY_BYTES_PER_TASK and ring:
                    used -= len(ring.pop(0).data)
                self._history_bytes[msg.task_id] = used
            self._prune_tick += 1
            if self._prune_tick >= 256:
                # long-lived managers: drop rings for tasks the store no
                # longer knows (reaped); active tasks keep their history.
                # Interval-gated (every 256 ingests) so the scan doesn't
                # rerun on every batch under the lock; unconditional on
                # ring count — ≤1024 dead rings still pin up to 256MiB
                self._prune_tick = 0
                for tid in list(self._history):
                    if self.store.raw_get(Task, tid) is None:
                        del self._history[tid]
                        self._history_bytes.pop(tid, None)
        for msg in messages:
            task = self.store.raw_get(Task, msg.task_id)
            for sub in subs:
                if sub.matches(msg, task):
                    sub.stream.publish(msg)

    def close(self) -> None:
        with self._mu:
            subs = list(self._subscriptions.values())
            self._subscriptions.clear()
        for sub in subs:
            sub.stream.close()
        self._listeners.close()
