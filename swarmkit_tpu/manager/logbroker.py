"""Log broker: connects log subscribers (users) to log publishers (agents).

Reference: manager/logbroker/{broker.go,subscription.go}.

``subscribe_logs`` registers a selector (services/tasks/nodes) and returns
a stream; agents listening via ``listen_subscriptions`` are told which
tasks to start publishing for, and push messages through ``publish_logs``,
which the broker fans out to matching subscribers.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..models.objects import Task
from ..state.store import ByNode, ByService, MemoryStore
from ..state.watch import Closed, Queue, Subscription
from ..utils import new_id

log = logging.getLogger("logbroker")


@dataclass
class LogSelector:
    """reference: api/logbroker.proto LogSelector."""

    service_ids: List[str] = field(default_factory=list)
    task_ids: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)


@dataclass
class LogMessage:
    task_id: str
    node_id: str
    stream: str       # "stdout" | "stderr"
    data: bytes
    timestamp: float = 0.0


@dataclass
class SubscriptionMessage:
    """Told to agents: start/stop publishing for these tasks."""

    id: str
    selector: LogSelector
    close: bool = False


class _LogSubscription:
    def __init__(self, broker: "LogBroker", selector: LogSelector,
                 follow: bool):
        self.id = new_id()
        self.broker = broker
        self.selector = selector
        self.follow = follow
        self.stream = Queue()
        self._sub = self.stream.subscribe()

    def matches(self, msg: LogMessage, task: Optional[Task]) -> bool:
        sel = self.selector
        if msg.task_id in sel.task_ids:
            return True
        if msg.node_id in sel.node_ids:
            return True
        if task is not None and task.service_id in sel.service_ids:
            return True
        return False

    def get(self, timeout: Optional[float] = None) -> LogMessage:
        return self._sub.get(timeout=timeout)

    def close(self) -> None:
        self.broker._remove_subscription(self)


class LogBroker:
    """reference: broker.go:52."""

    def __init__(self, store: MemoryStore):
        self.store = store
        self._mu = threading.Lock()
        self._subscriptions: Dict[str, _LogSubscription] = {}
        self._listeners = Queue()   # agents following subscription changes

    # ------------------------------------------------------------- consumers

    def subscribe_logs(self, selector: LogSelector,
                       follow: bool = True) -> _LogSubscription:
        """reference: broker.go:223 SubscribeLogs."""
        sub = _LogSubscription(self, selector, follow)
        with self._mu:
            self._subscriptions[sub.id] = sub
        self._listeners.publish(SubscriptionMessage(sub.id, selector))
        return sub

    def _remove_subscription(self, sub: _LogSubscription) -> None:
        with self._mu:
            self._subscriptions.pop(sub.id, None)
        self._listeners.publish(
            SubscriptionMessage(sub.id, sub.selector, close=True))
        sub.stream.close()

    # -------------------------------------------------------------- agents

    def listen_subscriptions(self) -> Subscription:
        """Agents follow this to learn what to publish
        (reference: broker.go:305); current subscriptions are replayed."""
        listener = self._listeners.subscribe()
        with self._mu:
            current = list(self._subscriptions.values())
        for sub in current:
            listener._publish(SubscriptionMessage(sub.id, sub.selector))
        return listener

    def stop_listening(self, listener: Subscription) -> None:
        self._listeners.unsubscribe(listener)

    def publish_logs(self, messages: List[LogMessage]) -> None:
        """Agent-side ingest (reference: broker.go:379 PublishLogs)."""
        with self._mu:
            subs = list(self._subscriptions.values())
        if not subs:
            return
        for msg in messages:
            task = self.store.raw_get(Task, msg.task_id)
            for sub in subs:
                if sub.matches(msg, task):
                    sub.stream.publish(msg)

    def close(self) -> None:
        with self._mu:
            subs = list(self._subscriptions.values())
            self._subscriptions.clear()
        for sub in subs:
            sub.stream.close()
        self._listeners.close()
