"""Manager composition: wires every control-plane component and drives
their lifecycle from raft leadership.

Reference: manager/manager.go — server registration :475-563, becomeLeader
:927-1147 / becomeFollower :1150, default cluster/node creation :952-1011,
role manager, cluster-spec watching :801.

All control loops (allocator, scheduler, orchestrators, reaper, enforcers,
keymanager, dispatcher) run **only on the raft leader**; followers keep
only the store + raft + serving surfaces.  In standalone mode (no raft)
the manager is always the leader.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..models.objects import Cluster, Node
from ..models.specs import ClusterSpec
from ..models.types import Annotations, NodeRole
from ..ops import TPUPlanner
from ..orchestrator import (
    ConstraintEnforcer, GlobalOrchestrator, JobsOrchestrator,
    ReplicatedOrchestrator, RestartSupervisor, TaskReaper, VolumeEnforcer,
)
from ..scheduler import Scheduler
from ..security.ca import CAServer, RootCA
from ..state.events import Event, EventSnapshotRestore
from ..state.store import ByName, MemoryStore
from ..utils import new_id
from .allocator import Allocator
from .controlapi import ControlAPI
from .dispatcher import Config_ as DispatcherConfig, Dispatcher
from .keymanager import KeyManager
from .logbroker import LogBroker
from .metrics import Collector
from .rolemanager import RoleManager
from .watchapi import WatchServer

log = logging.getLogger("manager")

DEFAULT_CLUSTER_NAME = "default"


class Manager:
    def __init__(self, store: Optional[MemoryStore] = None,
                 raft_node=None, node_id: Optional[str] = None,
                 root_ca: Optional[RootCA] = None,
                 dispatcher_config: Optional[DispatcherConfig] = None,
                 use_device_scheduler: bool = True,
                 csi_plugins: Optional[dict] = None,
                 secret_plugins: Optional[dict] = None,
                 scheduler_pipeline_depth: Optional[int] = None):
        """``raft_node``: a state.raft.RaftNode already wired as the
        store's proposer, or None for standalone single-manager mode.
        ``csi_plugins``: name -> CSIPlugin for the CSI controller manager
        (an in-memory plugin named "inmem" is always available).
        ``secret_plugins``: name -> endpoint URL or callable for
        driver-backed secrets (reference: manager/drivers).
        ``scheduler_pipeline_depth``: plan/commit pipeline depth for the
        scheduler (None -> SWARM_PIPELINE_DEPTH, default 2; 1 = serial
        escape hatch)."""
        self.node_id = node_id or new_id()
        self._scheduler_pipeline_depth = scheduler_pipeline_depth
        self.raft = raft_node
        self.store = store if store is not None else (
            raft_node.store if raft_node is not None else MemoryStore())
        self.root_ca = root_ca or RootCA()
        self.use_device_scheduler = use_device_scheduler
        self._dispatcher_config = dispatcher_config or DispatcherConfig()

        # always-on surfaces (follower-safe; mutations go through the
        # store's proposer so they fail on non-leaders)
        self.control_api = ControlAPI(self.store)
        self.control_api.root_ca = self.root_ca
        self.control_api.health = self.health_check
        self.watch_server = WatchServer(self.store)
        from .drivers import DriverProvider
        self.driver_provider = DriverProvider(secret_plugins)
        self.logbroker = LogBroker(self.store)
        self.ca_server = CAServer(self.root_ca)
        self.collector = Collector(self.store)
        from ..obs import LifecycleTracker, Sampler
        from ..obs.health import evaluator as _health_evaluator
        self.lifecycle = LifecycleTracker(self.store)
        # health/SLO plane + black box: the sampler thread snapshots the
        # registry into the flight recorder and re-judges the SLO checks
        # every interval; /debug/health and /debug/flightrec serve the
        # same shared singletons
        self.sampler = Sampler()
        self.health = _health_evaluator
        self.obs_sample_interval = 2.0

        # leader-only loops, created on become_leader
        self.dispatcher: Optional[Dispatcher] = None
        self.allocator: Optional[Allocator] = None
        self.scheduler: Optional[Scheduler] = None
        self.replicated: Optional[ReplicatedOrchestrator] = None
        self.global_: Optional[GlobalOrchestrator] = None
        self.jobs: Optional[JobsOrchestrator] = None
        self.reaper: Optional[TaskReaper] = None
        self.constraint_enforcer: Optional[ConstraintEnforcer] = None
        self.volume_enforcer: Optional[VolumeEnforcer] = None
        self.keymanager: Optional[KeyManager] = None
        self.role_manager: Optional[RoleManager] = None
        self.csi_manager = None
        self._csi_plugins = dict(csi_plugins or {})

        self._mu = threading.Lock()
        self._running = False
        self._is_leader = False
        # advertised raft-transport addresses of known managers, exchanged
        # through the raft_join RPC so joining managers can dial peers
        self.raft_peer_addrs: dict = {}
        # this manager's own (and any locally-known) remote-API addresses;
        # merged with the raft-replicated set in manager_api_addrs()
        self.api_addrs: dict = {}
        # leadership transitions apply strictly in arrival order: raft can
        # flap faster than loops start/stop, and out-of-order application
        # would leave a live leader with its control loops stopped
        import queue as _queue
        self._leadership_q: "_queue.Queue" = _queue.Queue()
        self._leadership_worker: Optional[threading.Thread] = None
        # fires after a root rotation finalizes (swarmd re-keys the WAL)
        self.on_root_rotated = None
        self._stop_event = threading.Event()
        # fires on any cluster-object change (swarmd re-seals state when
        # the autolock flag/unlock key changes)
        self.on_cluster_changed = None
        self._rotation_thread: Optional[threading.Thread] = None
        self.ca_rotation_check_interval = 1.0

    # ------------------------------------------------------------- lifecycle

    def run(self) -> None:
        self._running = True
        self.collector.start()
        self.lifecycle.start()
        # black-box recording is always on for a live manager: recent
        # spans/samples/store events stay dumpable via /debug/flightrec
        # whatever happens later.  The crash hook turns an unhandled
        # exception in any control-loop thread into a dumped post-mortem
        # (path + sha logged) instead of a silently-dead daemon thread.
        from ..obs.flightrec import flightrec, install_crash_hook
        if not getattr(self, "_crash_hook_installed", False):
            install_crash_hook()
            self._crash_hook_installed = True
        flightrec.enabled = True
        flightrec.watch_store(self.store)
        # the journey ledger rides the recorder's store tap (one watch
        # consumer for both): every member minting milestones from
        # replicated stamps is what lets a journey survive failover
        # stitched (obs/journey.py)
        from ..obs.journey import journeys
        flightrec.journey_sink = journeys.handle_event
        journeys.enabled = True
        self.sampler.rebase()
        self.sampler.start(interval=self.obs_sample_interval,
                           on_sample=self.health.evaluate)
        if self.raft is None:
            self._ensure_cluster_object()
            self._become_leader()
        else:
            self.raft.on_leadership = self._on_leadership
            if self.raft.is_leader:
                self._on_leadership(True)
            # followers adopt replicated CA state (key + join tokens) as
            # the cluster object arrives/changes, so they can validate
            # join tokens and certs without ever having led (reference:
            # every manager loads the cluster's security config)
            self._ca_sub = self.store.queue.subscribe(
                lambda ev: isinstance(ev, EventSnapshotRestore)
                or (isinstance(ev, Event) and isinstance(ev.obj, Cluster)),
                accepts_blocks=True)   # blocks are never cluster events
            # baseline digest = the root the daemon booted with, so even
            # the FIRST adoption fires the re-key hook when the replayed
            # cluster state carries a different (rotated) root
            self._adopted_root_digest = self.root_ca.digest
            self._adopt_ca_state()
            self._ca_worker = threading.Thread(
                target=self._ca_adoption_loop, name="ca-adoption",
                daemon=True)
            self._ca_worker.start()
        self._running = True

    def _restore_root_from_state(self, state) -> None:
        """Adopt persisted trust-root material incl. any in-progress
        rotation (single source of truth for both adoption paths)."""
        self.root_ca.restore(state.ca_key, state.ca_cert)
        self.root_ca.restore_join_tokens(state.join_tokens)
        if state.root_rotation_in_progress and state.rotation_ca_key:
            self.root_ca.restore_rotation(
                state.rotation_ca_key, state.rotation_ca_cert,
                state.cross_signed_ca_cert)
        elif self.root_ca.rotation is not None:
            self.root_ca.rotation = None

    def _adopt_ca_state(self) -> None:
        clusters = self.store.view(
            lambda tx: tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)))
        if not clusters:
            return
        state = clusters[0].root_ca
        if state is not None and state.ca_key:
            # the baseline is the root the daemon BOOTED with (seeded in
            # run()): a restart that replays an already-finalized
            # rotation from the WAL must still re-key local material
            prev_digest = getattr(self, "_adopted_root_digest", None)
            self._restore_root_from_state(state)
            self._adopted_root_digest = self.root_ca.digest
            if (prev_digest is not None
                    and prev_digest != self._adopted_root_digest
                    and self.on_root_rotated is not None):
                try:
                    self.on_root_rotated()
                except Exception:
                    log.exception("root-rotation hook failed")

    def _ca_adoption_loop(self) -> None:
        while self._running:
            try:
                ev = self._ca_sub.get(timeout=0.5)
            except TimeoutError:
                continue
            except Exception:
                return   # queue closed (Closed) or shutdown
            if ev is None:
                continue
            try:
                self._adopt_ca_state()
                self._apply_ca_config()   # followers issue on renewal too
            except Exception:
                log.exception("CA state adoption failed")
            hook = self.on_cluster_changed
            if hook is not None:
                try:
                    hook()
                except Exception:
                    log.exception("cluster-change hook failed")

    def stop(self) -> None:
        self._running = False
        self._stop_event.set()
        if getattr(self, "_ca_sub", None) is not None:
            self.store.queue.unsubscribe(self._ca_sub)
            self._ca_sub = None
        self._become_follower()
        self.sampler.stop()
        from ..obs.flightrec import flightrec, uninstall_crash_hook
        # uninstall exactly the reference this instance took: a double
        # stop() (or stop() without run()) must not strip a co-resident
        # manager's hook out from under it (the ref count pairs 1:1)
        if getattr(self, "_crash_hook_installed", False):
            self._crash_hook_installed = False
            uninstall_crash_hook()
        flightrec.unwatch_store(self.store)
        self.collector.stop()
        self.lifecycle.stop()
        self.logbroker.close()

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def _on_leadership(self, leader: bool) -> None:
        """raft leadership callback (runs on the raft thread)."""
        with self._mu:
            if self._leadership_worker is None \
                    or not self._leadership_worker.is_alive():
                self._leadership_worker = threading.Thread(
                    target=self._leadership_loop, name="leadership",
                    daemon=True)
                self._leadership_worker.start()
        self._leadership_q.put(leader)

    def _leadership_loop(self) -> None:
        import queue as _queue
        while self._running or not self._leadership_q.empty():
            try:
                leader = self._leadership_q.get(timeout=0.5)
            except _queue.Empty:
                continue
            # collapse bursts to the latest state
            while True:
                try:
                    leader = self._leadership_q.get_nowait()
                except _queue.Empty:
                    break
            try:
                if leader:
                    self._become_leader_safe()
                else:
                    self._become_follower()
            except Exception:
                log.exception("leadership transition failed")

    def _become_leader_safe(self) -> None:
        try:
            self._ensure_cluster_object()
            self._become_leader()
        except Exception:
            log.exception("becoming leader failed")

    def _ensure_cluster_object(self) -> None:
        """Create the default cluster (+ its join tokens) on first
        leadership (reference: manager.go:952-1011)."""
        def cb(tx):
            existing = tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME))
            if existing:
                # adopt the persisted trust root: a fresh random RootCA
                # would invalidate every issued cert and join token
                state = existing[0].root_ca
                if state is not None and state.ca_key:
                    self._restore_root_from_state(state)
                return
            cluster = Cluster(
                id=new_id(),
                spec=ClusterSpec(annotations=Annotations(
                    name=DEFAULT_CLUSTER_NAME)))
            from ..models.objects import RootCAState
            from ..models.types import JoinTokens
            cluster.root_ca = RootCAState(
                ca_key=self.root_ca.key,
                ca_cert=self.root_ca.cert_pem,
                join_tokens=JoinTokens(
                    worker=self.root_ca.join_token(NodeRole.WORKER),
                    manager=self.root_ca.join_token(NodeRole.MANAGER)))
            tx.create(cluster)

        try:
            self.store.update(cb)
        except Exception:
            log.exception("ensuring cluster object failed")

    def _become_leader(self) -> None:
        """reference: manager.go:927 becomeLeader."""
        with self._mu:
            if self._is_leader:
                return
            self._is_leader = True
            log.info("manager %s became leader", self.node_id[:8])
            restarts = RestartSupervisor(self.store)
            self.dispatcher = Dispatcher(
                self.store, self._dispatcher_config,
                driver_provider=self.driver_provider)
            # agents publish task logs through their dispatcher surface;
            # the CLI reads them back via the control api
            self.dispatcher.log_broker = self.logbroker
            self.control_api.log_broker = self.logbroker
            self.dispatcher.run()
            self.allocator = Allocator(self.store)
            planner = TPUPlanner() if self.use_device_scheduler else None
            self.scheduler = Scheduler(
                self.store, batch_planner=planner,
                pipeline_depth=self._scheduler_pipeline_depth)
            self.replicated = ReplicatedOrchestrator(self.store,
                                                     restarts=restarts)
            self.global_ = GlobalOrchestrator(self.store, restarts=restarts)
            self.jobs = JobsOrchestrator(self.store, restarts=restarts)
            self.reaper = TaskReaper(self.store)
            self.constraint_enforcer = ConstraintEnforcer(self.store)
            self.volume_enforcer = VolumeEnforcer(self.store)
            self.keymanager = KeyManager(self.store)
            self.role_manager = RoleManager(self.store,
                                            raft_node=self.raft)
            # CSI controller manager: drives volume create/publish/delete
            # from store events (reference: manager.go:1077 csi manager).
            # Plugins come from the constructor; an in-memory plugin named
            # "inmem" is always registered so volume lifecycles are
            # drivable out of the box (the image has no real CSI drivers).
            from .csi import InMemoryCSIPlugin, Manager as CSIManager
            plugins = dict(self._csi_plugins)
            plugins.setdefault("inmem", InMemoryCSIPlugin("inmem"))
            self.csi_manager = CSIManager(self.store, plugins=plugins)
            from .deallocator import Deallocator
            self.deallocator = Deallocator(self.store)
            # horizontal autoscaler: production mode wraps one thread;
            # the deterministic sim builds its own threadless supervisor
            from ..orchestrator.autoscaler import (
                Supervisor as AutoscaleSupervisor,
            )
            self.autoscaler = AutoscaleSupervisor(self.store)
            self.autoscaler.start()
            for loop in (self.allocator, self.scheduler, self.replicated,
                         self.global_, self.jobs, self.reaper,
                         self.constraint_enforcer, self.volume_enforcer,
                         self.keymanager, self.role_manager,
                         self.csi_manager, self.deallocator):
                loop.start()
            if self._rotation_thread is None \
                    or not self._rotation_thread.is_alive():
                self._rotation_thread = threading.Thread(
                    target=self._ca_rotation_loop, name="ca-rotation",
                    daemon=True)
                self._rotation_thread.start()

    def _ca_rotation_loop(self) -> None:
        """Root-rotation reconciler (reference: ca/reconciler.go): while
        a rotation is in progress, wait for every live node's cert to
        chain to the new root (issuer digests recorded from the agents'
        TLS identities at heartbeat), then finalize — new root becomes
        THE root, tokens re-derive, and persisted state flips over."""
        while self._running and self._is_leader:
            try:
                self._apply_ca_config()
                if self.root_ca.rotation is not None:
                    self._reconcile_ca_rotation()
            except Exception:
                log.exception("CA rotation reconciliation failed")
            self._stop_event.wait(self.ca_rotation_check_interval)

    def _apply_ca_config(self) -> None:
        """Live-apply ClusterSpec.ca_config to the signing CA:
        node_cert_expiry and external signer URLs (reference:
        ca/server.go UpdateRootCA reacting to CAConfig, ca/external.go)."""
        clusters = self.store.view(
            lambda tx: tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME)))
        if not clusters:
            return
        cfg = clusters[0].spec.ca_config
        expiry = cfg.node_cert_expiry
        if expiry > 0 and expiry != self.root_ca.node_cert_expiry:
            log.info("node cert expiry set to %.0fs from cluster spec",
                     expiry)
            self.root_ca.node_cert_expiry = expiry
        urls = [u for u in (cfg.external_cas or []) if u]
        current = self.ca_server.external
        current_urls = current.urls if current is not None else []
        if urls != current_urls:
            if urls:
                from ..security.external import ExternalCA
                self.ca_server.external = ExternalCA(
                    urls, org=self.root_ca.org,
                    ca_cert_pem=self.root_ca.cert_pem)
                log.info("external CA signing enabled: %s", urls)
            else:
                self.ca_server.external = None
                log.info("external CA signing disabled")

    def _reconcile_ca_rotation(self) -> None:
        from ..models.types import NodeState
        target = self.root_ca.active_digest
        nodes = self.store.view(lambda tx: tx.find(Node))
        for n in nodes:
            if n.status.state == NodeState.DOWN:
                continue   # down nodes cannot renew; operators remove them
            if n.certificate_issuer != target:
                return   # still waiting
        log.info("root CA rotation complete; finalizing")
        rotation = self.root_ca.rotation
        if rotation is None:
            return
        new_key, new_cert, _ = rotation
        # persist FIRST, then flip the in-memory root: the CA-adoption
        # thread may interleave, and it must only ever observe either
        # the in-progress state or the fully finalized one
        from ..security.ca import cert_digest
        new_digest = cert_digest(new_cert)

        def new_token(role: NodeRole) -> str:
            secret = self.root_ca._token_secrets[role]
            import base64 as _b64
            return "-".join([
                "SWMTKN-1", new_digest,
                _b64.b32encode(secret).decode("ascii")
                .strip("=").lower()])

        def cb(tx):
            clusters = tx.find(Cluster, ByName(DEFAULT_CLUSTER_NAME))
            if not clusters:
                return
            cluster = clusters[0].copy()
            state = cluster.root_ca
            if state is None:
                return
            state.ca_key = new_key
            state.ca_cert = new_cert
            state.rotation_ca_key = b""
            state.rotation_ca_cert = b""
            state.cross_signed_ca_cert = b""
            state.root_rotation_in_progress = False
            # token digests derive from the root cert: re-mint so the
            # persisted strings match what role_for_token validates
            from ..models.types import JoinTokens
            state.join_tokens = JoinTokens(
                worker=new_token(NodeRole.WORKER),
                manager=new_token(NodeRole.MANAGER))
            tx.update(cluster)

        self.store.update(cb)
        self.root_ca.finalize_rotation()
        hook = self.on_root_rotated
        if hook is not None:
            try:
                hook()
            except Exception:
                log.exception("root-rotation hook failed")

    def health_check(self, service: str = "") -> str:
        """Health RPC (reference: manager/health/health.go Check,
        api/health.proto:17): SERVING / NOT_SERVING / UNKNOWN.  The
        empty service means "the manager"; "raft" reports consensus
        membership health like the reference's Raft service."""
        if service in ("", "manager"):
            return "SERVING" if self._running else "NOT_SERVING"
        if service == "raft":
            if self.raft is None:
                return "SERVING"   # standalone: no consensus to be in
            if not self._running or self.raft.core.removed:
                return "NOT_SERVING"
            return "SERVING"
        return "UNKNOWN"

    def manager_api_addrs(self) -> list:
        """Remote-API addresses of all known managers (replicated via
        conf entries), distributed to agents in heartbeat responses so
        they can fail over (reference: session Message.Managers)."""
        addrs = {}
        if self.raft is not None:
            addrs.update(self.raft.core.api_addrs)
        addrs.update(self.api_addrs)
        return [list(a) for a in addrs.values()]

    def join_raft(self, node_id: str, addr=None, api_addr=None) -> dict:
        """Leader-side manager join: adds the caller to the raft group
        and returns the known peer transport addresses (reference:
        raft.go:926 Join RPC; called by a promoted node's manager at
        startup).  The caller must hold a MANAGER certificate — enforced
        by the network layer."""
        import base64
        if self.raft is None:
            raise RuntimeError("standalone manager has no raft group")
        if not self.raft.is_leader:
            # only the leader can change membership; hand the caller the
            # leader's API address when we know it (reference: raft.go
            # Join forwards to the leader)
            leader = self.raft.leader_id
            redirect = self.raft.core.api_addrs.get(leader)
            if redirect is not None:
                return {"redirect": list(redirect)}
            raise RuntimeError(
                "not the raft leader and the leader's API address is "
                "unknown; retry against the leader")
        # membership only changes on the hop that carries the joiner's
        # transport address: the address-less first hop (which fetches the
        # CA key before the joiner can even bind its transport) must not
        # add a member that may never start — a dead phantom peer would
        # wedge quorum permanently on small clusters
        if addr is not None and node_id not in self.raft.core.peers:
            # a still-valid MANAGER cert is not enough when the store
            # says the node is (again) a worker — a join racing a
            # demotion must not commit a phantom voter (the role manager
            # flips the role to MANAGER before a node can ever promote,
            # so a registered joiner's record always agrees)
            from ..models.objects import Node as NodeObject
            from ..models.types import NodeRole as _NR
            rec = self.store.view(lambda tx: tx.get(NodeObject, node_id))
            if rec is not None and _NR(rec.role) != _NR.MANAGER:
                raise PermissionError(
                    f"node {node_id} has role {_NR(rec.role).name}; "
                    "promote it before joining raft")
            self.raft.add_member(node_id, tuple(addr),
                                 tuple(api_addr) if api_addr else None)
        members = {k: list(v) for k, v in self.raft_peer_addrs.items()}
        # replicated addresses (conf entries/snapshots) are authoritative
        members.update({k: list(v)
                        for k, v in self.raft.core.peer_addrs.items()})
        if addr is not None:
            members[node_id] = list(addr)
            self.raft_peer_addrs[node_id] = tuple(addr)
        # managers co-hold the cluster root key (the reference ships CA
        # material to joining managers via the certificate response,
        # ca/certificates.go); the RPC is MANAGER-cert gated
        return {"members": members,
                "ca_key": base64.b64encode(self.root_ca.key).decode(),
                "ca_cert": base64.b64encode(
                    self.root_ca.cert_pem).decode()}

    def _become_follower(self) -> None:
        """reference: manager.go:1150 becomeFollower."""
        with self._mu:
            if not self._is_leader:
                return
            self._is_leader = False
            # a follower's broker receives nothing (agents publish to
            # the leader): collect_logs must fail loudly, not block then
            # return empty
            self.control_api.log_broker = None
            log.info("manager %s lost leadership", self.node_id[:8])
            loops = [getattr(self, "autoscaler", None),
                     getattr(self, "deallocator", None),
                     self.csi_manager, self.role_manager,
                     self.keymanager, self.volume_enforcer,
                     self.constraint_enforcer, self.reaper, self.jobs,
                     self.global_, self.replicated, self.scheduler,
                     self.allocator]
            for loop in loops:
                if loop is not None:
                    try:
                        loop.stop()
                    except Exception:
                        log.exception("stopping %r failed", loop)
            if self.dispatcher is not None:
                try:
                    # flush buffered agent status updates only while the
                    # proposer can still commit them: standalone always,
                    # raft only if we are STILL the leader (a graceful
                    # shutdown of a live leader must not drop reported
                    # states).  On genuine deposal the epoch is fenced
                    # and the flush would only raise — the successor's
                    # dispatcher re-learns task state from the agents'
                    # re-registration.
                    self.dispatcher.stop(
                        flush=self.raft is None or self.raft.is_leader)
                except Exception:
                    log.exception("stopping dispatcher failed")
            self.dispatcher = self.allocator = self.scheduler = None
            self.replicated = self.global_ = self.jobs = None
            self.autoscaler = None
            self.csi_manager = None
            self.deallocator = None
            self.reaper = None
            self.constraint_enforcer = self.volume_enforcer = None
            self.keymanager = None
            self.role_manager = None
