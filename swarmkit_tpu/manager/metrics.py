"""Object-count metrics collector.

Reference: manager/metrics/collector.go:41-80 — gauges for nodes (by
state), tasks (by state), services, networks, secrets, configs, updated
from store events.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, Optional

from ..models.objects import (
    Config, Network, Node, Secret, Service, Task,
)
from ..state.events import Event, EventSnapshotRestore, EventTaskBlock
from ..state.store import MemoryStore
from ..state.watch import Closed
from ..utils.metrics import registry


class Collector:
    KINDS = (Node, Service, Task, Network, Secret, Config)

    def __init__(self, store: MemoryStore):
        self.store = store
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._counts: Dict[str, int] = defaultdict(int)
        self._task_states: Dict[int, int] = defaultdict(int)
        self._node_states: Dict[int, int] = defaultdict(int)
        # every state label ever exported: a state whose count drops to
        # zero (or vanishes across an EventSnapshotRestore recount) must
        # keep exporting 0, not linger at its stale pre-restore value
        self._exported_task_states: set = set()
        self._exported_node_states: set = set()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="metrics",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._done.wait(timeout=5)

    def run(self) -> None:
        try:
            def init(tx):
                for kind in self.KINDS:
                    objs = tx.find(kind)
                    self._counts[kind.collection] = len(objs)
                    if kind is Task:
                        for t in objs:
                            self._task_states[int(t.status.state)] += 1
                    elif kind is Node:
                        for n in objs:
                            self._node_states[int(n.status.state)] += 1

            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            self._export()
            try:
                while not self._stop.is_set():
                    try:
                        ev = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    if isinstance(ev, EventSnapshotRestore):
                        self._recount()
                    elif isinstance(ev, EventTaskBlock):
                        # n state transitions in one event: shift the
                        # histogram from the pre-assignment states (the
                        # olds arrays, no materialization needed)
                        for old in ev.olds:
                            self._task_states[int(old.status.state)] -= 1
                        self._task_states[int(ev.state)] += len(ev)
                        self._export()
                    elif isinstance(ev, Event):
                        self._handle(ev)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _recount(self) -> None:
        self._counts.clear()
        self._task_states.clear()
        self._node_states.clear()

        def init(tx):
            for kind in self.KINDS:
                objs = tx.find(kind)
                self._counts[kind.collection] = len(objs)
                if kind is Task:
                    for t in objs:
                        self._task_states[int(t.status.state)] += 1
                elif kind is Node:
                    for n in objs:
                        self._node_states[int(n.status.state)] += 1

        self.store.view(init)
        self._export()

    def _handle(self, ev: Event) -> None:
        obj = ev.obj
        coll = getattr(obj, "collection", None)
        if coll is None:
            return
        if ev.action == "create":
            self._counts[coll] += 1
        elif ev.action == "delete":
            self._counts[coll] -= 1
        if isinstance(obj, Task):
            if ev.action == "delete":
                self._task_states[int(obj.status.state)] -= 1
            else:
                if ev.action == "update" and ev.old is not None:
                    self._task_states[int(ev.old.status.state)] -= 1
                self._task_states[int(obj.status.state)] += 1
        elif isinstance(obj, Node):
            if ev.action == "delete":
                self._node_states[int(obj.status.state)] -= 1
            else:
                if ev.action == "update" and ev.old is not None:
                    self._node_states[int(ev.old.status.state)] -= 1
                self._node_states[int(obj.status.state)] += 1
        self._export()

    def _export(self) -> None:
        from ..models.types import NodeState, TaskState
        for coll, n in self._counts.items():
            registry.gauge(f"swarm_manager_{coll}", n)
        # labeled exposition (reference: collector.go's
        # {state="running"}-style gauge vectors).  States seen earlier but
        # absent now export 0 so scrapes never read a stale count.
        self._exported_task_states.update(self._task_states)
        for state in self._exported_task_states:
            registry.gauge(
                f'swarm_manager_tasks{{state='
                f'"{TaskState(state).name.lower()}"}}',
                self._task_states.get(state, 0))
        self._exported_node_states.update(self._node_states)
        for state in self._exported_node_states:
            registry.gauge(
                f'swarm_manager_nodes{{state='
                f'"{NodeState(state).name.lower()}"}}',
                self._node_states.get(state, 0))
