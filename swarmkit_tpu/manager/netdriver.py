"""Network-allocator driver seam (ROADMAP item 10).

The reference allocator routes network/address allocation through
pluggable drivers (cnmallocator + ipamapi); ours hard-wired the
built-in IPAM.  This module is the small driver interface the
``Allocator`` now consumes: per-network, the driver named by
``NetworkSpec.driver_config`` owns subnet carving and address
allocation/release.  Two built-ins ship:

* ``ipam`` (default, also the unnamed driver): the existing pool-carving
  IPAM — behavior unchanged for every current workload.
* ``inert``: completes allocation without addressing (empty IPAM config,
  no VIPs/addresses) — for driver-managed networks whose addressing
  happens off-cluster, and the seam's always-available null object.

Tests register fakes via ``NetworkDriverRegistry.register`` and observe
allocate/free calls; the registry remembers which driver allocated each
network id so release paths (which only carry the id) route back to the
owning driver.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..models.objects import Network
from ..models.types import IPAMOptions


class NetworkDriver:
    """Interface one network driver implements (the built-in IPAM's
    shape).  ``allocate_ip``/``release_ip`` cover VIPs and per-task
    addresses alike; an empty string from ``allocate_ip`` means "this
    driver does not address" and the caller attaches no address."""

    name = "driver"

    def allocate_network(self, net: Network) -> IPAMOptions:
        raise NotImplementedError

    def restore_network(self, net: Network) -> None:
        raise NotImplementedError

    def release_network(self, network_id: str) -> None:
        raise NotImplementedError

    def allocate_ip(self, network_id: str) -> str:
        raise NotImplementedError

    def restore_ip(self, network_id: str, addr: str) -> None:
        raise NotImplementedError

    def release_ip(self, network_id: str, addr: str) -> None:
        raise NotImplementedError


class IPAMNetworkDriver(NetworkDriver):
    """The built-in pool-carving IPAM behind the driver interface.
    Holds no state of its own: it reads the allocator's live ``ipam``
    through a getter so a store resync (which rebuilds the IPAM) never
    leaves the driver pointing at a dead instance."""

    name = "ipam"

    def __init__(self, get_ipam: Callable):
        self._get_ipam = get_ipam

    def allocate_network(self, net: Network) -> IPAMOptions:
        return self._get_ipam().allocate_network(net)

    def restore_network(self, net: Network) -> None:
        self._get_ipam().restore_network(net)

    def release_network(self, network_id: str) -> None:
        self._get_ipam().release_network(network_id)

    def allocate_ip(self, network_id: str) -> str:
        return self._get_ipam().allocate_ip(network_id)

    def restore_ip(self, network_id: str, addr: str) -> None:
        self._get_ipam().restore_ip(network_id, addr)

    def release_ip(self, network_id: str, addr: str) -> None:
        self._get_ipam().release_ip(network_id, addr)


class InertNetworkDriver(NetworkDriver):
    """Addressing-free driver: networks allocate (empty IPAM config) so
    dependent services/tasks proceed, but no VIPs or per-task addresses
    are handed out."""

    name = "inert"

    def allocate_network(self, net: Network) -> IPAMOptions:
        return IPAMOptions(configs=[])

    def restore_network(self, net: Network) -> None:
        pass

    def release_network(self, network_id: str) -> None:
        pass

    def allocate_ip(self, network_id: str) -> str:
        return ""

    def restore_ip(self, network_id: str, addr: str) -> None:
        pass

    def release_ip(self, network_id: str, addr: str) -> None:
        pass


class NetworkDriverRegistry:
    """name -> driver, plus the network-id -> driver binding release
    paths need (deletes only carry the id)."""

    def __init__(self, get_ipam: Callable):
        default = IPAMNetworkDriver(get_ipam)
        self._drivers: Dict[str, NetworkDriver] = {
            "": default,
            "default": default,
            IPAMNetworkDriver.name: default,
            InertNetworkDriver.name: InertNetworkDriver(),
        }
        self._by_network: Dict[str, NetworkDriver] = {}

    def register(self, name: str, driver: NetworkDriver) -> None:
        self._drivers[name] = driver

    def known(self, name: str) -> bool:
        return name in self._drivers

    def for_network(self, net: Network) -> NetworkDriver:
        """Resolve (and bind) the driver owning ``net``.  An unknown
        driver name falls back to the default IPAM — allocation must
        not wedge on a typo'd spec; the allocator logs it."""
        cfg = getattr(net.spec, "driver_config", None)
        name = (cfg.name if cfg else "") or ""
        drv = self._drivers.get(name, self._drivers[""])
        self._by_network[net.id] = drv
        return drv

    def for_id(self, network_id: str) -> NetworkDriver:
        return self._by_network.get(network_id, self._drivers[""])

    def release_binding(self, network_id: str) -> NetworkDriver:
        """Unbind a deleted network; returns the driver that owned it
        (the default IPAM when the binding predates this process — its
        release_network no-ops on ids it never carved)."""
        return self._by_network.pop(network_id, self._drivers[""])

    def reset_bindings(self) -> None:
        self._by_network.clear()
