"""Resource API: agent-initiated network attachments.

Reference: manager/resourceapi/allocator.go — AttachNetwork creates a
network-attachment pseudo-task bound to the calling node (used for
``docker run --net=<swarm overlay>``), DetachNetwork removes it.
"""

from __future__ import annotations

from typing import List, Optional

from ..models.objects import Network, Node, Task
from ..models.specs import NetworkAttachmentSpec, TaskSpec
from ..models.types import (
    NetworkAttachment, TaskState, TaskStatus, now,
)
from ..state.store import MemoryStore
from ..utils import new_id
from .controlapi import InvalidArgument, NotFound


class ResourceAPI:
    def __init__(self, store: MemoryStore):
        self.store = store

    def attach_network(self, node_id: str, network_id: str,
                       container_id: str = "",
                       addresses: Optional[List[str]] = None) -> str:
        """Create an attachment task for the node; returns the attachment
        (task) id (reference: allocator.go AttachNetwork)."""
        def cb(tx):
            if tx.get(Node, node_id) is None:
                raise NotFound(f"node {node_id} not found")
            network = tx.get(Network, network_id)
            if network is None:
                raise NotFound(f"network {network_id} not found")
            if not network.spec.attachable:
                raise InvalidArgument(
                    "network is not attachable")
            task = Task(
                id=new_id(),
                node_id=node_id,
                spec=TaskSpec(attachment=NetworkAttachmentSpec(
                    container_id=container_id)),
                status=TaskStatus(state=TaskState.NEW, timestamp=now(),
                                  message="created"),
                desired_state=TaskState.RUNNING,
                networks=[NetworkAttachment(
                    network_id=network_id,
                    addresses=list(addresses or []))])
            tx.create(task)
            return task.id

        return self.store.update(cb)

    def detach_network(self, node_id: str, attachment_id: str) -> None:
        """reference: allocator.go DetachNetwork."""
        def cb(tx):
            t = tx.get(Task, attachment_id)
            if t is None or t.spec.attachment is None:
                raise NotFound(
                    f"attachment {attachment_id} not found")
            if t.node_id != node_id:
                raise InvalidArgument(
                    "attachment belongs to a different node")
            tx.delete(Task, attachment_id)

        self.store.update(cb)
