"""Role manager: reconciles node desired_role changes with raft membership
and certificates.

Reference: manager/role_manager.go — promotion adds the node to the raft
cluster; demotion removes it from raft FIRST and only then changes the
observed role (design/raft.md:136-158: removing before demoting avoids a
window where a manager holds raft state it should not).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..models.objects import Node
from ..models.types import NodeRole
from ..state.events import Event
from ..state.store import MemoryStore
from ..state.watch import Closed

log = logging.getLogger("rolemanager")


RECONCILE_INTERVAL = 5.0   # periodic pass so transient failures retry


class RoleManager:
    def __init__(self, store: MemoryStore, raft_node=None,
                 reconcile_interval: float = RECONCILE_INTERVAL):
        self.store = store
        self.raft = raft_node
        self.reconcile_interval = reconcile_interval
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="rolemanager",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # must outlast a membership proposal in flight (10s wait in
        # _propose_conf) so no orphaned thread acts after leadership loss
        self._done.wait(timeout=15)

    def run(self) -> None:
        try:
            def pred(ev):
                return (isinstance(ev, Event)
                        and isinstance(ev.obj, Node))

            def init(tx):
                return tx.find(Node)

            nodes, sub = self.store.view_and_watch(init, predicate=pred,
                                                   accepts_blocks=True)
            try:
                for n in nodes:
                    self._reconcile(n)
                from ..models.types import now as _now
                next_pass = _now() + self.reconcile_interval
                while not self._stop.is_set():
                    try:
                        ev = sub.get(timeout=0.2)
                    except TimeoutError:
                        ev = None
                    except Closed:
                        return
                    if ev is not None and ev.action != "delete":
                        self._reconcile(ev.obj)
                    if _now() >= next_pass:
                        # ticker: retry transiently-failed transitions
                        # (reference: role_manager.go's ticker)
                        next_pass = _now() + self.reconcile_interval
                        for n in self.store.view(
                                lambda tx: tx.find(Node)):
                            self._reconcile(n)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()

    def _reconcile(self, node: Node) -> None:
        desired = NodeRole(node.spec.desired_role)
        observed = NodeRole(node.role)
        if desired == observed:
            if (desired == NodeRole.WORKER and self.raft is not None
                    and node.id != self.raft.id
                    and node.id in getattr(self.raft.core, "peers", set())):
                # phantom voter: a raft join racing a demotion can land
                # AFTER the observed role flipped to worker (the join RPC
                # is gated on the still-valid manager cert).  The ticker
                # re-runs this sweep, so the dead member cannot inflate
                # quorum forever.
                try:
                    self.raft.remove_member(node.id)
                    log.info("removed phantom raft member %s "
                             "(role is worker)", node.id[:8])
                except Exception:
                    log.exception("removing phantom member %s failed",
                                  node.id)
            return
        if desired == NodeRole.WORKER:
            # demotion: leave raft BEFORE flipping the observed role
            # (design/raft.md:136-158)
            if self.raft is not None and \
                    node.id in getattr(self.raft.core, "peers", set()):
                if node.id == self.raft.id and self.raft.is_leader:
                    # demoting ourselves: hand leadership off first; the
                    # next leader's role manager performs the removal
                    # (reference: TransferLeadership before self-demotion)
                    log.info("stepping down before self-demotion")
                    self.raft.step_down()
                    return
                try:
                    self.raft.remove_member(node.id)
                except Exception:
                    log.exception("removing %s from raft failed", node.id)
                    return  # the ticker retries
            self._set_observed_role(node.id, NodeRole.WORKER)
        else:
            # promotion: flip the observed role only — raft membership is
            # added when the promoted node's manager process actually
            # joins via the raft_join RPC (net/server.py; reference:
            # JoinAndStart -> Join RPC on the leader).  Adding a
            # not-yet-running member here would inflate quorum with a dead
            # peer and can wedge small clusters.
            self._set_observed_role(node.id, NodeRole.MANAGER)

    def _set_observed_role(self, node_id: str, role: NodeRole) -> None:
        def cb(tx):
            n = tx.get(Node, node_id)
            if n is None or n.role == int(role):
                return
            n = n.copy()
            n.role = int(role)
            tx.update(n)

        try:
            self.store.update(cb)
            log.info("node %s role reconciled to %s", node_id[:8],
                     role.name)
        except Exception:
            log.exception("setting observed role failed")
