"""Watch API: an external event-stream surface over the store's queue.

Reference: manager/watchapi/watch.go:16 (Watch) and :32 (WatchFrom).

Clients subscribe with per-kind/action/field filters and receive committed
change events; ``include_old_object`` mirrors the reference's option.
``resume_from_version`` replays every change committed after that store
version (backed by the store's changelog ring, the analogue of the
reference's raft-log ChangesBetween, raft.go:1617) before going live; a
version older than the retained window raises — the caller must re-list
and watch from the current version, exactly like the reference when the
raft log was compacted.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Type

from ..state.events import Event
from ..state.store import MemoryStore
from ..state.watch import Subscription


@dataclass
class WatchRequest:
    kinds: List[Type] = field(default_factory=list)   # [] = all kinds
    actions: List[str] = field(default_factory=list)  # [] = all actions
    id_prefix: str = ""
    name_prefix: str = ""
    # task-shaped selectors (reference: watch.proto SelectByServiceID /
    # SelectByNodeID); objects without the field never match
    service_ids: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)
    include_old_object: bool = False
    # store version to resume from (0/None = live-only, no replay)
    resume_from_version: Optional[int] = None


@dataclass
class WatchEvent:
    action: str
    obj: Any
    old: Optional[Any] = None


class ResumeCompacted(Exception):
    """The requested resume version is older than the retained changelog;
    re-list and watch from the current version."""


class WatchServer:
    def __init__(self, store: MemoryStore):
        self.store = store

    def watch(self, request: WatchRequest) -> "WatchStream":
        kinds = tuple(request.kinds) or None
        actions = set(request.actions) or None

        def pred(ev) -> bool:
            if not isinstance(ev, Event):
                return False
            if kinds is not None and not isinstance(ev.obj, kinds):
                return False
            if actions is not None and ev.action not in actions:
                return False
            if request.id_prefix and \
                    not ev.obj.id.startswith(request.id_prefix):
                return False
            if request.name_prefix:
                from ..state.store import _obj_name
                if not _obj_name(ev.obj).lower().startswith(
                        request.name_prefix.lower()):
                    return False
            if request.service_ids and \
                    getattr(ev.obj, "service_id", None) \
                    not in request.service_ids:
                return False
            if request.node_ids and \
                    getattr(ev.obj, "node_id", None) \
                    not in request.node_ids:
                return False
            return True

        if request.resume_from_version is not None:
            from ..state.store import InvalidStoreAction
            try:
                replay, sub = self.store.watch_from(
                    request.resume_from_version, pred)
            except InvalidStoreAction as e:
                raise ResumeCompacted(str(e))
            replay = [ev for ev in replay if pred(ev)]
        else:
            replay = []
            sub = self.store.queue.subscribe(pred)
        return WatchStream(self, sub, request.include_old_object, replay)


class WatchStream:
    def __init__(self, server: WatchServer, sub: Subscription,
                 include_old: bool, replay: Optional[List[Event]] = None):
        self._server = server
        self._sub = sub
        self._include_old = include_old
        self._replay = list(replay or [])

    def get(self, timeout: Optional[float] = None) -> WatchEvent:
        if self._replay:
            ev = self._replay.pop(0)
        else:
            ev = self._sub.get(timeout=timeout)
        return WatchEvent(ev.action, ev.obj,
                          ev.old if self._include_old else None)

    def close(self) -> None:
        self._server.store.queue.unsubscribe(self._sub)
