"""Watch API: an external event-stream surface over a store's queue.

Reference: manager/watchapi/watch.go:16 (Watch) and :32 (WatchFrom),
selector semantics from watch.proto:74-120 (SelectBy*).

Clients subscribe with per-kind/action/field filters and receive
committed change events.  Every delivered event carries a **resume
token** (``WatchEvent.version``, the store version the change committed
at): passing it back as ``resume_from_version`` replays every change
committed after that version (backed by the store's changelog ring, the
analogue of the reference's raft-log ChangesBetween, raft.go:1617)
before going live.  Version stamping is part of the replicated state —
leader and follower stores stamp identical indices — so a token taken
from one member resumes, gap-free and dup-free, on ANY member's
replicated store: the watch plane survives leader loss by reattaching
elsewhere.  A token older than the retained window raises
``ResumeCompacted`` — the caller must re-list from a current view and
watch from that version, exactly like the reference when the raft log
was compacted (snapshot re-sync).

Filter evaluation is member-agnostic by construction:
``compile_filter`` builds one pure predicate over the event payload
(never over live store rows), shared by leader- and follower-served
streams and by the simulator's continuity checkers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple, Type

from ..state.events import Event, event_version
from ..state.store import MemoryStore
from ..state.watch import Subscription


@dataclass
class WatchRequest:
    kinds: List[Type] = field(default_factory=list)   # [] = all kinds
    actions: List[str] = field(default_factory=list)  # [] = all actions
    id_prefix: str = ""
    name_prefix: str = ""
    # task-shaped selectors (reference: watch.proto SelectByServiceID /
    # SelectByNodeID); objects without the field never match
    service_ids: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)
    # ---- per-kind field filters (watch.proto:74-120 parity) ----
    # exact names (SelectByName; case-insensitive like the store index)
    names: List[str] = field(default_factory=list)
    # (service_id, slot) pairs (SelectBySlot)
    slots: List[Tuple[str, int]] = field(default_factory=list)
    # desired-state ints (SelectByDesiredState)
    desired_states: List[int] = field(default_factory=list)
    # node role / membership ints (SelectByRole / SelectByMembership)
    roles: List[int] = field(default_factory=list)
    memberships: List[int] = field(default_factory=list)
    # resource kind strings (SelectByKind)
    resource_kinds: List[str] = field(default_factory=list)
    # custom-index selectors over annotations.indices (SelectByCustom /
    # SelectByCustomPrefix): (index, value) exact or value-prefix pairs
    custom_indices: List[Tuple[str, str]] = field(default_factory=list)
    custom_index_prefixes: List[Tuple[str, str]] = \
        field(default_factory=list)
    include_old_object: bool = False
    # store version to resume from (None = live-only, no replay)
    resume_from_version: Optional[int] = None


def _annotations(obj: Any) -> Any:
    ann = getattr(obj, "annotations", None)
    if ann is not None:
        return ann
    spec = getattr(obj, "spec", None)
    return getattr(spec, "annotations", None)


def compile_filter(request: WatchRequest) -> Callable[[Any], bool]:
    """One pure predicate over event payloads for this request's
    selectors.  Evaluation never reads live store rows, so the SAME
    filter yields the SAME stream on every member — the property the
    follower-served watch plane (and its no-gap-no-dup checker) rests
    on."""
    kinds = tuple(request.kinds) or None
    actions = set(request.actions) or None
    names = {n.lower() for n in request.names} or None
    slots = set(request.slots) or None
    desired = set(request.desired_states) or None
    roles = set(request.roles) or None
    memberships = set(request.memberships) or None
    rkinds = set(request.resource_kinds) or None
    custom = list(request.custom_indices)
    custom_prefix = list(request.custom_index_prefixes)

    def pred(ev: Any) -> bool:
        if not isinstance(ev, Event):
            return False
        obj = ev.obj
        if kinds is not None and not isinstance(obj, kinds):
            return False
        if actions is not None and ev.action not in actions:
            return False
        if request.id_prefix and not obj.id.startswith(request.id_prefix):
            return False
        if request.name_prefix or names is not None:
            from ..state.store import _obj_name
            name = _obj_name(obj).lower()
            if request.name_prefix and \
                    not name.startswith(request.name_prefix.lower()):
                return False
            if names is not None and name not in names:
                return False
        if request.service_ids and \
                getattr(obj, "service_id", None) \
                not in request.service_ids:
            return False
        if request.node_ids and \
                getattr(obj, "node_id", None) not in request.node_ids:
            return False
        if slots is not None and \
                (getattr(obj, "service_id", None),
                 getattr(obj, "slot", None)) not in slots:
            return False
        if desired is not None:
            ds = getattr(obj, "desired_state", None)
            if ds is None or int(ds) not in desired:
                return False
        if roles is not None:
            spec = getattr(obj, "spec", None)
            role = getattr(spec, "desired_role", None)
            if role is None or int(role) not in roles:
                return False
        if memberships is not None:
            spec = getattr(obj, "spec", None)
            mem = getattr(spec, "membership", None)
            if mem is None or int(mem) not in memberships:
                return False
        if rkinds is not None and \
                getattr(obj, "kind", None) not in rkinds:
            return False
        if custom or custom_prefix:
            ann = _annotations(obj)
            indices = getattr(ann, "indices", None) or {}
            for index, value in custom:
                if indices.get(index) == value:
                    break
            else:
                for index, prefix in custom_prefix:
                    got = indices.get(index)
                    if got is not None and got.startswith(prefix):
                        break
                else:
                    return False
        return True

    return pred


@dataclass
class WatchEvent:
    action: str
    obj: Any
    old: Optional[Any] = None
    #: resume token: the store version this change committed at; pass it
    #: back as ``resume_from_version`` to continue exactly after this
    #: event on any member
    version: int = 0


class ResumeCompacted(Exception):
    """The requested resume version is older than the retained changelog;
    re-list and watch from the current version."""


class WatchServer:
    """Serves watch streams over ONE store — the leader's or, with
    follower-served reads, any member's replicated store (identical
    event payloads and version stamps by the store's convergence
    contract)."""

    def __init__(self, store: MemoryStore):
        self.store = store

    def watch(self, request: WatchRequest) -> "WatchStream":
        pred = compile_filter(request)
        if request.resume_from_version is not None:
            from ..state.store import InvalidStoreAction
            try:
                replay, sub = self.store.watch_from(
                    request.resume_from_version, pred)
            except InvalidStoreAction as e:
                raise ResumeCompacted(str(e))
            replay = [ev for ev in replay if pred(ev)]
        else:
            replay = []
            sub = self.store.queue.subscribe(pred)
        return WatchStream(self, sub, request.include_old_object, replay)


class WatchStream:
    def __init__(self, server: WatchServer, sub: Subscription,
                 include_old: bool, replay: Optional[List[Event]] = None):
        self._server = server
        self._sub = sub
        self._include_old = include_old
        self._replay = list(replay or [])

    def _wrap(self, ev: Event) -> WatchEvent:
        return WatchEvent(ev.action, ev.obj,
                          ev.old if self._include_old else None,
                          version=event_version(ev))

    def get(self, timeout: Optional[float] = None) -> WatchEvent:
        if self._replay:
            return self._wrap(self._replay.pop(0))
        return self._wrap(self._sub.get(timeout=timeout))

    def poll(self) -> Optional[WatchEvent]:
        """Non-blocking ``get``: the next buffered event or None."""
        if self._replay:
            return self._wrap(self._replay.pop(0))
        ev = self._sub.poll()
        return None if ev is None else self._wrap(ev)

    @property
    def closed(self) -> bool:
        """True once the subscription is closed and drained (overflow or
        store shutdown): the consumer must reattach — with its resume
        token, to any member."""
        return not self._replay and self._sub.closed

    def close(self) -> None:
        self._server.store.queue.unsubscribe(self._sub)
