from .types import *  # noqa: F401,F403
from .specs import *  # noqa: F401,F403
from .objects import *  # noqa: F401,F403
