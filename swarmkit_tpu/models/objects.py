"""Top-level store objects (reference: api/objects.proto).

Every store object has an ``id``, a ``Meta`` (store version + timestamps), a
user ``spec`` and system-owned runtime state.  ``collection`` names the store
table; ``copy()`` produces the deep copy the store keeps on write so readers
can treat returned objects as immutable snapshots.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .specs import (
    ClusterSpec,
    ConfigSpec,
    ExtensionSpec,
    NetworkSpec,
    NodeSpec,
    SecretSpec,
    ServiceSpec,
    TaskSpec,
    VolumeSpec,
)
from .types import (
    Annotations,
    Driver,
    Endpoint,
    EncryptionKey,
    GenericResource,
    IPAMOptions,
    JoinTokens,
    NetworkAttachment,
    NodeCSIInfo,
    NodeDescription,
    NodeStatus,
    RaftMemberStatus,
    TaskState,
    TaskStatus,
    TopologyRequirement,
    UpdateStatus,
    Version,
    VolumeAttachment,
    VolumePublishStatus,
    now,
)


@dataclass
class Meta:
    """Store metadata (reference: api/objects.proto:17)."""

    version: Version = field(default_factory=Version)
    created_at: float = 0.0
    updated_at: float = 0.0

    def copy(self) -> "Meta":
        return Meta(self.version.copy(), self.created_at, self.updated_at)


@dataclass
class Node:
    """reference: api/objects.proto:28"""

    collection = "nodes"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    description: Optional[NodeDescription] = None
    status: NodeStatus = field(default_factory=NodeStatus)
    manager_status: Optional[RaftMemberStatus] = None
    attachments: List[NetworkAttachment] = field(default_factory=list)
    certificate: Optional[bytes] = None
    role: int = 0               # observed role (reconciled towards spec)
    vxlan_udp_port: int = 0
    # digest of the root this node's cert chains to, recorded at network
    # issuance/renewal — drives the CA-rotation reconciler's progress
    # tracking (reference: ca/reconciler.go node cert states)
    certificate_issuer: str = ""

    def copy(self) -> "Node":
        return Node(
            self.id, self.meta.copy(), self.spec.copy(),
            self.description.copy() if self.description else None,
            self.status.copy(),
            dataclasses.replace(self.manager_status) if self.manager_status else None,
            [a.copy() for a in self.attachments],
            self.certificate, self.role, self.vxlan_udp_port,
            self.certificate_issuer)


@dataclass
class Service:
    """reference: api/objects.proto:90"""

    collection = "services"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    spec_version: Optional[Version] = None
    previous_spec: Optional[ServiceSpec] = None
    previous_spec_version: Optional[Version] = None
    endpoint: Optional[Endpoint] = None
    update_status: Optional[UpdateStatus] = None
    job_status: Optional["JobStatus"] = None
    pending_delete: bool = False
    autoscale_status: Optional["AutoscaleStatus"] = None
    pipeline_status: Optional["PipelineStatus"] = None

    def copy(self) -> "Service":
        return Service(
            self.id, self.meta.copy(), self.spec.copy(),
            self.spec_version.copy() if self.spec_version else None,
            self.previous_spec.copy() if self.previous_spec else None,
            self.previous_spec_version.copy() if self.previous_spec_version else None,
            self.endpoint.copy() if self.endpoint else None,
            self.update_status.copy() if self.update_status else None,
            dataclasses.replace(self.job_status) if self.job_status else None,
            self.pending_delete,
            self.autoscale_status.copy() if self.autoscale_status else None,
            self.pipeline_status.copy() if self.pipeline_status else None)


@dataclass
class AutoscaleStatus:
    """System-owned autoscaler resume state (orchestrator/autoscaler.py).

    Written in the SAME transaction as every replica change, so a
    successor leader's supervisor resumes the policy — stabilization
    window, direction history, flap freeze — from the replicated row
    instead of forgetting it across failover.  All stamps read
    ``models.types.now()`` (virtual under the sim).
    """

    last_decision_at: float = 0.0
    last_direction: int = 0          # -1 down, 0 none yet, +1 up
    reversal_stamps: List[float] = field(default_factory=list)
    frozen_until: float = 0.0        # flap breaker: no writes until then

    def copy(self) -> "AutoscaleStatus":
        return AutoscaleStatus(self.last_decision_at, self.last_direction,
                               list(self.reversal_stamps),
                               self.frozen_until)


@dataclass
class PipelineStatus:
    """System-owned pipeline-gate state (orchestrator/pipeline.py).

    Written on the Service row by the PipelineSupervisor — replicated,
    so a successor leader's supervisor resumes the DAG rollout exactly
    where the crashed one left it.  ``state`` is "waiting" (upstreams
    not ready yet; the scheduler defers this stage's tasks), "released"
    (sticky: the stage has been handed to the scheduler), or "halted"
    (an upstream is poisoned; cascaded downstream).  Stamps read
    ``models.types.now()`` (virtual under the sim).

    ``failed_ids`` replicates the poison OBSERVATIONS (distinct task
    ids seen FAILED/REJECTED), not just the verdict: a successor
    leader's supervisor resumes the count where the deposed one left
    it, so 2 observations before a crash plus 1 after still trip the
    ``POISON_FAILURES`` threshold.  Bounded: failed task rows, like
    the services they belong to, are garbage-collected by the task
    reaper, and the list only grows while the stage is actually
    flapping toward a halt verdict.
    """

    state: str = "waiting"
    reason: str = ""
    updated_at: float = 0.0
    failed_ids: List[str] = field(default_factory=list)
    #: operator-resume watermark (controlapi ``resume_pipeline``):
    #: failures stamped at/before it are forgiven — supervisors reset
    #: their local observation ledgers when the stamp changes and skip
    #: failed task rows older than it, so the poison the operator just
    #: fixed can never re-trip the threshold.  0.0 = never resumed.
    resumed_at: float = 0.0

    def copy(self) -> "PipelineStatus":
        return PipelineStatus(self.state, self.reason, self.updated_at,
                              list(self.failed_ids), self.resumed_at)


@dataclass
class JobStatus:
    """Status of a job-mode service (reference: api/objects.proto)."""

    job_iteration: Version = field(default_factory=Version)
    last_execution: float = 0.0


@dataclass
class Task:
    """reference: api/objects.proto:183"""

    collection = "tasks"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: TaskSpec = field(default_factory=TaskSpec)
    spec_version: Optional[Version] = None
    service_id: str = ""
    slot: int = 0
    node_id: str = ""
    annotations: Annotations = field(default_factory=Annotations)
    service_annotations: Annotations = field(default_factory=Annotations)
    status: TaskStatus = field(default_factory=TaskStatus)
    desired_state: TaskState = TaskState.NEW
    networks: List[NetworkAttachment] = field(default_factory=list)
    endpoint: Optional[Endpoint] = None
    log_driver: Optional[Driver] = None
    assigned_generic_resources: List[GenericResource] = field(default_factory=list)
    job_iteration: Optional[Version] = None
    volumes: List[VolumeAttachment] = field(default_factory=list)

    def copy(self) -> "Task":
        # Hot path: tasks are copied once per scheduling decision and once
        # per store write.  Fields follow a replace-don't-mutate convention
        # (spec/annotations/spec_version/endpoint/log_driver are immutable
        # once attached — the system "never modifies" a spec,
        # api/objects.proto:203 — so they are shared by reference); only
        # meta/status (stamped by the store / scheduler) and the list
        # containers are isolated.
        new = object.__new__(Task)
        new.__dict__.update(self.__dict__)
        new.meta = self.meta.copy()
        new.status = self.status.copy()
        new.networks = list(self.networks)
        new.assigned_generic_resources = list(self.assigned_generic_resources)
        new.volumes = list(self.volumes)
        return new


@dataclass
class Network:
    """reference: api/objects.proto:297"""

    collection = "networks"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: NetworkSpec = field(default_factory=NetworkSpec)
    driver_state: Optional[Driver] = None
    ipam: Optional[IPAMOptions] = None
    pending_delete: bool = False

    def copy(self) -> "Network":
        return Network(
            self.id, self.meta.copy(), self.spec.copy(),
            self.driver_state.copy() if self.driver_state else None,
            self.ipam.copy() if self.ipam else None,
            self.pending_delete)


@dataclass
class Cluster:
    """reference: api/objects.proto:343"""

    collection = "clusters"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    root_ca: Optional["RootCAState"] = None
    network_bootstrap_keys: List[EncryptionKey] = field(default_factory=list)
    encryption_key_lamport_clock: int = 0
    unlock_keys: List[EncryptionKey] = field(default_factory=list)
    fips: bool = False
    default_address_pool: List[str] = field(default_factory=list)
    subnet_size: int = 24
    vxlan_udp_port: int = 4789

    def copy(self) -> "Cluster":
        # root_ca copies deeply: join_tokens is mutable and a shallow
        # replace would alias the committed object's tokens, breaking the
        # store's copy-on-write contract under token rotation
        root_ca = None
        if self.root_ca is not None:
            root_ca = dataclasses.replace(
                self.root_ca,
                join_tokens=dataclasses.replace(self.root_ca.join_tokens))
        return Cluster(
            self.id, self.meta.copy(), self.spec.copy(),
            root_ca,
            list(self.network_bootstrap_keys),
            self.encryption_key_lamport_clock,
            list(self.unlock_keys), self.fips,
            list(self.default_address_pool), self.subnet_size,
            self.vxlan_udp_port)


@dataclass
class RootCAState:
    """Cluster CA material (reference: api/types.proto:936)."""

    ca_key: bytes = b""
    ca_cert: bytes = b""
    cross_signed_ca_cert: bytes = b""
    join_tokens: JoinTokens = field(default_factory=JoinTokens)
    root_rotation_in_progress: bool = False
    last_forced_rotation: int = 0
    # in-progress rotation target (reference: api.RootRotation)
    rotation_ca_key: bytes = b""
    rotation_ca_cert: bytes = b""


@dataclass
class Secret:
    collection = "secrets"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: SecretSpec = field(default_factory=SecretSpec)
    internal: bool = False

    def copy(self) -> "Secret":
        return Secret(self.id, self.meta.copy(), self.spec.copy(),
                      self.internal)


@dataclass
class Config:
    collection = "configs"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ConfigSpec = field(default_factory=ConfigSpec)

    def copy(self) -> "Config":
        return Config(self.id, self.meta.copy(), self.spec.copy())


@dataclass
class Volume:
    """CSI volume (reference: api/objects.proto:526)."""

    collection = "volumes"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: VolumeSpec = field(default_factory=VolumeSpec)
    publish_status: List[VolumePublishStatus] = field(default_factory=list)
    volume_info: Optional["VolumeInfo"] = None
    pending_delete: bool = False

    def copy(self) -> "Volume":
        return Volume(
            self.id, self.meta.copy(), self.spec.copy(),
            [p.copy() for p in self.publish_status],
            dataclasses.replace(self.volume_info) if self.volume_info else None,
            self.pending_delete)


@dataclass
class VolumeInfo:
    capacity_bytes: int = 0
    volume_context: Dict[str, str] = field(default_factory=dict)
    volume_id: str = ""   # plugin-side id
    accessible_topology: List[Dict[str, str]] = field(default_factory=list)


@dataclass
class Extension:
    """Custom object-type registration (reference: api/objects.proto:487)."""

    collection = "extensions"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    annotations: Annotations = field(default_factory=Annotations)
    description: str = ""

    def copy(self) -> "Extension":
        return Extension(self.id, self.meta.copy(), self.annotations.copy(),
                         self.description)

    @property
    def spec(self) -> ExtensionSpec:  # uniform access for the store
        return ExtensionSpec(self.annotations, self.description)


@dataclass
class Resource:
    """Custom object instance of an Extension kind
    (reference: api/objects.proto:456)."""

    collection = "resources"

    id: str = ""
    meta: Meta = field(default_factory=Meta)
    annotations: Annotations = field(default_factory=Annotations)
    kind: str = ""
    payload: bytes = b""

    def copy(self) -> "Resource":
        return Resource(self.id, self.meta.copy(), self.annotations.copy(),
                        self.kind, self.payload)

    @property
    def spec(self):  # uniform access for the store
        return self


STORE_OBJECT_TYPES = (Node, Service, Task, Network, Cluster, Secret, Config,
                      Volume, Extension, Resource)

__all__ = [name for name in dir() if not name.startswith("_")]
