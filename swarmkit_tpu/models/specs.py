"""User-authored desired-state specs (reference: api/specs.proto).

A spec is what the user writes; the system never modifies it.  Objects carry a
spec plus system-owned runtime state (see objects.py).
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import (
    Annotations,
    CAConfig,
    ConfigReference,
    DispatcherConfig,
    Driver,
    EncryptionConfig,
    EndpointSpec,
    GenericResource,
    IPAMOptions,
    Mount,
    NetworkAttachmentConfig,
    NodeAvailability,
    NodeRole,
    OrchestrationConfig,
    Placement,
    Platform,
    RaftConfig,
    ResourceRequirements,
    RestartPolicy,
    SecretReference,
    TaskDefaults,
    TenantQuota,
    TopologyRequirement,
    UpdateConfig,
    VolumeAccessMode,
)


@dataclass
class AutoscaleConfig:
    """Horizontal autoscaling policy for a replicated service
    (orchestrator/autoscaler.py AutoscaleSupervisor).

    Exactly one of ``target_utilization`` (observed load per replica;
    the supervisor's sampler seam supplies the load signal) or
    ``target_p99`` (pending->assigned p99 seconds from the obs
    lifecycle timers) drives the loop; 0 disables that signal.  The
    supervisor moves replicas by ``scale_up_step``/``scale_down_step``
    at most once per ``stabilization_window``, inside
    [min_replicas, max_replicas], with a +-``hysteresis`` deadband
    around the target so metric noise cannot oscillate replicas; a
    policy that still reverses direction ``flap_reversals`` times
    inside the flap window freezes itself and raises a health warn
    (the ``autoscale_flapping`` check).
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_utilization: float = 0.0
    target_p99: float = 0.0
    scale_up_step: int = 1
    scale_down_step: int = 1
    stabilization_window: float = 30.0
    hysteresis: float = 0.1
    flap_reversals: int = 3

    def copy(self) -> "AutoscaleConfig":
        return dataclasses.replace(self)


@dataclass
class NodeSpec:
    """reference: api/specs.proto:21"""

    annotations: Annotations = field(default_factory=Annotations)
    desired_role: NodeRole = NodeRole.WORKER
    membership: int = 1  # NodeMembership.ACCEPTED
    availability: NodeAvailability = NodeAvailability.ACTIVE

    def copy(self) -> "NodeSpec":
        return NodeSpec(self.annotations.copy(), self.desired_role,
                        self.membership, self.availability)


class ServiceMode(enum.IntEnum):
    REPLICATED = 0
    GLOBAL = 1
    REPLICATED_JOB = 2
    GLOBAL_JOB = 3


@dataclass
class ReplicatedService:
    replicas: int = 1


@dataclass
class GlobalService:
    pass


@dataclass
class ReplicatedJob:
    """Run-to-completion job (reference: api/specs.proto:106)."""

    max_concurrent: int = 0       # 0 = same as total_completions
    total_completions: int = 1


@dataclass
class GlobalJob:
    pass


@dataclass
class HealthConfig:
    test: List[str] = field(default_factory=list)
    interval: float = 0.0
    timeout: float = 0.0
    retries: int = 0
    start_period: float = 0.0


@dataclass
class ContainerSpec:
    """Container runtime parameters (reference: api/specs.proto:188).

    Trimmed to the fields the orchestration layer actually consumes; the
    executor receives the whole spec and may interpret more.
    """

    image: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    hostname: str = ""
    env: List[str] = field(default_factory=list)
    dir: str = ""
    user: str = ""
    groups: List[str] = field(default_factory=list)
    tty: bool = False
    open_stdin: bool = False
    read_only: bool = False
    stop_signal: str = ""
    stop_grace_period: float = 10.0
    mounts: List[Mount] = field(default_factory=list)
    secrets: List[SecretReference] = field(default_factory=list)
    configs: List[ConfigReference] = field(default_factory=list)
    hosts: List[str] = field(default_factory=list)
    healthcheck: Optional[HealthConfig] = None
    isolation: str = ""
    init: Optional[bool] = None
    sysctls: Dict[str, str] = field(default_factory=dict)
    capability_add: List[str] = field(default_factory=list)
    capability_drop: List[str] = field(default_factory=list)
    ulimits: Dict[str, int] = field(default_factory=dict)

    def copy(self) -> "ContainerSpec":
        return dataclasses.replace(
            self,
            labels=dict(self.labels), command=list(self.command),
            args=list(self.args), env=list(self.env),
            groups=list(self.groups),
            mounts=[m.copy() for m in self.mounts],
            secrets=list(self.secrets), configs=list(self.configs),
            hosts=list(self.hosts), sysctls=dict(self.sysctls),
            capability_add=list(self.capability_add),
            capability_drop=list(self.capability_drop),
            ulimits=dict(self.ulimits))


@dataclass
class GenericRuntimeSpec:
    kind: str = ""
    payload: bytes = b""


@dataclass
class NetworkAttachmentSpec:
    """Task is a network-attachment pseudo-task
    (reference: api/specs.proto:180)."""

    container_id: str = ""


@dataclass
class TaskSpec:
    """reference: api/specs.proto:124.

    Exactly one of (container, generic_runtime, attachment) is the runtime.
    """

    container: Optional[ContainerSpec] = None
    generic_runtime: Optional[GenericRuntimeSpec] = None
    attachment: Optional[NetworkAttachmentSpec] = None

    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    restart: RestartPolicy = field(default_factory=RestartPolicy)
    placement: Placement = field(default_factory=Placement)
    log_driver: Optional[Driver] = None
    networks: List[NetworkAttachmentConfig] = field(default_factory=list)
    force_update: int = 0   # counter: bump to force task replacement
    resource_references: List[str] = field(default_factory=list)
    # priority class: higher wins.  0 is the default band; only tasks
    # with priority > 0 may preempt, and victims must be STRICTLY lower
    # (scheduler/preempt.py).  Propagated from ServiceSpec.priority at
    # task creation when unset (orchestrator/common.effective_task_spec).
    priority: int = 0
    # gang membership key (scheduler/gang.py).  Tasks sharing a gang_id
    # are admitted all-or-nothing; "" plus Placement.gang means "gang =
    # the service itself".  Old records decode to "" (gang off).
    gang_id: str = ""

    def __post_init__(self) -> None:
        # strategy-seam differential knob: SWARM_DEFAULT_PLACEMENT_
        # STRATEGY stamps every spec whose strategy is unset — the
        # seam-identity twin runs the SAME scenario with "" and an
        # explicit "spread" and asserts byte-identical behavior
        # (tests/test_strategy.py).  Unset (production) this is a no-op.
        if not self.placement.strategy:
            import os
            default = os.environ.get(
                "SWARM_DEFAULT_PLACEMENT_STRATEGY", "")
            if default:
                self.placement.strategy = default

    def copy(self) -> "TaskSpec":
        return TaskSpec(
            container=self.container.copy() if self.container else None,
            generic_runtime=self.generic_runtime,
            attachment=self.attachment,
            resources=self.resources.copy(),
            restart=self.restart.copy(),
            placement=self.placement.copy(),
            log_driver=self.log_driver.copy() if self.log_driver else None,
            networks=[n.copy() for n in self.networks],
            force_update=self.force_update,
            resource_references=list(self.resource_references),
            priority=self.priority,
            gang_id=self.gang_id)


@dataclass
class ServiceSpec:
    """reference: api/specs.proto:63"""

    annotations: Annotations = field(default_factory=Annotations)
    task: TaskSpec = field(default_factory=TaskSpec)
    mode: ServiceMode = ServiceMode.REPLICATED
    replicated: Optional[ReplicatedService] = None
    replicated_job: Optional[ReplicatedJob] = None
    update: Optional[UpdateConfig] = None
    rollback: Optional[UpdateConfig] = None
    networks: List[NetworkAttachmentConfig] = field(default_factory=list)
    endpoint: Optional[EndpointSpec] = None
    # service-level priority class (authoring convenience): copied into
    # each task's spec at creation when task.priority is unset, so the
    # scheduler only ever reads task.spec.priority
    priority: int = 0
    # horizontal autoscaling policy (replicated services only); None =
    # replicas are operator-owned
    autoscale: Optional[AutoscaleConfig] = None
    # pipeline DAG edges: names of upstream services that must be
    # RUNNING (or, for jobs, complete) before this service's tasks are
    # released to the scheduler (orchestrator/pipeline.py).  Validated
    # acyclic by controlapi; old records decode to [] (no gating).
    depends_on: List[str] = field(default_factory=list)
    # what the pipeline supervisor does to THIS stage when an upstream
    # is poisoned: "halt" (default; freeze, surface reason) or
    # "rollback" (scale to zero replicas until the upstream recovers)
    on_upstream_failure: str = ""

    def replicas(self) -> int:
        if self.mode == ServiceMode.REPLICATED:
            return self.replicated.replicas if self.replicated else 1
        raise ValueError("replicas() only valid for replicated services")

    def copy(self) -> "ServiceSpec":
        return ServiceSpec(
            annotations=self.annotations.copy(),
            task=self.task.copy(),
            mode=self.mode,
            replicated=dataclasses.replace(self.replicated) if self.replicated else None,
            replicated_job=dataclasses.replace(self.replicated_job) if self.replicated_job else None,
            update=self.update.copy() if self.update else None,
            rollback=self.rollback.copy() if self.rollback else None,
            networks=[n.copy() for n in self.networks],
            endpoint=self.endpoint.copy() if self.endpoint else None,
            priority=self.priority,
            autoscale=self.autoscale.copy() if self.autoscale else None,
            depends_on=list(self.depends_on),
            on_upstream_failure=self.on_upstream_failure)


@dataclass
class NetworkSpec:
    """reference: api/specs.proto:412"""

    annotations: Annotations = field(default_factory=Annotations)
    driver_config: Optional[Driver] = None
    ipv6_enabled: bool = False
    internal: bool = False
    ipam: Optional[IPAMOptions] = None
    attachable: bool = False
    ingress: bool = False

    def copy(self) -> "NetworkSpec":
        return NetworkSpec(
            self.annotations.copy(),
            self.driver_config.copy() if self.driver_config else None,
            self.ipv6_enabled, self.internal,
            self.ipam.copy() if self.ipam else None,
            self.attachable, self.ingress)


@dataclass
class ClusterSpec:
    """reference: api/specs.proto:453"""

    annotations: Annotations = field(default_factory=Annotations)
    acceptance_policy: Dict[str, str] = field(default_factory=dict)  # legacy
    orchestration: OrchestrationConfig = field(default_factory=OrchestrationConfig)
    raft: RaftConfig = field(default_factory=RaftConfig)
    dispatcher: DispatcherConfig = field(default_factory=DispatcherConfig)
    ca_config: CAConfig = field(default_factory=CAConfig)
    task_defaults: TaskDefaults = field(default_factory=TaskDefaults)
    encryption_config: EncryptionConfig = field(default_factory=EncryptionConfig)
    # multi-tenant QoS: per-tenant quotas keyed by tenant name (the
    # ``swarm.tenant`` service-annotation label); enforced at admission
    # by the scheduler (scheduler/quota.py TenantLedger)
    tenants: Dict[str, TenantQuota] = field(default_factory=dict)

    def copy(self) -> "ClusterSpec":
        return ClusterSpec(
            self.annotations.copy(), dict(self.acceptance_policy),
            self.orchestration.copy(), self.raft.copy(),
            self.dispatcher.copy(), self.ca_config.copy(),
            self.task_defaults.copy(), self.encryption_config.copy(),
            {k: q.copy() for k, q in self.tenants.items()})


@dataclass
class SecretSpec:
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    templating: Optional[Driver] = None
    driver: Optional[Driver] = None

    def copy(self) -> "SecretSpec":
        return SecretSpec(self.annotations.copy(), self.data,
                          self.templating.copy() if self.templating else None,
                          self.driver.copy() if self.driver else None)


@dataclass
class ConfigSpec:
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    templating: Optional[Driver] = None

    def copy(self) -> "ConfigSpec":
        return ConfigSpec(self.annotations.copy(), self.data,
                          self.templating.copy() if self.templating else None)


@dataclass
class VolumeSpec:
    """CSI volume spec (reference: api/specs.proto:515)."""

    annotations: Annotations = field(default_factory=Annotations)
    group: str = ""
    driver: Optional[Driver] = None
    access_mode: VolumeAccessMode = field(default_factory=VolumeAccessMode)
    secrets: Dict[str, str] = field(default_factory=dict)
    accessibility_requirements: Optional[TopologyRequirement] = None
    capacity_min: int = 0
    capacity_max: int = 0
    availability: int = 0  # VolumeAvailability

    def copy(self) -> "VolumeSpec":
        return VolumeSpec(
            self.annotations.copy(), self.group,
            self.driver.copy() if self.driver else None,
            self.access_mode.copy(), dict(self.secrets),
            self.accessibility_requirements.copy()
            if self.accessibility_requirements else None,
            self.capacity_min, self.capacity_max, self.availability)


@dataclass
class ExtensionSpec:
    annotations: Annotations = field(default_factory=Annotations)
    description: str = ""

    def copy(self) -> "ExtensionSpec":
        return ExtensionSpec(self.annotations.copy(), self.description)


__all__ = [name for name in dir() if not name.startswith("_")]
