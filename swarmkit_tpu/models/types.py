"""Core value types of the cluster data model.

These are idiomatic-Python equivalents of the reference's protobuf value types
(reference: api/types.proto).  They are plain dataclasses: the control plane is
host-side and never touches the device, so there is no reason for protobuf
codegen here.  Serialization goes through ``to_dict``/``from_dict`` (see
serde.py) for snapshots, the WAL, and the wire.

Design notes
------------
* ``TaskState`` is a lamport-ordered IntEnum exactly like the reference
  (api/types.proto:510-557): a task only ever moves to a *greater* state, and
  gaps are left between values for future insertion.
* Resources are normalized at the edge: CPUs in nano-CPUs (int), memory in
  bytes (int), matching the reference's resource accounting
  (api/types.proto:68-77).  The TPU scheduler path converts these to float32
  SoA arrays; the host oracle uses them directly.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


# Injectable time source: everything in the control plane that stamps or
# compares wall-clock times (task status timestamps, dispatcher heartbeat
# TTLs, scheduler debounce) reads through now(), so the deterministic
# simulator (swarmkit_tpu/sim) can swap in a virtual clock and replay the
# whole control plane under controlled time.  Production never touches it.
_time_source = time.time


def set_time_source(source=None) -> None:
    """Install a replacement ``now()`` source (``None`` restores
    ``time.time``).  Only the simulator and tests should call this."""
    global _time_source
    _time_source = source if source is not None else time.time


def now() -> float:
    return _time_source()


def time_source_installed() -> bool:
    """True while a replacement time source (the simulator's virtual
    clock) is live — consumers that would otherwise mix wall-clock
    measurements into deterministic artifacts check this (e.g. the
    planner zeroes compile-span durations so sim traces stay a pure
    function of the seed)."""
    return _time_source is not time.time


class TaskState(enum.IntEnum):
    """Monotonic task lifecycle state (reference: api/types.proto:510).

    Values keep the reference's 64-wide gaps so orderings (and any on-disk
    data) stay comparable across versions.
    """

    NEW = 0
    PENDING = 64      # waiting for allocation / scheduling decision
    ASSIGNED = 192    # scheduler picked a node
    ACCEPTED = 256    # accepted by an agent
    PREPARING = 320
    READY = 384
    STARTING = 448
    RUNNING = 512
    COMPLETE = 576    # terminal: ran to successful completion
    SHUTDOWN = 640    # terminal: orchestrator requested shutdown
    FAILED = 704      # terminal: execution failed
    REJECTED = 768    # terminal: never ran (e.g. node-side setup failed)
    REMOVE = 800      # marked for deletion once shut down (desired state only)
    ORPHANED = 832    # node unresponsive >24h; resources freed


TERMINAL_STATES = frozenset(
    {TaskState.COMPLETE, TaskState.SHUTDOWN, TaskState.FAILED,
     TaskState.REJECTED, TaskState.ORPHANED}
)


class NodeRole(enum.IntEnum):
    WORKER = 0
    MANAGER = 1


class NodeMembership(enum.IntEnum):
    PENDING = 0
    ACCEPTED = 1


class NodeAvailability(enum.IntEnum):
    ACTIVE = 0   # accept new tasks
    PAUSE = 1    # no new tasks; existing keep running
    DRAIN = 2    # no new tasks; existing are rescheduled away


class NodeState(enum.IntEnum):
    UNKNOWN = 0
    DOWN = 1
    READY = 2
    DISCONNECTED = 3


@dataclass
class Version:
    """Optimistic-concurrency version: the store index at last write
    (reference: api/types.proto:14)."""

    index: int = 0

    def copy(self) -> "Version":
        return Version(self.index)


@dataclass
class Annotations:
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    indices: Dict[str, str] = field(default_factory=dict)  # custom indexes

    def copy(self) -> "Annotations":
        return Annotations(self.name, dict(self.labels), dict(self.indices))


class GenericResourceKind(enum.IntEnum):
    DISCRETE = 0  # a count, e.g. gpu=4
    NAMED = 1     # a named unit of a set, e.g. gpu=uuid1


@dataclass(frozen=True)
class GenericResource:
    """A custom node resource (reference: api/types.proto:38-59).

    Discrete resources carry a count in ``value``; named resources carry the
    unit id in ``value_str``.
    """

    kind: str                  # resource kind, e.g. "gpu", "fpga"
    value: int = 0             # count (DISCRETE)
    value_str: str = ""        # unit name (NAMED)
    res_type: GenericResourceKind = GenericResourceKind.DISCRETE


@dataclass
class Resources:
    """Normalized resources (reference: api/types.proto:68).

    nano_cpus: 1e-9 CPUs so integer math is exact (3.5 CPUs == 3_500_000_000).
    memory_bytes: bytes.
    generic: custom resources (GPUs etc.).
    """

    nano_cpus: int = 0
    memory_bytes: int = 0
    generic: List[GenericResource] = field(default_factory=list)

    def copy(self) -> "Resources":
        return Resources(self.nano_cpus, self.memory_bytes, list(self.generic))


@dataclass
class ResourceRequirements:
    reservations: Optional[Resources] = None
    limits: Optional[Resources] = None

    def copy(self) -> "ResourceRequirements":
        return ResourceRequirements(
            self.reservations.copy() if self.reservations else None,
            self.limits.copy() if self.limits else None,
        )


@dataclass
class Platform:
    architecture: str = ""
    os: str = ""

    def copy(self) -> "Platform":
        return Platform(self.architecture, self.os)


@dataclass
class PluginDescription:
    type: str = ""   # "Volume" | "Network" | "Log" | csi plugin name...
    name: str = ""


@dataclass
class EngineDescription:
    engine_version: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    plugins: List[PluginDescription] = field(default_factory=list)

    def copy(self) -> "EngineDescription":
        return EngineDescription(self.engine_version, dict(self.labels),
                                 list(self.plugins))


@dataclass
class NodeCSIInfo:
    plugin_name: str = ""
    node_id: str = ""
    max_volumes_per_node: int = 0
    accessible_topology: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeDescription:
    """What a node reports about itself (reference: api/types.proto:127)."""

    hostname: str = ""
    platform: Platform = field(default_factory=Platform)
    resources: Resources = field(default_factory=Resources)
    engine: EngineDescription = field(default_factory=EngineDescription)
    tls_info: Optional["NodeTLSInfo"] = None
    fips: bool = False
    csi_info: List[NodeCSIInfo] = field(default_factory=list)

    def copy(self) -> "NodeDescription":
        # None-tolerant: executors may report partial descriptions
        # (e.g. resources only), and the store defensively copies every
        # node write — a partial description must round-trip, not crash
        return NodeDescription(
            hostname=self.hostname,
            platform=self.platform.copy() if self.platform else None,
            resources=self.resources.copy() if self.resources else None,
            engine=self.engine.copy() if self.engine else None,
            tls_info=self.tls_info, fips=self.fips,
            csi_info=list(self.csi_info))


@dataclass
class NodeTLSInfo:
    trust_root: bytes = b""
    cert_issuer_subject: bytes = b""
    cert_issuer_public_key: bytes = b""


@dataclass
class NodeStatus:
    state: NodeState = NodeState.UNKNOWN
    message: str = ""
    addr: str = ""

    def copy(self) -> "NodeStatus":
        return NodeStatus(self.state, self.message, self.addr)


@dataclass
class RaftMemberStatus:
    leader: bool = False
    reachability: int = 0  # 0 unknown / 1 unreachable / 2 reachable
    message: str = ""


class RestartCondition(enum.IntEnum):
    NONE = 0
    ON_FAILURE = 1
    ANY = 2


@dataclass
class RestartPolicy:
    """reference: api/types.proto:380"""

    condition: RestartCondition = RestartCondition.ANY
    delay: float = 5.0            # seconds between restarts
    max_attempts: int = 0         # 0 = unlimited (within window)
    window: float = 0.0           # seconds; 0 = unbounded attempt window

    def copy(self) -> "RestartPolicy":
        return dataclasses.replace(self)


class UpdateFailureAction(enum.IntEnum):
    PAUSE = 0
    CONTINUE = 1
    ROLLBACK = 2


class UpdateOrder(enum.IntEnum):
    STOP_FIRST = 0
    START_FIRST = 1


@dataclass
class UpdateConfig:
    """Rolling-update knobs (reference: api/types.proto:407)."""

    parallelism: int = 0          # 0 = all at once
    delay: float = 0.0            # seconds between batches
    failure_action: UpdateFailureAction = UpdateFailureAction.PAUSE
    monitor: float = 30.0         # seconds to monitor each task for failure
    max_failure_ratio: float = 0.0
    order: UpdateOrder = UpdateOrder.STOP_FIRST

    def copy(self) -> "UpdateConfig":
        return dataclasses.replace(self)


class UpdateState(enum.IntEnum):
    UNKNOWN = 0
    UPDATING = 1
    PAUSED = 2
    COMPLETED = 3
    ROLLBACK_STARTED = 4
    ROLLBACK_PAUSED = 5
    ROLLBACK_COMPLETED = 6


@dataclass
class UpdateStatus:
    state: UpdateState = UpdateState.UNKNOWN
    started_at: float = 0.0
    completed_at: float = 0.0
    message: str = ""

    def copy(self) -> "UpdateStatus":
        return dataclasses.replace(self)


@dataclass
class ContainerStatus:
    container_id: str = ""
    pid: int = 0
    exit_code: int = 0


@dataclass
class PortStatus:
    ports: List["PortConfig"] = field(default_factory=list)


@dataclass
class TaskStatus:
    """Observed task state (reference: api/types.proto:572)."""

    timestamp: float = 0.0
    state: TaskState = TaskState.NEW
    message: str = ""
    err: str = ""
    container: Optional[ContainerStatus] = None
    port_status: Optional[PortStatus] = None
    applied_by: str = ""   # node that reported this status
    applied_at: float = 0.0

    def copy(self) -> "TaskStatus":
        # hot path (copied with every Task.copy): avoid dataclasses.replace
        new = object.__new__(TaskStatus)
        new.__dict__.update(self.__dict__)
        return new


class PortProtocol(enum.IntEnum):
    TCP = 0
    UDP = 1
    SCTP = 2


class PublishMode(enum.IntEnum):
    INGRESS = 0  # routing-mesh: port reserved on every node
    HOST = 1     # published directly on the host the task lands on


@dataclass(frozen=True)
class PortConfig:
    """reference: api/types.proto:682"""

    name: str = ""
    protocol: PortProtocol = PortProtocol.TCP
    target_port: int = 0
    published_port: int = 0
    publish_mode: PublishMode = PublishMode.INGRESS


class EndpointResolutionMode(enum.IntEnum):
    VIP = 0
    DNSRR = 1


@dataclass
class EndpointSpec:
    mode: EndpointResolutionMode = EndpointResolutionMode.VIP
    ports: List[PortConfig] = field(default_factory=list)

    def copy(self) -> "EndpointSpec":
        return EndpointSpec(self.mode, list(self.ports))


@dataclass
class EndpointVIP:
    network_id: str = ""
    addr: str = ""


@dataclass
class Endpoint:
    """Runtime endpoint state attached to services/tasks
    (reference: api/objects.proto:147)."""

    spec: EndpointSpec = field(default_factory=EndpointSpec)
    ports: List[PortConfig] = field(default_factory=list)
    virtual_ips: List[EndpointVIP] = field(default_factory=list)

    def copy(self) -> "Endpoint":
        return Endpoint(self.spec.copy(), list(self.ports),
                        list(self.virtual_ips))


@dataclass(frozen=True)
class SpreadOver:
    spread_descriptor: str = ""   # e.g. "node.labels.datacenter"


@dataclass(frozen=True)
class PlacementPreference:
    spread: Optional[SpreadOver] = None


@dataclass
class GangConfig:
    """All-or-nothing (gang) placement policy (scheduler/gang.py).

    A service or job carrying a gang config is admitted atomically: the
    scheduler places every pending member of the gang in one
    epoch-pinned commit, or defers the whole gang — never a partial
    placement that strands quota or deadlocks against another
    half-placed gang.  ``min_size`` is the member count that must place
    together; 0 means "the whole pending group".  Topology packing or
    spreading hints are expressed through the ordinary constraint/
    spread-preference machinery on the same Placement.
    """

    min_size: int = 0

    def copy(self) -> "GangConfig":
        return GangConfig(self.min_size)


@dataclass
class Placement:
    """reference: api/types.proto:909"""

    constraints: List[str] = field(default_factory=list)  # "key==value" exprs
    preferences: List[PlacementPreference] = field(default_factory=list)
    platforms: List[Platform] = field(default_factory=list)
    max_replicas: int = 0   # per-node cap; 0 = unlimited
    # placement-scoring strategy (scheduler/strategy.py registry):
    # "" / "spread" (default, reference semantics), "binpack",
    # "weighted", "learned".  Validated by controlapi; an unknown name
    # on a task written behind the API degrades to spread (counted).
    strategy: str = ""
    # per-service term weights for the "weighted" strategy (keys:
    # spread/cpu/mem/generic; ints clamped to [0, W_CLAMP] — see
    # scheduler/strategy.py); ignored by the other strategies
    strategy_weights: Dict[str, int] = field(default_factory=dict)
    # all-or-nothing admission; None = ordinary per-task placement
    gang: Optional[GangConfig] = None

    def copy(self) -> "Placement":
        return Placement(list(self.constraints), list(self.preferences),
                         [p.copy() for p in self.platforms],
                         self.max_replicas, self.strategy,
                         dict(self.strategy_weights),
                         self.gang.copy() if self.gang else None)


@dataclass
class Driver:
    name: str = ""
    options: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "Driver":
        return Driver(self.name, dict(self.options))


@dataclass
class JoinTokens:
    worker: str = ""
    manager: str = ""

    def copy(self) -> "JoinTokens":
        return JoinTokens(self.worker, self.manager)


@dataclass
class EncryptionKey:
    subsystem: str = ""
    algorithm: int = 0
    key: bytes = b""
    lamport_time: int = 0


@dataclass
class CAConfig:
    node_cert_expiry: float = 90 * 24 * 3600.0  # seconds
    external_cas: List[str] = field(default_factory=list)
    signing_ca_cert: bytes = b""
    signing_ca_key: bytes = b""
    force_rotate: int = 0

    def copy(self) -> "CAConfig":
        return dataclasses.replace(self, external_cas=list(self.external_cas))


@dataclass
class OrchestrationConfig:
    task_history_retention_limit: int = 5

    def copy(self) -> "OrchestrationConfig":
        return dataclasses.replace(self)


@dataclass
class DispatcherConfig:
    # 0 = unset: the manager's configured default applies (reference:
    # api/types.proto DispatcherConfig.heartbeat_period, 0 means default)
    heartbeat_period: float = 0.0

    def copy(self) -> "DispatcherConfig":
        return dataclasses.replace(self)


@dataclass
class TenantQuota:
    """Per-tenant resource quota (multi-tenant QoS, ClusterSpec.tenants).

    A tenant is named by the ``swarm.tenant`` service-annotation label;
    the quota caps the COMMITTED reservations of its assigned, live
    tasks.  0 on any dimension = that dimension is unlimited.  The
    scheduler enforces quotas at admission (scheduler/quota.py): a
    tenant's burst is clamped before placement, never fought by
    preemption after the fact.
    """

    nano_cpus: int = 0
    memory_bytes: int = 0
    max_tasks: int = 0

    def copy(self) -> "TenantQuota":
        return dataclasses.replace(self)


@dataclass
class RaftConfig:
    snapshot_interval: int = 10000
    keep_old_snapshots: int = 0
    log_entries_for_slow_followers: int = 500
    heartbeat_tick: int = 1
    election_tick: int = 3

    def copy(self) -> "RaftConfig":
        return dataclasses.replace(self)


@dataclass
class EncryptionConfig:
    auto_lock_managers: bool = False

    def copy(self) -> "EncryptionConfig":
        return dataclasses.replace(self)


@dataclass
class TaskDefaults:
    log_driver: Optional[Driver] = None

    def copy(self) -> "TaskDefaults":
        return TaskDefaults(self.log_driver.copy() if self.log_driver else None)


# ---------------------------------------------------------------------------
# Volumes (CSI)
# ---------------------------------------------------------------------------

class VolumeAccessScope(enum.IntEnum):
    SINGLE_NODE = 0
    MULTI_NODE = 1


class VolumeSharing(enum.IntEnum):
    NONE = 0
    READONLY = 1
    ONEWRITER = 2
    ALL = 3


class VolumeAvailability(enum.IntEnum):
    ACTIVE = 0
    PAUSE = 1
    DRAIN = 2


@dataclass
class VolumeAccessMode:
    scope: VolumeAccessScope = VolumeAccessScope.SINGLE_NODE
    sharing: VolumeSharing = VolumeSharing.NONE
    block: bool = False  # block device vs mount

    def copy(self) -> "VolumeAccessMode":
        return dataclasses.replace(self)


@dataclass
class TopologyRequirement:
    requisite: List[Dict[str, str]] = field(default_factory=list)
    preferred: List[Dict[str, str]] = field(default_factory=list)

    def copy(self) -> "TopologyRequirement":
        return TopologyRequirement([dict(t) for t in self.requisite],
                                   [dict(t) for t in self.preferred])


@dataclass
class VolumePublishStatus:
    class State(enum.IntEnum):
        PENDING_PUBLISH = 0
        PUBLISHED = 1
        PENDING_NODE_UNPUBLISH = 2
        PENDING_UNPUBLISH = 3

    node_id: str = ""
    state: "VolumePublishStatus.State" = 0  # type: ignore[assignment]
    publish_context: Dict[str, str] = field(default_factory=dict)
    message: str = ""

    def copy(self) -> "VolumePublishStatus":
        return VolumePublishStatus(self.node_id, self.state,
                                   dict(self.publish_context), self.message)


@dataclass
class VolumeAttachment:
    id: str = ""       # volume object id
    source: str = ""   # mount source as given in the task spec
    target: str = ""   # mount target

    def copy(self) -> "VolumeAttachment":
        return dataclasses.replace(self)


class MountType(enum.IntEnum):
    BIND = 0
    VOLUME = 1
    TMPFS = 2
    NPIPE = 3
    CSI = 4


@dataclass
class Mount:
    type: MountType = MountType.VOLUME
    source: str = ""
    target: str = ""
    readonly: bool = False
    volume_driver: str = ""   # driver name for VOLUME mounts

    def copy(self) -> "Mount":
        return dataclasses.replace(self)


# ---------------------------------------------------------------------------
# Networks
# ---------------------------------------------------------------------------

@dataclass
class IPAMConfig:
    family: int = 4
    subnet: str = ""
    range: str = ""
    gateway: str = ""
    reserved: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "IPAMConfig":
        return dataclasses.replace(self, reserved=dict(self.reserved))


@dataclass
class IPAMOptions:
    driver: Optional[Driver] = None
    configs: List[IPAMConfig] = field(default_factory=list)

    def copy(self) -> "IPAMOptions":
        return IPAMOptions(self.driver.copy() if self.driver else None,
                           [c.copy() for c in self.configs])


@dataclass
class NetworkAttachmentConfig:
    target: str = ""  # network id or name
    aliases: List[str] = field(default_factory=list)
    addresses: List[str] = field(default_factory=list)
    driver_attachment_opts: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "NetworkAttachmentConfig":
        return NetworkAttachmentConfig(self.target, list(self.aliases),
                                       list(self.addresses),
                                       dict(self.driver_attachment_opts))


@dataclass
class NetworkAttachment:
    network_id: str = ""
    addresses: List[str] = field(default_factory=list)
    aliases: List[str] = field(default_factory=list)

    def copy(self) -> "NetworkAttachment":
        return NetworkAttachment(self.network_id, list(self.addresses),
                                 list(self.aliases))


# ---------------------------------------------------------------------------
# Secrets / configs references
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SecretReference:
    secret_id: str = ""
    secret_name: str = ""
    target: str = ""   # filename in the container


@dataclass(frozen=True)
class ConfigReference:
    config_id: str = ""
    config_name: str = ""
    target: str = ""


__all__ = [name for name in dir() if not name.startswith("_")]
