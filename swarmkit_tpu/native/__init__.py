"""Native hot-path loader.

Compiles hotpath.c on first use (cached as an in-place .so next to the
source) and falls back to the pure-Python implementations when compilation
or import fails — the package never *requires* the toolchain.  Set
SWARMKIT_TPU_NO_NATIVE=1 to force the Python paths (used by differential
tests that pit the two implementations against each other).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys

log = logging.getLogger("native")

_mod = None
_tried = False


def get():
    """Return the _hotpath C module, or None when unavailable/disabled."""
    global _mod, _tried
    if os.environ.get("SWARMKIT_TPU_NO_NATIVE"):
        return None
    if _tried:
        return _mod
    _tried = True
    try:
        from . import _hotpath as m  # type: ignore[attr-defined]
        _mod = m
        return _mod
    except ImportError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "build.py")],
            check=True, capture_output=True, timeout=300, cwd=here)
        from . import _hotpath as m  # type: ignore[attr-defined]
        _mod = m
    except Exception as e:  # toolchain missing, etc. — run pure-Python
        log.warning("native hotpath unavailable (%s); using Python paths", e)
        _mod = None
    return _mod
