"""Native hot-path loader.

Compiles hotpath.c on first use (cached as an in-place .so next to the
source) and falls back to the pure-Python implementations when compilation
or import fails — the package never *requires* the toolchain.  Set
SWARMKIT_TPU_NO_NATIVE=1 to force the Python paths (used by differential
tests that pit the two implementations against each other).

Staleness: ``build.py`` stamps the sha256 of ``hotpath.c`` next to the
.so; ``get()`` rebuilds before importing whenever the stamp disagrees
with the current source, so an edited hotpath.c can never be served by a
stale prebuilt module (scripts/ci_check.sh enforces the same hash).

The columnar commit plane (binary block raft entries, native decode /
follower apply / watch fan-out) has its own escape hatch on top:
``SWARM_NATIVE_COMMIT=0`` routes it to the pure-Python oracle paths —
same breaker discipline as the device planner.  ``get_commit()`` is the
accessor those call sites use; when the native module is unavailable
while the commit plane is *not* explicitly disabled, each call counts a
``swarm_native_commit_fallbacks`` tick so a bench window can prove the
native path actually ran (scripts/bench_compare.py gates on it).
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import sys

log = logging.getLogger("native")

_mod = None
_tried = False


def _source_stale() -> bool:
    """True when the in-place .so predates the current hotpath.c (or
    has no stamp at all — pre-stamp builds)."""
    here = os.path.dirname(os.path.abspath(__file__))
    stamp = os.path.join(here, "_hotpath.src.sha256")
    try:
        with open(stamp) as f:
            recorded = f.read().strip()
        with open(os.path.join(here, "hotpath.c"), "rb") as f:
            current = hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return True
    return recorded != current


def _rebuild() -> bool:
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "build.py")],
            check=True, capture_output=True, timeout=300, cwd=here)
        return True
    except Exception as e:  # toolchain missing, etc. — run pure-Python
        log.warning("native hotpath build failed (%s); using Python "
                    "paths", e)
        return False


def get():
    """Return the _hotpath C module, or None when unavailable/disabled."""
    global _mod, _tried
    if os.environ.get("SWARMKIT_TPU_NO_NATIVE"):
        return None
    if _tried:
        return _mod
    _tried = True
    if _source_stale() and not _rebuild():
        # a stale .so would serve old semantics for new source — worse
        # than the Python fallback, which is always current
        _mod = None
        return _mod
    try:
        from . import _hotpath as m  # type: ignore[attr-defined]
        _mod = m
        return _mod
    except ImportError:
        pass
    # fresh stamp but no importable .so (e.g. a clean checkout whose
    # stamp survived while build artifacts are gitignored): build once
    if _rebuild():
        try:
            from . import _hotpath as m  # type: ignore[attr-defined]
            _mod = m
            return _mod
        except ImportError as e:
            log.warning("native hotpath unavailable (%s); using Python "
                        "paths", e)
    _mod = None
    return _mod


def commit_enabled() -> bool:
    """The columnar-commit-plane escape hatch, read per call so tests
    can flip it without reimporting."""
    return os.environ.get("SWARM_NATIVE_COMMIT", "1") != "0"


def get_commit():
    """The native module for the columnar commit plane (block decode,
    follower apply, watch fan-out), or None when disabled
    (``SWARM_NATIVE_COMMIT=0``) or unavailable.  An unavailable-but-
    requested native plane counts a fallback tick per call — the bench
    gate's evidence that a timed window really ran native."""
    if not commit_enabled():
        return None
    mod = get()
    if mod is None:
        from ..utils.metrics import registry as _metrics
        _metrics.counter("swarm_native_commit_fallbacks")
    return mod
