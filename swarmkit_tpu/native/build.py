"""Build the _hotpath C extension in place (invoked as a subprocess by
swarmkit_tpu.native on first import; see __init__.py)."""

import os

from setuptools import Extension, setup

os.chdir(os.path.dirname(os.path.abspath(__file__)))

setup(
    name="swarmkit-tpu-hotpath",
    script_args=["build_ext", "--inplace"],
    ext_modules=[
        Extension("_hotpath", ["hotpath.c"], extra_compile_args=["-O2"])
    ],
)
