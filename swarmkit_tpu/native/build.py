"""Build the _hotpath C extension in place (invoked as a subprocess by
swarmkit_tpu.native on first import; see __init__.py).

After a successful build the source hash is stamped next to the .so
(``_hotpath.src.sha256``): the loader and ``scripts/ci_check.sh`` both
compare it against the current ``hotpath.c`` so a stale prebuilt .so can
never silently serve an edited source file.
"""

import hashlib
import os

from setuptools import Extension, setup

HERE = os.path.dirname(os.path.abspath(__file__))
STAMP = os.path.join(HERE, "_hotpath.src.sha256")

os.chdir(HERE)

setup(
    name="swarmkit-tpu-hotpath",
    script_args=["build_ext", "--inplace"],
    ext_modules=[
        Extension("_hotpath", ["hotpath.c"], extra_compile_args=["-O2"])
    ],
)

with open(os.path.join(HERE, "hotpath.c"), "rb") as f:
    digest = hashlib.sha256(f.read()).hexdigest()
with open(STAMP, "w") as f:
    f.write(digest + "\n")
