/* Native hot path for the columnar scheduler commit.
 *
 * The TPU kernel plans a 100k-task group in ~0.1s; the Python loops that
 * clone Task objects and commit them to the store were ~10us/task and
 * dominated end-to-end throughput (see BASELINE.md).  This module moves
 * exactly those two loops to C:
 *
 *   plan_apply     - clone-and-register the planner's per-task decisions
 *                    (replaces ops/planner.py's apply loop body)
 *   commit_prepare - validate + version-check + stamp one commit chunk
 *                    (replaces the per-task half of store.bulk_update_tasks)
 *   commit_apply   - install stamped tasks into the store table + indexes
 *
 * The columnar commit plane (ISSUE 13) extends the seam to the other
 * side of consensus and to watch delivery:
 *
 *   block_decode         - parse the compact binary task-block raft
 *                          entry (serde.BLOCK_ENTRY_MAGIC) into a
 *                          TaskBlockAction; the byte scan runs with the
 *                          GIL released
 *   block_apply_follower - follower-side apply of a decoded block into
 *                          the task overlay + by_node index, one batched
 *                          index pass per chunk
 *   fanout_expand        - synthesize the per-task watch Events of one
 *                          EventTaskBlock (the Python oracle is
 *                          events.EventTaskBlock.expand_events)
 *   fanout_filter        - per-subscriber predicate pre-filter over an
 *                          expanded event list
 *   per_node_group       - node_id -> [(old, version)] grouping for
 *                          block-aware dispatcher sessions
 *
 * Semantics are identical to the pure-Python implementations, which remain
 * as fallbacks (and as the differential-test oracle).  The reference has no
 * native code (SURVEY.md section 2); this is a deliberate tpu-framework
 * improvement, not parity work.
 *
 * All objects handled here are plain-dict Python instances following the
 * store's replace-don't-mutate convention, so a shallow __dict__ copy plus
 * targeted overrides reproduces Task.copy()/Meta.copy() exactly.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *s_dict, *s_meta, *s_version, *s_index, *s_created_at,
    *s_updated_at, *s_status, *s_node_id, *s_networks, *s_volumes, *s_agr,
    *s_id, *s_state, *s_message, *s_err, *s_service_id, *s_slot, *s_old,
    *s_new, *s_update, *s_task_block;
static PyObject *empty_tuple;

static PyObject *
new_instance(PyTypeObject *tp)
{
    return tp->tp_new(tp, empty_tuple, NULL);
}

/* Fresh instance of type(obj) with a copy of obj.__dict__.  If out_dict is
 * non-NULL it receives a NEW reference to the copied dict (mutating it
 * mutates the clone's attributes). */
static PyObject *
shallow_clone(PyObject *obj, PyObject **out_dict)
{
    PyTypeObject *tp = Py_TYPE(obj);
    PyObject *nobj = new_instance(tp);
    if (!nobj)
        return NULL;
    PyObject *od = PyObject_GetAttr(obj, s_dict);
    if (!od)
        goto fail;
    PyObject *d = PyDict_Copy(od);
    Py_DECREF(od);
    if (!d)
        goto fail;
    if (PyObject_SetAttr(nobj, s_dict, d) < 0) {
        Py_DECREF(d);
        goto fail;
    }
    if (out_dict)
        *out_dict = d; /* transfer our reference */
    else
        Py_DECREF(d);
    return nobj;
fail:
    Py_DECREF(nobj);
    return NULL;
}

/* Meta copy: clone meta and its nested Version (objects.py Meta.copy). */
static PyObject *
clone_meta(PyObject *meta)
{
    PyObject *md = NULL;
    PyObject *nm = shallow_clone(meta, &md);
    if (!nm)
        return NULL;
    PyObject *ver = PyDict_GetItem(md, s_version); /* borrowed */
    if (ver) {
        PyObject *nv = shallow_clone(ver, NULL);
        if (!nv) {
            Py_DECREF(md);
            Py_DECREF(nm);
            return NULL;
        }
        if (PyDict_SetItem(md, s_version, nv) < 0) {
            Py_DECREF(nv);
            Py_DECREF(md);
            Py_DECREF(nm);
            return NULL;
        }
        Py_DECREF(nv);
    }
    Py_DECREF(md);
    return nm;
}

/* Replace d[key] (a list) with a shallow copy of it. */
static int
copy_list_field(PyObject *d, PyObject *key)
{
    PyObject *lst = PyDict_GetItem(d, key);
    if (!lst)
        return 0;
    PyObject *c = PySequence_List(lst);
    if (!c)
        return -1;
    int r = PyDict_SetItem(d, key, c);
    Py_DECREF(c);
    return r;
}

/* plan_apply(items, slots, node_ids_by_node, task_dicts_by_node,
 *            shared_status, all_tasks, decisions, decision_cls) -> None
 *
 * items: list of (task_id, Task) pairs; slots: list of int node indices
 * (aligned with items); node_ids_by_node / task_dicts_by_node: per-*node*
 * lookup tables (id string, NodeInfo.tasks dict).  For each i: clone
 * items[i]'s task as an ASSIGNED task on node slots[i], register it in
 * all_tasks and that node's task map, and store decision_cls(old, new) in
 * decisions keyed by task id.  min(len(items), len(slots)) entries are
 * processed — slots may be shorter when the group did not fully fit.
 */
static PyObject *
plan_apply(PyObject *self, PyObject *args)
{
    PyObject *items, *slots, *node_ids, *task_dicts, *status, *all_tasks,
        *decisions, *decision_cls;
    if (!PyArg_ParseTuple(args, "OOOOOOOO", &items, &slots, &node_ids,
                          &task_dicts, &status, &all_tasks, &decisions,
                          &decision_cls))
        return NULL;
    if (!PyList_Check(items) || !PyList_Check(slots) ||
        !PyList_Check(node_ids) || !PyList_Check(task_dicts)) {
        PyErr_SetString(PyExc_TypeError, "expected lists");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(items);
    Py_ssize_t ns = PyList_GET_SIZE(slots);
    if (ns < n)
        n = ns;
    Py_ssize_t n_nodes = PyList_GET_SIZE(node_ids);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PyList_GET_ITEM(items, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError, "items must be (id, task)");
            return NULL;
        }
        PyObject *old = PyTuple_GET_ITEM(pair, 1);
        Py_ssize_t ni = PyLong_AsSsize_t(PyList_GET_ITEM(slots, i));
        if (ni < 0 || ni >= n_nodes) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_IndexError, "slot out of range");
            return NULL;
        }
        PyObject *nid = PyList_GET_ITEM(node_ids, ni);
        PyObject *idict = PyList_GET_ITEM(task_dicts, ni);
        PyObject *d = NULL;
        PyObject *nt = shallow_clone(old, &d);
        if (!nt)
            return NULL;
        PyObject *meta = PyDict_GetItem(d, s_meta);
        if (meta) {
            PyObject *nm = clone_meta(meta);
            if (!nm)
                goto item_fail;
            if (PyDict_SetItem(d, s_meta, nm) < 0) {
                Py_DECREF(nm);
                goto item_fail;
            }
            Py_DECREF(nm);
        }
        if (PyDict_SetItem(d, s_status, status) < 0 ||
            PyDict_SetItem(d, s_node_id, nid) < 0 ||
            copy_list_field(d, s_networks) < 0 ||
            copy_list_field(d, s_volumes) < 0)
            goto item_fail;
        PyObject *empty = PyList_New(0);
        if (!empty || PyDict_SetItem(d, s_agr, empty) < 0) {
            Py_XDECREF(empty);
            goto item_fail;
        }
        Py_DECREF(empty);
        PyObject *tid = PyDict_GetItem(d, s_id);
        if (!tid) {
            PyErr_SetString(PyExc_AttributeError, "task has no id");
            goto item_fail;
        }
        if (PyDict_SetItem(all_tasks, tid, nt) < 0 ||
            PyDict_SetItem(idict, tid, nt) < 0)
            goto item_fail;
        PyObject *dec = new_instance((PyTypeObject *)decision_cls);
        if (!dec)
            goto item_fail;
        if (PyObject_SetAttr(dec, s_old, old) < 0 ||
            PyObject_SetAttr(dec, s_new, nt) < 0 ||
            PyDict_SetItem(decisions, tid, dec) < 0) {
            Py_DECREF(dec);
            goto item_fail;
        }
        Py_DECREF(dec);
        Py_DECREF(d);
        Py_DECREF(nt);
        continue;
    item_fail:
        Py_XDECREF(d);
        Py_DECREF(nt);
        return NULL;
    }
    Py_RETURN_NONE;
}

/* block_stage(items, slots, node_ids_by_node, task_dicts_by_node)
 *   -> (olds, nids)
 *
 * Columnar staging of a planned group for the block-commit path: for each
 * of the min(len(items), len(slots)) placements, plant the (unmodified)
 * mirror task into its node's NodeInfo.tasks dict and emit parallel
 * olds/nids columns ready for MemoryStore.commit_task_block.  No task
 * objects are built — this replaces a per-task Python loop that allocated
 * a 3-tuple per placement (ops/planner.py block-mode apply).
 */
static PyObject *
block_stage(PyObject *self, PyObject *args)
{
    PyObject *items, *slots, *node_ids, *task_dicts;
    if (!PyArg_ParseTuple(args, "O!O!O!O!", &PyList_Type, &items,
                          &PyList_Type, &slots, &PyList_Type, &node_ids,
                          &PyList_Type, &task_dicts))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(items);
    Py_ssize_t ns = PyList_GET_SIZE(slots);
    if (ns < n)
        n = ns;
    Py_ssize_t n_nodes = PyList_GET_SIZE(node_ids);
    PyObject *olds = PyList_New(n);
    PyObject *nids = PyList_New(n);
    if (!olds || !nids)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PyList_GET_ITEM(items, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError, "items must be (id, task)");
            goto fail;
        }
        PyObject *tid = PyTuple_GET_ITEM(pair, 0);
        PyObject *task = PyTuple_GET_ITEM(pair, 1);
        Py_ssize_t ni = PyLong_AsSsize_t(PyList_GET_ITEM(slots, i));
        if (ni < 0 || ni >= n_nodes) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_IndexError, "slot out of range");
            goto fail;
        }
        PyObject *nid = PyList_GET_ITEM(node_ids, ni);
        PyObject *tdict = PyList_GET_ITEM(task_dicts, ni);
        if (PyDict_SetItem(tdict, tid, task) < 0)
            goto fail;
        Py_INCREF(task);
        PyList_SET_ITEM(olds, i, task);
        Py_INCREF(nid);
        PyList_SET_ITEM(nids, i, nid);
    }
    {
        PyObject *out = PyTuple_Pack(2, olds, nids);
        Py_DECREF(olds);
        Py_DECREF(nids);
        return out;
    }
fail:
    Py_XDECREF(olds);
    Py_XDECREF(nids);
    return NULL;
}

/* commit_prepare(new_tasks, start, stop, objects, seq_start, ts,
 *                guard_state, action_cls_or_None, event_cls_or_None,
 *                on_missing, on_assigned)
 *   -> (committed_idx, failed_idx, stamped, actions_or_None, events_or_None)
 *
 * Mirrors the validation half of MemoryStore.bulk_update_tasks:
 *   - missing stored object        -> on_missing(new), skip
 *   - status unchanged             -> skip
 *   - stored state >= guard_state  -> on_assigned(new) False => fail
 *   - version mismatch             -> fail (SequenceConflict semantics)
 *   - otherwise stamp version/timestamps and collect
 */
static PyObject *
commit_prepare(PyObject *self, PyObject *args)
{
    PyObject *new_tasks, *objects, *action_cls, *event_cls, *on_missing,
        *on_assigned, *guard_state;
    Py_ssize_t start, stop;
    long long seq;
    double ts;
    if (!PyArg_ParseTuple(args, "OnnOLdOOOOO", &new_tasks, &start, &stop,
                          &objects, &seq, &ts, &guard_state, &action_cls,
                          &event_cls, &on_missing, &on_assigned))
        return NULL;

    int want_actions = action_cls != Py_None;
    int want_events = event_cls != Py_None;
    PyObject *committed = PyList_New(0);
    PyObject *failed = PyList_New(0);
    PyObject *stamped = PyList_New(0);
    PyObject *actions = want_actions ? PyList_New(0) : Py_NewRef(Py_None);
    PyObject *events = want_events ? PyList_New(0) : Py_NewRef(Py_None);
    PyObject *ts_obj = PyFloat_FromDouble(ts);
    if (!committed || !failed || !stamped || !actions || !events || !ts_obj)
        goto fail;

    for (Py_ssize_t i = start; i < stop; i++) {
        PyObject *nt = PyList_GET_ITEM(new_tasks, i);
        PyObject *nd = PyObject_GetAttr(nt, s_dict);
        if (!nd)
            goto fail;
        PyObject *tid = PyDict_GetItem(nd, s_id);
        PyObject *cur = tid ? PyDict_GetItem(objects, tid) : NULL;
        if (!cur) {
            Py_DECREF(nd);
            PyObject *r = PyObject_CallOneArg(on_missing, nt);
            if (!r)
                goto fail;
            Py_DECREF(r);
            continue;
        }
        PyObject *cd = PyObject_GetAttr(cur, s_dict);
        if (!cd) {
            Py_DECREF(nd);
            goto fail;
        }
        /* status equality (state, message, err) */
        PyObject *cstat = PyDict_GetItem(cd, s_status);
        PyObject *nstat = PyDict_GetItem(nd, s_status);
        int skip = 0, failed_item = 0;
        PyObject *cs_state = NULL;
        if (cstat && nstat) {
            PyObject *csd = PyObject_GetAttr(cstat, s_dict);
            PyObject *nsd = PyObject_GetAttr(nstat, s_dict);
            if (!csd || !nsd) {
                Py_XDECREF(csd);
                Py_XDECREF(nsd);
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
            cs_state = PyDict_GetItem(csd, s_state);
            Py_XINCREF(cs_state);
            int eq = 1;
            PyObject *keys[3] = {s_state, s_message, s_err};
            for (int k = 0; k < 3 && eq; k++) {
                PyObject *a = PyDict_GetItem(csd, keys[k]);
                PyObject *b = PyDict_GetItem(nsd, keys[k]);
                if (a == b)
                    continue;
                if (!a || !b) {
                    eq = 0;
                    break;
                }
                int r = PyObject_RichCompareBool(a, b, Py_EQ);
                if (r < 0) {
                    Py_DECREF(csd);
                    Py_DECREF(nsd);
                    Py_XDECREF(cs_state);
                    Py_DECREF(cd);
                    Py_DECREF(nd);
                    goto fail;
                }
                eq = r;
            }
            Py_DECREF(csd);
            Py_DECREF(nsd);
            skip = eq;
        }
        if (!skip && cs_state) {
            int ge = PyObject_RichCompareBool(cs_state, guard_state, Py_GE);
            if (ge < 0) {
                Py_XDECREF(cs_state);
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
            if (ge) {
                PyObject *r = PyObject_CallOneArg(on_assigned, nt);
                if (!r) {
                    Py_XDECREF(cs_state);
                    Py_DECREF(cd);
                    Py_DECREF(nd);
                    goto fail;
                }
                int ok = PyObject_IsTrue(r);
                Py_DECREF(r);
                if (!ok)
                    failed_item = 1;
            }
        }
        Py_XDECREF(cs_state);
        if (skip) {
            Py_DECREF(cd);
            Py_DECREF(nd);
            continue;
        }
        PyObject *cmeta = PyDict_GetItem(cd, s_meta);
        PyObject *nmeta = PyDict_GetItem(nd, s_meta);
        if (!failed_item) {
            /* version check: cur.meta.version.index == new.meta.version.index */
            PyObject *cv = cmeta ? PyObject_GetAttr(cmeta, s_version) : NULL;
            PyObject *nv = nmeta ? PyObject_GetAttr(nmeta, s_version) : NULL;
            PyObject *cvi = cv ? PyObject_GetAttr(cv, s_index) : NULL;
            PyObject *nvi = nv ? PyObject_GetAttr(nv, s_index) : NULL;
            Py_XDECREF(cv);
            Py_XDECREF(nv);
            if (!cvi || !nvi) {
                Py_XDECREF(cvi);
                Py_XDECREF(nvi);
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
            int eq = PyObject_RichCompareBool(cvi, nvi, Py_EQ);
            Py_DECREF(cvi);
            Py_DECREF(nvi);
            if (eq < 0) {
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
            if (!eq)
                failed_item = 1;
        }
        if (failed_item) {
            PyObject *iobj = PyLong_FromSsize_t(i);
            int r = iobj ? PyList_Append(failed, iobj) : -1;
            Py_XDECREF(iobj);
            Py_DECREF(cd);
            Py_DECREF(nd);
            if (r < 0)
                goto fail;
            continue;
        }
        /* stamp */
        seq += 1;
        {
            PyObject *nv = PyObject_GetAttr(nmeta, s_version);
            PyObject *seq_obj = PyLong_FromLongLong(seq);
            PyObject *created = cmeta ? PyObject_GetAttr(cmeta, s_created_at)
                                      : NULL;
            int err = !nv || !seq_obj || !created ||
                      PyObject_SetAttr(nv, s_index, seq_obj) < 0 ||
                      PyObject_SetAttr(nmeta, s_created_at, created) < 0 ||
                      PyObject_SetAttr(nmeta, s_updated_at, ts_obj) < 0;
            Py_XDECREF(nv);
            Py_XDECREF(seq_obj);
            Py_XDECREF(created);
            if (err) {
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
        }
        PyObject *iobj = PyLong_FromSsize_t(i);
        int r = iobj ? PyList_Append(committed, iobj) : -1;
        Py_XDECREF(iobj);
        if (r < 0 || PyList_Append(stamped, nt) < 0) {
            Py_DECREF(cd);
            Py_DECREF(nd);
            goto fail;
        }
        if (want_actions) {
            PyObject *act = PyObject_CallFunctionObjArgs(action_cls, s_update,
                                                         nt, NULL);
            int ar = act ? PyList_Append(actions, act) : -1;
            Py_XDECREF(act);
            if (ar < 0) {
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
        }
        if (want_events) {
            PyObject *ev = PyObject_CallFunctionObjArgs(event_cls, s_update,
                                                        nt, cur, NULL);
            int er = ev ? PyList_Append(events, ev) : -1;
            Py_XDECREF(ev);
            if (er < 0) {
                Py_DECREF(cd);
                Py_DECREF(nd);
                goto fail;
            }
        }
        Py_DECREF(cd);
        Py_DECREF(nd);
    }
    Py_DECREF(ts_obj);
    PyObject *out = PyTuple_Pack(5, committed, failed, stamped, actions,
                                 events);
    Py_DECREF(committed);
    Py_DECREF(failed);
    Py_DECREF(stamped);
    Py_DECREF(actions);
    Py_DECREF(events);
    return out;
fail:
    Py_XDECREF(committed);
    Py_XDECREF(failed);
    Py_XDECREF(stamped);
    Py_XDECREF(actions);
    Py_XDECREF(events);
    Py_XDECREF(ts_obj);
    return NULL;
}

/* Equality of two borrowed dict values where either may be NULL (missing
 * key).  Returns 1/0, or -1 with an exception set.  Kept out of the `||`
 * short-circuit form: in C, `x || rich_compare()` turns an error return of
 * -1 into truthy 1, silently swallowing the pending exception. */
static int
dict_vals_equal(PyObject *a, PyObject *b)
{
    if (a == b)
        return 1;
    if (a == NULL || b == NULL)
        return 0;
    return PyObject_RichCompareBool(a, b, Py_EQ);
}

/* Index buckets are insertion-ordered {task_id: None} dicts, not sets:
 * indexed find() results feed placement decisions, and set iteration
 * order varies with hash randomization (per-process nondeterminism the
 * sim's byte-identical-report contract forbids).  Discard = guarded
 * delete; missing key is not an error (mirrors set.discard). */
static int
bucket_discard(PyObject *bucket, PyObject *key)
{
    int has = PyDict_Contains(bucket, key);
    if (has < 0)
        return -1;
    if (has && PyDict_DelItem(bucket, key) < 0)
        return -1;
    return 0;
}

/* commit_apply(stamped, objects, by_node, reindex_cb) -> None
 *
 * Install each stamped task into the objects table; maintain the by_node
 * index for the common case (only node_id changed).  reindex_cb(old, new)
 * handles the rare service/slot change. */
static PyObject *
commit_apply(PyObject *self, PyObject *args)
{
    PyObject *stamped, *objects, *by_node, *reindex_cb;
    if (!PyArg_ParseTuple(args, "OOOO", &stamped, &objects, &by_node,
                          &reindex_cb))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(stamped);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *obj = PyList_GET_ITEM(stamped, i);
        PyObject *d = PyObject_GetAttr(obj, s_dict);
        if (!d)
            return NULL;
        PyObject *tid = PyDict_GetItem(d, s_id);
        if (!tid) {
            PyErr_SetString(PyExc_KeyError, "stamped task has no id");
            Py_DECREF(d);
            return NULL;
        }
        PyObject *old = PyDict_GetItem(objects, tid); /* borrowed */
        Py_XINCREF(old);
        if (PyDict_SetItem(objects, tid, obj) < 0) {
            Py_XDECREF(old);
            Py_DECREF(d);
            return NULL;
        }
        if (old) {
            PyObject *od = PyObject_GetAttr(old, s_dict);
            if (!od) {
                Py_DECREF(old);
                Py_DECREF(d);
                return NULL;
            }
            PyObject *osid = PyDict_GetItem(od, s_service_id);
            PyObject *nsid = PyDict_GetItem(d, s_service_id);
            PyObject *oslot = PyDict_GetItem(od, s_slot);
            PyObject *nslot = PyDict_GetItem(d, s_slot);
            int same_sid = dict_vals_equal(osid, nsid);
            int same_slot = same_sid < 0 ? 0
                            : dict_vals_equal(oslot, nslot);
            if (same_sid < 0 || same_slot < 0) {
                Py_DECREF(od);
                Py_DECREF(old);
                Py_DECREF(d);
                return NULL;
            }
            if (!same_sid || !same_slot) {
                PyObject *r = PyObject_CallFunctionObjArgs(reindex_cb, old,
                                                           obj, NULL);
                if (!r) {
                    Py_DECREF(od);
                    Py_DECREF(old);
                    Py_DECREF(d);
                    return NULL;
                }
                Py_DECREF(r);
            }
            else {
                PyObject *onid = PyDict_GetItem(od, s_node_id);
                PyObject *nnid = PyDict_GetItem(d, s_node_id);
                int eq = dict_vals_equal(onid, nnid);
                if (eq < 0) {
                    Py_DECREF(od);
                    Py_DECREF(old);
                    Py_DECREF(d);
                    return NULL;
                }
                if (!eq) {
                    if (onid && PyObject_IsTrue(onid)) {
                        PyObject *st = PyDict_GetItem(by_node, onid);
                        if (st && bucket_discard(st, tid) < 0) {
                            Py_DECREF(od);
                            Py_DECREF(old);
                            Py_DECREF(d);
                            return NULL;
                        }
                    }
                    if (nnid && PyObject_IsTrue(nnid)) {
                        PyObject *st = PyDict_GetItem(by_node, nnid);
                        if (!st) {
                            PyObject *ns = PyDict_New();
                            if (!ns ||
                                PyDict_SetItem(by_node, nnid, ns) < 0) {
                                Py_XDECREF(ns);
                                Py_DECREF(od);
                                Py_DECREF(old);
                                Py_DECREF(d);
                                return NULL;
                            }
                            Py_DECREF(ns);
                            st = PyDict_GetItem(by_node, nnid);
                        }
                        if (PyDict_SetItem(st, tid, Py_None) < 0) {
                            Py_DECREF(od);
                            Py_DECREF(old);
                            Py_DECREF(d);
                            return NULL;
                        }
                    }
                }
            }
            Py_DECREF(od);
            Py_DECREF(old);
        }
        Py_DECREF(d);
    }
    Py_RETURN_NONE;
}

/* block_commit(old_tasks, node_ids, objects, overlay, by_node,
 *              ts, state, message, start_seq, guard_state)
 *   -> (committed, slow, new_seq)
 *
 * Fast path of MemoryStore.commit_task_block: items whose mirror object
 * IS the stored object (pointer identity), with no pending overlay entry
 * and a stored state below guard_state, commit by writing an overlay
 * tuple (node_id, version, ts, state, message) and maintaining the
 * by_node index.  Everything else lands in `slow` (list of indices) for
 * the Python caller's full-semantics loop.  No Task objects are built.
 */
static PyObject *
block_commit(PyObject *self, PyObject *args)
{
    PyObject *old_tasks, *node_ids, *objects, *overlay, *by_node;
    PyObject *ts, *state, *message, *guard_state;
    long long seq;
    if (!PyArg_ParseTuple(args, "O!O!O!O!O!OOOLO", &PyList_Type,
                          &old_tasks, &PyList_Type, &node_ids,
                          &PyDict_Type, &objects, &PyDict_Type, &overlay,
                          &PyDict_Type, &by_node, &ts, &state, &message,
                          &seq, &guard_state))
        return NULL;
    /* the guard is an IntEnum: convert once so the per-task check is a
     * plain C compare instead of a RichCompare through enum __ge__ */
    long long guard_ll = PyLong_AsLongLong(guard_state);
    int guard_ok = !(guard_ll == -1 && PyErr_Occurred());
    if (!guard_ok)
        PyErr_Clear();
    Py_ssize_t n = PyList_GET_SIZE(old_tasks);
    if (PyList_GET_SIZE(node_ids) != n) {
        PyErr_SetString(PyExc_ValueError, "old_tasks/node_ids mismatch");
        return NULL;
    }
    PyObject *committed = PyList_New(0);
    PyObject *slow = PyList_New(0);
    if (!committed || !slow)
        goto fail;
    /* the planner emits placements sorted by node (np.repeat over the
     * per-node counts), so consecutive items usually share a node: cache
     * the by_node set across the run instead of a dict lookup per task */
    PyObject *run_nid = NULL;  /* borrowed; element of node_ids */
    PyObject *run_set = NULL;  /* borrowed; by_node[run_nid] or NULL */
    /* committed is usually exactly range(n): track contiguity and only
     * materialize index objects once a gap appears */
    Py_ssize_t n_contig = 0;
    int contiguous = 1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *old = PyList_GET_ITEM(old_tasks, i);
        /* instance dicts via the dict pointer: dataclass instances always
         * have one, and this skips the __dict__ descriptor machinery on
         * the hottest lookup of the loop (falls back for odd objects) */
        PyObject **dp = _PyObject_GetDictPtr(old);
        PyObject *d;
        if (dp != NULL && *dp != NULL) {
            d = *dp;
            Py_INCREF(d);   /* keep the DECREF discipline uniform */
        } else {
            d = PyObject_GetAttr(old, s_dict);
            if (!d)
                goto fail;
        }
        PyObject *tid = PyDict_GetItem(d, s_id);
        int take_slow = 0;
        if (!tid) {
            take_slow = 1;
        } else {
            PyObject *cur = PyDict_GetItem(objects, tid);
            int in_overlay = PyDict_Contains(overlay, tid);
            if (in_overlay < 0) {
                Py_DECREF(d);
                goto fail;
            }
            if (cur != old || in_overlay) {
                take_slow = 1;
            } else {
                PyObject *status = PyDict_GetItem(d, s_status);
                PyObject *st = NULL;
                if (status != NULL) {
                    PyObject **sdp = _PyObject_GetDictPtr(status);
                    if (sdp != NULL && *sdp != NULL)
                        st = PyDict_GetItem(*sdp, s_state); /* borrowed */
                }
                if (!st) {
                    take_slow = 1;
                } else if (guard_ok) {
                    long long stv = PyLong_AsLongLong(st);
                    if (stv == -1 && PyErr_Occurred()) {
                        PyErr_Clear();
                        take_slow = 1;
                    } else {
                        take_slow = stv >= guard_ll;
                    }
                } else {
                    int ge = PyObject_RichCompareBool(st, guard_state,
                                                      Py_GE);
                    if (ge < 0) {
                        Py_DECREF(d);
                        goto fail;
                    }
                    take_slow = ge;   /* guard conflict: Python decides */
                }
            }
        }
        if (take_slow) {
            PyObject *idx = PyLong_FromSsize_t(i);
            int r = idx ? PyList_Append(slow, idx) : -1;
            Py_XDECREF(idx);
            Py_DECREF(d);
            if (r < 0)
                goto fail;
            if (contiguous) {
                /* a gap: backfill 0..n_contig-1 and switch to appends */
                contiguous = 0;
                for (Py_ssize_t j = 0; j < n_contig; j++) {
                    PyObject *jo = PyLong_FromSsize_t(j);
                    int jr = jo ? PyList_Append(committed, jo) : -1;
                    Py_XDECREF(jo);
                    if (jr < 0)
                        goto fail;
                }
            }
            continue;
        }
        /* accept: overlay entry + by_node index + version */
        seq++;
        PyObject *nid = PyList_GET_ITEM(node_ids, i);
        PyObject *ver = PyLong_FromLongLong(seq);
        if (!ver) {
            Py_DECREF(d);
            goto fail;
        }
        PyObject *entry = PyTuple_Pack(5, nid, ver, ts, state, message);
        Py_DECREF(ver);
        if (!entry || PyDict_SetItem(overlay, tid, entry) < 0) {
            Py_XDECREF(entry);
            Py_DECREF(d);
            goto fail;
        }
        Py_DECREF(entry);
        PyObject *onid = PyDict_GetItem(d, s_node_id);
        if (onid && PyObject_IsTrue(onid) && onid != nid) {
            int eq = dict_vals_equal(onid, nid);
            if (eq < 0) {
                Py_DECREF(d);
                goto fail;
            }
            if (!eq) {
                PyObject *os = PyDict_GetItem(by_node, onid);
                if (os && bucket_discard(os, tid) < 0) {
                    Py_DECREF(d);
                    goto fail;
                }
            }
        }
        if (nid != run_nid) {
            run_nid = nid;
            run_set = NULL;
            if (PyObject_IsTrue(nid)) {
                run_set = PyDict_GetItem(by_node, nid);
                if (!run_set) {
                    PyObject *fresh = PyDict_New();
                    if (!fresh ||
                        PyDict_SetItem(by_node, nid, fresh) < 0) {
                        Py_XDECREF(fresh);
                        Py_DECREF(d);
                        goto fail;
                    }
                    Py_DECREF(fresh);
                    run_set = PyDict_GetItem(by_node, nid);
                }
            }
        }
        if (run_set && PyDict_SetItem(run_set, tid, Py_None) < 0) {
            Py_DECREF(d);
            goto fail;
        }
        if (contiguous) {
            /* while contiguous, every accepted item has i == n_contig:
             * the only way to skip an index is the slow branch, which
             * clears the flag and backfills */
            n_contig++;
        } else {
            PyObject *idx = PyLong_FromSsize_t(i);
            int r = idx ? PyList_Append(committed, idx) : -1;
            Py_XDECREF(idx);
            if (r < 0) {
                Py_DECREF(d);
                goto fail;
            }
        }
        Py_DECREF(d);
    }
    {
        PyObject *out;
        if (contiguous) {
            /* all items fast-committed in order: hand back range(n_contig)
             * instead of n PyLong list entries */
            PyObject *rng = PyObject_CallFunction(
                (PyObject *)&PyRange_Type, "n", n_contig);
            if (!rng)
                goto fail;
            out = Py_BuildValue("(OOL)", rng, slow, seq);
            Py_DECREF(rng);
        } else {
            out = Py_BuildValue("(OOL)", committed, slow, seq);
        }
        Py_DECREF(committed);
        Py_DECREF(slow);
        return out;
    }
fail:
    Py_XDECREF(committed);
    Py_XDECREF(slow);
    return NULL;
}

/* block_validate(old_tasks, node_ids, objects, overlay, guard_state)
 *     -> (accepted: range|list, slow: list)
 *
 * Read-only screen for the PROPOSER block path (store.py
 * _commit_task_block_proposed): an item fast-accepts when the mirror IS
 * the stored instance, is not overlaid, and its status state is below
 * the guard; everything else routes to the Python slow loop for the
 * full bulk-path checks.  No writes — the overlay/index mutation runs
 * later, inside the consensus apply callback (block_apply below). */
static PyObject *
block_validate(PyObject *self, PyObject *args)
{
    PyObject *old_tasks, *node_ids, *objects, *overlay, *guard_state;
    if (!PyArg_ParseTuple(args, "O!O!O!O!O", &PyList_Type, &old_tasks,
                          &PyList_Type, &node_ids, &PyDict_Type, &objects,
                          &PyDict_Type, &overlay, &guard_state))
        return NULL;
    long long guard_ll = PyLong_AsLongLong(guard_state);
    int guard_ok = !(guard_ll == -1 && PyErr_Occurred());
    if (!guard_ok)
        PyErr_Clear();
    Py_ssize_t n = PyList_GET_SIZE(old_tasks);
    if (PyList_GET_SIZE(node_ids) != n) {
        PyErr_SetString(PyExc_ValueError, "old_tasks/node_ids mismatch");
        return NULL;
    }
    PyObject *accepted = PyList_New(0);
    PyObject *slow = PyList_New(0);
    if (!accepted || !slow)
        goto fail;
    Py_ssize_t n_contig = 0;
    int contiguous = 1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *old = PyList_GET_ITEM(old_tasks, i);
        PyObject **dp = _PyObject_GetDictPtr(old);
        PyObject *d = (dp != NULL && *dp != NULL) ? *dp : NULL;
        int take_slow = 0;
        PyObject *tid = d ? PyDict_GetItem(d, s_id) : NULL;
        if (!tid) {
            take_slow = 1;
        } else {
            PyObject *cur = PyDict_GetItem(objects, tid);
            int in_overlay = PyDict_Contains(overlay, tid);
            if (in_overlay < 0)
                goto fail;
            if (cur != old || in_overlay) {
                take_slow = 1;
            } else {
                PyObject *status = PyDict_GetItem(d, s_status);
                PyObject *st = NULL;
                if (status != NULL) {
                    PyObject **sdp = _PyObject_GetDictPtr(status);
                    if (sdp != NULL && *sdp != NULL)
                        st = PyDict_GetItem(*sdp, s_state);
                }
                if (!st) {
                    take_slow = 1;
                } else if (guard_ok) {
                    long long stv = PyLong_AsLongLong(st);
                    if (stv == -1 && PyErr_Occurred()) {
                        PyErr_Clear();
                        take_slow = 1;
                    } else {
                        take_slow = stv >= guard_ll;
                    }
                } else {
                    int ge = PyObject_RichCompareBool(st, guard_state,
                                                      Py_GE);
                    if (ge < 0)
                        goto fail;
                    take_slow = ge;
                }
            }
        }
        if (take_slow) {
            PyObject *idx = PyLong_FromSsize_t(i);
            int r = idx ? PyList_Append(slow, idx) : -1;
            Py_XDECREF(idx);
            if (r < 0)
                goto fail;
            if (contiguous) {
                contiguous = 0;
                for (Py_ssize_t j = 0; j < n_contig; j++) {
                    PyObject *jo = PyLong_FromSsize_t(j);
                    int jr = jo ? PyList_Append(accepted, jo) : -1;
                    Py_XDECREF(jo);
                    if (jr < 0)
                        goto fail;
                }
            }
            continue;
        }
        if (contiguous) {
            n_contig++;
        } else {
            PyObject *idx = PyLong_FromSsize_t(i);
            int r = idx ? PyList_Append(accepted, idx) : -1;
            Py_XDECREF(idx);
            if (r < 0)
                goto fail;
        }
    }
    {
        PyObject *out;
        if (contiguous) {
            PyObject *rng = PyObject_CallFunction(
                (PyObject *)&PyRange_Type, "n", n_contig);
            if (!rng)
                goto fail;
            out = Py_BuildValue("(OO)", rng, slow);
            Py_DECREF(rng);
        } else {
            out = Py_BuildValue("(OO)", accepted, slow);
        }
        Py_DECREF(accepted);
        Py_DECREF(slow);
        return out;
    }
fail:
    Py_XDECREF(accepted);
    Py_XDECREF(slow);
    return NULL;
}

/* block_apply(old_tasks, node_ids, accepted, overlay, by_node, ts,
 *             state, message, base_seq) -> end_seq
 *
 * Write phase of the proposer block path, run inside the consensus
 * apply callback: install (node_id, version, ts, state, message)
 * overlay entries and maintain the by_node index for every accepted
 * index, versions running base_seq+1.. in accepted order.  Mirrors the
 * accept branch of block_commit exactly. */
static PyObject *
block_apply(PyObject *self, PyObject *args)
{
    PyObject *old_tasks, *node_ids, *accepted;
    PyObject *overlay, *by_node, *ts, *state, *message;
    long long seq;
    if (!PyArg_ParseTuple(args, "O!O!OO!O!OOOL", &PyList_Type, &old_tasks,
                          &PyList_Type, &node_ids, &accepted,
                          &PyDict_Type, &overlay, &PyDict_Type, &by_node,
                          &ts, &state, &message, &seq))
        return NULL;
    PyObject *fast = PySequence_Fast(accepted, "accepted must be iterable");
    if (!fast)
        return NULL;
    Py_ssize_t k = PySequence_Fast_GET_SIZE(fast);
    Py_ssize_t n = PyList_GET_SIZE(old_tasks);
    PyObject *run_nid = NULL;
    PyObject *run_set = NULL;
    for (Py_ssize_t j = 0; j < k; j++) {
        PyObject *io = PySequence_Fast_GET_ITEM(fast, j);
        Py_ssize_t i = PyLong_AsSsize_t(io);
        if (i < 0 || i >= n) {
            if (PyErr_Occurred())
                goto fail;
            PyErr_SetString(PyExc_IndexError, "accepted index out of range");
            goto fail;
        }
        PyObject *old = PyList_GET_ITEM(old_tasks, i);
        PyObject **dp = _PyObject_GetDictPtr(old);
        PyObject *d = (dp != NULL && *dp != NULL) ? *dp : NULL;
        PyObject *tid = d ? PyDict_GetItem(d, s_id) : NULL;
        if (!tid) {
            PyErr_SetString(PyExc_ValueError, "task without id");
            goto fail;
        }
        seq++;
        PyObject *nid = PyList_GET_ITEM(node_ids, i);
        PyObject *ver = PyLong_FromLongLong(seq);
        if (!ver)
            goto fail;
        PyObject *entry = PyTuple_Pack(5, nid, ver, ts, state, message);
        Py_DECREF(ver);
        if (!entry || PyDict_SetItem(overlay, tid, entry) < 0) {
            Py_XDECREF(entry);
            goto fail;
        }
        Py_DECREF(entry);
        PyObject *onid = PyDict_GetItem(d, s_node_id);
        if (onid && PyObject_IsTrue(onid) && onid != nid) {
            int eq = dict_vals_equal(onid, nid);
            if (eq < 0)
                goto fail;
            if (!eq) {
                PyObject *os = PyDict_GetItem(by_node, onid);
                if (os && bucket_discard(os, tid) < 0)
                    goto fail;
            }
        }
        if (nid != run_nid) {
            run_nid = nid;
            run_set = NULL;
            if (PyObject_IsTrue(nid)) {
                run_set = PyDict_GetItem(by_node, nid);
                if (!run_set) {
                    PyObject *fresh = PyDict_New();
                    if (!fresh ||
                        PyDict_SetItem(by_node, nid, fresh) < 0) {
                        Py_XDECREF(fresh);
                        goto fail;
                    }
                    Py_DECREF(fresh);
                    run_set = PyDict_GetItem(by_node, nid);
                }
            }
        }
        if (run_set && PyDict_SetItem(run_set, tid, Py_None) < 0)
            goto fail;
    }
    Py_DECREF(fast);
    return PyLong_FromLongLong(seq);
fail:
    Py_DECREF(fast);
    return NULL;
}

/* ------------------------------------------------------------------ *
 * Columnar commit plane (ISSUE 13): binary block entries, follower    *
 * apply, and native watch fan-out.                                   *
 * ------------------------------------------------------------------ */

/* Little-endian readers over an untrusted byte buffer.  The container
 * targets x86_64; plain memcpy reads are both alignment-safe and
 * little-endian there (serde.block_to_bytes writes `<` struct codes). */
static uint32_t
rd_u32(const char *p)
{
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

static int64_t
rd_i64(const char *p)
{
    int64_t v;
    memcpy(&v, p, 8);
    return v;
}

static int32_t
rd_i32(const char *p)
{
    int32_t v;
    memcpy(&v, p, 4);
    return v;
}

static double
rd_f64(const char *p)
{
    double v;
    memcpy(&v, p, 8);
    return v;
}

/* Split a NUL-joined blob of `count` strings into a fresh tuple.  The
 * offset scan runs with the GIL released (pure byte work); the string
 * objects are built afterwards under the GIL. */
static PyObject *
split_nul_blob(const char *blob, Py_ssize_t len, Py_ssize_t count)
{
    if (count == 0) {
        if (len != 0) {
            PyErr_SetString(PyExc_ValueError, "block: dangling blob");
            return NULL;
        }
        return PyTuple_New(0);
    }
    Py_ssize_t *offs = PyMem_Malloc((count + 1) * sizeof(Py_ssize_t));
    if (!offs)
        return PyErr_NoMemory();
    Py_ssize_t found = 0;
    int ok = 1;
    Py_BEGIN_ALLOW_THREADS
    offs[0] = 0;
    found = 1;
    const char *p = blob;
    const char *end = blob + len;
    for (; p < end && found < count;) {
        const char *nul = memchr(p, '\0', end - p);
        if (nul == NULL)
            break;
        offs[found++] = (nul - blob) + 1;
        p = nul + 1;
    }
    offs[count] = len + 1;  /* sentinel: final string ends at len */
    if (found != count)
        ok = 0;
    else if (memchr(p, '\0', end - p) != NULL)
        /* extra separators beyond count-1: the Python oracle's split()
         * would yield more strings and raise — match it exactly */
        ok = 0;
    Py_END_ALLOW_THREADS
    if (!ok) {
        PyMem_Free(offs);
        PyErr_SetString(PyExc_ValueError, "block: string count mismatch");
        return NULL;
    }
    PyObject *out = PyTuple_New(count);
    if (!out) {
        PyMem_Free(offs);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < count; i++) {
        Py_ssize_t start = offs[i];
        Py_ssize_t stop = offs[i + 1] - 1;  /* drop the separator */
        PyObject *s = PyUnicode_DecodeUTF8(blob + start, stop - start,
                                           "strict");
        if (!s) {
            PyMem_Free(offs);
            Py_DECREF(out);
            return NULL;
        }
        PyTuple_SET_ITEM(out, i, s);
    }
    PyMem_Free(offs);
    return out;
}

/* block_decode(data: bytes, taskblock_cls) -> TaskBlockAction
 *
 * Parse the compact binary task-block raft entry (layout documented in
 * state/serde.py block_to_bytes, magic "SKB1") straight into a
 * TaskBlockAction — no JSON dicts, no per-item Python loop on the
 * caller's side.  The differential oracle is serde.block_from_bytes. */
static PyObject *
block_decode(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    PyObject *cls;
    if (!PyArg_ParseTuple(args, "y*O", &buf, &cls))
        return NULL;
    const char *p = buf.buf;
    Py_ssize_t len = buf.len;
    PyObject *ids = NULL, *msg = NULL, *nids = NULL, *out = NULL;
    PyObject *runs = NULL;
#define NEED(nbytes)                                                          \
    do {                                                                      \
        if (len - off < (Py_ssize_t)(nbytes)) {                               \
            PyErr_SetString(PyExc_ValueError, "block: truncated entry");      \
            goto done;                                                        \
        }                                                                     \
    } while (0)
    Py_ssize_t off = 0;
    NEED(32);
    if (memcmp(p, "SKB1", 4) != 0) {
        PyErr_SetString(PyExc_ValueError, "block: bad magic");
        goto done;
    }
    uint32_t n = rd_u32(p + 4);
    int64_t base = rd_i64(p + 8);
    int32_t state = rd_i32(p + 16);
    double ts = rd_f64(p + 20);
    uint32_t msg_len = rd_u32(p + 28);
    off = 32;
    NEED(msg_len);
    msg = PyUnicode_DecodeUTF8(p + off, msg_len, "strict");
    if (!msg)
        goto done;
    off += msg_len;
    NEED(4);
    uint32_t ids_len = rd_u32(p + off);
    off += 4;
    NEED(ids_len);
    ids = split_nul_blob(p + off, ids_len, n);
    if (!ids)
        goto done;
    off += ids_len;
    NEED(4);
    uint32_t n_runs = rd_u32(p + off);
    off += 4;
    NEED((size_t)n_runs * 4 + 4);
    const char *counts = p + off;
    off += (Py_ssize_t)n_runs * 4;
    uint32_t nid_len = rd_u32(p + off);
    off += 4;
    NEED(nid_len);
    runs = split_nul_blob(p + off, nid_len, n_runs);
    if (!runs)
        goto done;
    off += nid_len;
    if (off != len) {
        PyErr_SetString(PyExc_ValueError, "block: trailing bytes");
        goto done;
    }
    /* expand the node-id runs into the full n-length column */
    nids = PyTuple_New(n);
    if (!nids)
        goto done;
    {
        Py_ssize_t k = 0;
        for (uint32_t r = 0; r < n_runs; r++) {
            uint32_t cnt = rd_u32(counts + (size_t)r * 4);
            PyObject *nid = PyTuple_GET_ITEM(runs, r);
            for (uint32_t c = 0; c < cnt; c++) {
                if (k >= (Py_ssize_t)n) {
                    PyErr_SetString(PyExc_ValueError,
                                    "block: run counts exceed n");
                    goto done;
                }
                Py_INCREF(nid);
                PyTuple_SET_ITEM(nids, k++, nid);
            }
        }
        if (k != (Py_ssize_t)n) {
            PyErr_SetString(PyExc_ValueError,
                            "block: run counts short of n");
            goto done;
        }
    }
    {
        PyObject *base_obj = PyLong_FromLongLong(base);
        PyObject *state_obj = PyLong_FromLong(state);
        PyObject *ts_obj = PyFloat_FromDouble(ts);
        if (base_obj && state_obj && ts_obj)
            out = PyObject_CallFunctionObjArgs(
                cls, s_task_block, ids, nids, base_obj, state_obj, msg,
                ts_obj, NULL);
        Py_XDECREF(base_obj);
        Py_XDECREF(state_obj);
        Py_XDECREF(ts_obj);
    }
done:
    Py_XDECREF(ids);
    Py_XDECREF(msg);
    Py_XDECREF(nids);
    Py_XDECREF(runs);
    PyBuffer_Release(&buf);
    return out;
#undef NEED
}

/* block_apply_follower(ids, node_ids, objects, overlay, by_node, ts,
 *                      state, message, base_version) -> olds list | None
 *
 * Follower-side fast path of MemoryStore._apply_task_block_locked: when
 * EVERY id resolves to a stored object and none has a pending overlay
 * entry (the healthy-log case), install the overlay tuples and maintain
 * the by_node index in ONE batched pass per chunk (run-cached bucket,
 * insertion order preserved) and return the pre-assignment stored tasks
 * in block order.  Any miss returns None untouched — the Python loop
 * then runs the full per-item semantics (materialization, skipped-id
 * contiguity handling). */
static PyObject *
block_apply_follower(PyObject *self, PyObject *args)
{
    PyObject *ids, *node_ids, *objects, *overlay, *by_node;
    PyObject *ts, *state, *message;
    long long base;
    if (!PyArg_ParseTuple(args, "OOO!O!O!OOOL", &ids, &node_ids,
                          &PyDict_Type, &objects, &PyDict_Type, &overlay,
                          &PyDict_Type, &by_node, &ts, &state, &message,
                          &base))
        return NULL;
    PyObject *ids_f = PySequence_Fast(ids, "ids must be a sequence");
    if (!ids_f)
        return NULL;
    PyObject *nids_f = PySequence_Fast(node_ids,
                                       "node_ids must be a sequence");
    if (!nids_f) {
        Py_DECREF(ids_f);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(ids_f);
    PyObject *olds = NULL;
    if (PySequence_Fast_GET_SIZE(nids_f) != n) {
        PyErr_SetString(PyExc_ValueError, "ids/node_ids mismatch");
        goto fail;
    }
    /* screen: every id stored, none overlaid — else the Python slow
     * path owns the whole block (mixed fast/slow would reorder the
     * version assignment the changelog contract pins) */
    olds = PyList_New(n);
    if (!olds)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *tid = PySequence_Fast_GET_ITEM(ids_f, i);
        PyObject *cur = PyDict_GetItemWithError(objects, tid);
        if (!cur) {
            if (PyErr_Occurred())
                goto fail;
            Py_DECREF(olds);
            Py_DECREF(ids_f);
            Py_DECREF(nids_f);
            Py_RETURN_NONE;
        }
        int in_overlay = PyDict_Contains(overlay, tid);
        if (in_overlay < 0)
            goto fail;
        if (in_overlay) {
            Py_DECREF(olds);
            Py_DECREF(ids_f);
            Py_DECREF(nids_f);
            Py_RETURN_NONE;
        }
        Py_INCREF(cur);
        PyList_SET_ITEM(olds, i, cur);
    }
    /* apply: overlay entries + one batched by_node pass (run-cached) */
    {
        PyObject *run_nid = NULL;
        PyObject *run_set = NULL;
        long long seq = base;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *tid = PySequence_Fast_GET_ITEM(ids_f, i);
            PyObject *nid = PySequence_Fast_GET_ITEM(nids_f, i);
            seq++;
            PyObject *ver = PyLong_FromLongLong(seq);
            if (!ver)
                goto fail;
            PyObject *entry = PyTuple_Pack(5, nid, ver, ts, state,
                                           message);
            Py_DECREF(ver);
            if (!entry || PyDict_SetItem(overlay, tid, entry) < 0) {
                Py_XDECREF(entry);
                goto fail;
            }
            Py_DECREF(entry);
            PyObject *cur = PyList_GET_ITEM(olds, i);
            PyObject **cdp = _PyObject_GetDictPtr(cur);
            PyObject *onid = (cdp && *cdp)
                ? PyDict_GetItem(*cdp, s_node_id) : NULL;
            if (onid && PyObject_IsTrue(onid) && onid != nid) {
                int eq = dict_vals_equal(onid, nid);
                if (eq < 0)
                    goto fail;
                if (!eq) {
                    PyObject *os = PyDict_GetItem(by_node, onid);
                    if (os && bucket_discard(os, tid) < 0)
                        goto fail;
                }
            }
            if (nid != run_nid) {
                run_nid = nid;
                run_set = NULL;
                if (PyObject_IsTrue(nid)) {
                    run_set = PyDict_GetItem(by_node, nid);
                    if (!run_set) {
                        PyObject *fresh = PyDict_New();
                        if (!fresh ||
                            PyDict_SetItem(by_node, nid, fresh) < 0) {
                            Py_XDECREF(fresh);
                            goto fail;
                        }
                        Py_DECREF(fresh);
                        run_set = PyDict_GetItem(by_node, nid);
                    }
                }
            }
            if (run_set && PyDict_SetItem(run_set, tid, Py_None) < 0)
                goto fail;
        }
    }
    Py_DECREF(ids_f);
    Py_DECREF(nids_f);
    return olds;
fail:
    Py_XDECREF(olds);
    Py_DECREF(ids_f);
    Py_DECREF(nids_f);
    return NULL;
}

/* fanout_expand(olds, node_ids, base_version, ts, status, event_cls)
 *   -> list[Event]
 *
 * Synthesize the per-task update Events of one EventTaskBlock in a
 * single native pass: clone each pre-assignment task (Task.copy
 * semantics — shared spec, isolated meta/status/list containers), stamp
 * node_id / the shared assigned status / version base+1+i /
 * updated_at=ts, and wrap it in event_cls("update", new, old).  The
 * pure-Python oracle is EventTaskBlock.expand_events; `status` is the
 * TaskStatus every materialized task shares (same value the oracle
 * builds per task — plan_apply's shared-status precedent). */
static PyObject *
fanout_expand(PyObject *self, PyObject *args)
{
    PyObject *olds, *node_ids, *ts, *status, *event_cls;
    long long base;
    if (!PyArg_ParseTuple(args, "OOLOOO", &olds, &node_ids, &base, &ts,
                          &status, &event_cls))
        return NULL;
    PyObject *olds_f = PySequence_Fast(olds, "olds must be a sequence");
    if (!olds_f)
        return NULL;
    PyObject *nids_f = PySequence_Fast(node_ids,
                                       "node_ids must be a sequence");
    if (!nids_f) {
        Py_DECREF(olds_f);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(olds_f);
    PyObject *out = NULL;
    if (PySequence_Fast_GET_SIZE(nids_f) != n) {
        PyErr_SetString(PyExc_ValueError, "olds/node_ids mismatch");
        goto fail;
    }
    out = PyList_New(n);
    if (!out)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *old = PySequence_Fast_GET_ITEM(olds_f, i);
        PyObject *nid = PySequence_Fast_GET_ITEM(nids_f, i);
        PyObject *d = NULL;
        PyObject *nt = shallow_clone(old, &d);
        if (!nt)
            goto fail;
        PyObject *meta = PyDict_GetItem(d, s_meta);
        PyObject *nm = NULL;
        if (meta) {
            nm = clone_meta(meta);
            if (!nm || PyDict_SetItem(d, s_meta, nm) < 0)
                goto item_fail;
        }
        if (PyDict_SetItem(d, s_status, status) < 0 ||
            PyDict_SetItem(d, s_node_id, nid) < 0 ||
            copy_list_field(d, s_networks) < 0 ||
            copy_list_field(d, s_volumes) < 0 ||
            copy_list_field(d, s_agr) < 0)
            goto item_fail;
        if (nm) {
            PyObject *nv = PyObject_GetAttr(nm, s_version);
            PyObject *ver = PyLong_FromLongLong(base + 1 + i);
            int err = !nv || !ver ||
                      PyObject_SetAttr(nv, s_index, ver) < 0 ||
                      PyObject_SetAttr(nm, s_updated_at, ts) < 0;
            Py_XDECREF(nv);
            Py_XDECREF(ver);
            if (err)
                goto item_fail;
        }
        {
            PyObject *ev = PyObject_CallFunctionObjArgs(
                event_cls, s_update, nt, old, NULL);
            if (!ev)
                goto item_fail;
            PyList_SET_ITEM(out, i, ev);
        }
        Py_XDECREF(nm);
        Py_DECREF(d);
        Py_DECREF(nt);
        continue;
    item_fail:
        Py_XDECREF(nm);
        Py_XDECREF(d);
        Py_DECREF(nt);
        goto fail;
    }
    Py_DECREF(olds_f);
    Py_DECREF(nids_f);
    return out;
fail:
    Py_XDECREF(out);
    Py_DECREF(olds_f);
    Py_DECREF(nids_f);
    return NULL;
}

/* fanout_filter(events, predicate) -> list
 *
 * Per-subscriber predicate pre-filter over an expanded event list: one
 * tight native loop instead of a Python-level comprehension per
 * subscriber.  A predicate exception drops only the offending event —
 * the same granularity as Subscription._expand's Python fallback. */
static PyObject *
fanout_filter(PyObject *self, PyObject *args)
{
    PyObject *events, *pred;
    if (!PyArg_ParseTuple(args, "OO", &events, &pred))
        return NULL;
    PyObject *events_f = PySequence_Fast(events,
                                         "events must be a sequence");
    if (!events_f)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(events_f);
    PyObject *out = PyList_New(0);
    if (!out) {
        Py_DECREF(events_f);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PySequence_Fast_GET_ITEM(events_f, i);
        PyObject *r = PyObject_CallOneArg(pred, ev);
        if (!r) {
            /* drop the offending event only — but, like the oracle's
             * `except Exception`, let KeyboardInterrupt/SystemExit/
             * MemoryError unwind instead of eating them */
            if (!PyErr_ExceptionMatches(PyExc_Exception)) {
                Py_DECREF(out);
                Py_DECREF(events_f);
                return NULL;
            }
            PyErr_Clear();
            continue;
        }
        int keep = PyObject_IsTrue(r);
        Py_DECREF(r);
        if (keep < 0) {
            if (!PyErr_ExceptionMatches(PyExc_Exception)) {
                Py_DECREF(out);
                Py_DECREF(events_f);
                return NULL;
            }
            PyErr_Clear();   /* truthiness raised: drop the event */
            keep = 0;
        }
        if (keep && PyList_Append(out, ev) < 0) {
            Py_DECREF(out);
            Py_DECREF(events_f);
            return NULL;
        }
    }
    Py_DECREF(events_f);
    return out;
}

/* per_node_group(olds, node_ids, base_version) -> dict
 *
 * node_id -> [(old_task, version), ...] grouping of one block (the
 * dispatcher sessions' O(1) membership probe), built in one native
 * pass with a run-cached bucket.  Oracle: EventTaskBlock.per_node. */
static PyObject *
per_node_group(PyObject *self, PyObject *args)
{
    PyObject *olds, *node_ids;
    long long base;
    if (!PyArg_ParseTuple(args, "OOL", &olds, &node_ids, &base))
        return NULL;
    PyObject *olds_f = PySequence_Fast(olds, "olds must be a sequence");
    if (!olds_f)
        return NULL;
    PyObject *nids_f = PySequence_Fast(node_ids,
                                       "node_ids must be a sequence");
    if (!nids_f) {
        Py_DECREF(olds_f);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(olds_f);
    PyObject *out = NULL;
    if (PySequence_Fast_GET_SIZE(nids_f) != n) {
        PyErr_SetString(PyExc_ValueError, "olds/node_ids mismatch");
        goto fail;
    }
    out = PyDict_New();
    if (!out)
        goto fail;
    {
        PyObject *run_nid = NULL;
        PyObject *run_lst = NULL;   /* borrowed */
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *nid = PySequence_Fast_GET_ITEM(nids_f, i);
            if (nid != run_nid || run_lst == NULL) {
                run_nid = nid;
                run_lst = PyDict_GetItemWithError(out, nid);
                if (!run_lst) {
                    if (PyErr_Occurred())
                        goto fail;
                    PyObject *fresh = PyList_New(0);
                    if (!fresh ||
                        PyDict_SetItem(out, nid, fresh) < 0) {
                        Py_XDECREF(fresh);
                        goto fail;
                    }
                    Py_DECREF(fresh);
                    run_lst = PyDict_GetItem(out, nid);
                }
            }
            PyObject *ver = PyLong_FromLongLong(base + 1 + i);
            if (!ver)
                goto fail;
            PyObject *pair = PyTuple_Pack(
                2, PySequence_Fast_GET_ITEM(olds_f, i), ver);
            Py_DECREF(ver);
            if (!pair || PyList_Append(run_lst, pair) < 0) {
                Py_XDECREF(pair);
                goto fail;
            }
            Py_DECREF(pair);
        }
    }
    Py_DECREF(olds_f);
    Py_DECREF(nids_f);
    return out;
fail:
    Py_XDECREF(out);
    Py_DECREF(olds_f);
    Py_DECREF(nids_f);
    return NULL;
}

static PyMethodDef methods[] = {
    {"plan_apply", plan_apply, METH_VARARGS,
     "Clone and register planner decisions."},
    {"block_commit", block_commit, METH_VARARGS,
     "Columnar task-block commit fast path (overlay + by_node index)."},
    {"block_stage", block_stage, METH_VARARGS,
     "Columnar staging of planned placements for the block-commit path."},
    {"block_validate", block_validate, METH_VARARGS,
     "Read-only screen for the proposer block-commit path."},
    {"block_apply", block_apply, METH_VARARGS,
     "Apply accepted block items (overlay + by_node), proposer path."},
    {"commit_prepare", commit_prepare, METH_VARARGS,
     "Validate, version-check, and stamp one commit chunk."},
    {"commit_apply", commit_apply, METH_VARARGS,
     "Install stamped tasks into the store table and indexes."},
    {"block_decode", block_decode, METH_VARARGS,
     "Parse a binary columnar task-block raft entry (GIL-released scan)."},
    {"block_apply_follower", block_apply_follower, METH_VARARGS,
     "Follower-side block apply: overlay + batched by_node index pass."},
    {"fanout_expand", fanout_expand, METH_VARARGS,
     "Synthesize the per-task watch Events of one EventTaskBlock."},
    {"fanout_filter", fanout_filter, METH_VARARGS,
     "Per-subscriber predicate pre-filter over an expanded event list."},
    {"per_node_group", per_node_group, METH_VARARGS,
     "node_id -> [(old, version)] grouping of one block."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_hotpath",
                                       NULL, -1, methods};

PyMODINIT_FUNC
PyInit__hotpath(void)
{
#define INTERN(var, str)                                                      \
    do {                                                                      \
        var = PyUnicode_InternFromString(str);                                \
        if (!var)                                                             \
            return NULL;                                                      \
    } while (0)
    INTERN(s_dict, "__dict__");
    INTERN(s_meta, "meta");
    INTERN(s_version, "version");
    INTERN(s_index, "index");
    INTERN(s_created_at, "created_at");
    INTERN(s_updated_at, "updated_at");
    INTERN(s_status, "status");
    INTERN(s_node_id, "node_id");
    INTERN(s_networks, "networks");
    INTERN(s_volumes, "volumes");
    INTERN(s_agr, "assigned_generic_resources");
    INTERN(s_id, "id");
    INTERN(s_state, "state");
    INTERN(s_message, "message");
    INTERN(s_err, "err");
    INTERN(s_service_id, "service_id");
    INTERN(s_slot, "slot");
    INTERN(s_old, "old");
    INTERN(s_new, "new");
    INTERN(s_update, "update");
    INTERN(s_task_block, "task_block");
#undef INTERN
    empty_tuple = PyTuple_New(0);
    if (!empty_tuple)
        return NULL;
    return PyModule_Create(&moduledef);
}
