from .client import (
    RemoteControlClient, RemoteDispatcherClient, issue_certificate,
    join_raft, renew_certificate,
)
from .raft_transport import TCPRaftTransport
from .server import ManagerServer

__all__ = ["ManagerServer", "RemoteControlClient",
           "RemoteDispatcherClient", "TCPRaftTransport",
           "issue_certificate", "join_raft", "renew_certificate"]
