"""Network clients: the dispatcher surface for remote agents and the
control surface for remote swarmctl.

``RemoteDispatcherClient`` implements exactly the client surface
``agent.Agent`` consumes (register / heartbeat / open_assignments /
update_task_status), so an agent runs against a remote manager unchanged.
``RemoteControlClient`` mirrors ControlAPI methods for the CLI.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..manager.controlapi import (
    AlreadyExists, APIError, FailedPrecondition, InvalidArgument, NotFound,
)
from ..models.objects import STORE_OBJECT_TYPES
from ..models.types import TaskStatus
from ..security.ca import (
    Certificate, InvalidToken, SecurityError, generate_key_pem, make_csr,
)
from ..security.tls import client_context, require_server_role
from ..state import serde
from ..state.watch import Closed
from .wire import recv_frame, send_frame

_COLLECTIONS = {t.collection: t for t in STORE_OBJECT_TYPES}

class NotLeader(Exception):
    """The contacted manager is not the leader (server code
    'not_leader'); callers should rotate to another manager."""


class SessionInvalid(Exception):
    """The dispatcher no longer recognizes this session (server codes
    'session_invalid' / 'node_not_registered'): the link is healthy but
    the session is gone — re-register, preferably with a DIFFERENT
    manager (the old one may be mid-teardown)."""

    code = "session_invalid"


_ERROR_TYPES = {
    "not_leader": NotLeader,
    "invalid_argument": InvalidArgument,
    "not_found": NotFound,
    "already_exists": AlreadyExists,
    "failed_precondition": FailedPrecondition,
    "unauthenticated": PermissionError,
    "session_invalid": SessionInvalid,
    "node_not_registered": SessionInvalid,
}


class RemoteError(Exception):
    pass


def _obj_in(data):
    if data is None:
        return None
    cls = _COLLECTIONS[data["collection"]]
    return serde.from_dict(cls, data["obj"])


class _Connection:
    """One mTLS link to a manager.  With ``tls`` (default) the client
    presents its certificate in the handshake and verifies the server
    chains to the cluster root AND carries the manager role; ``tls=False``
    falls back to plaintext hello-frame attestation (debug knob);
    ``insecure=True`` skips server verification for the join bootstrap."""

    def __init__(self, addr: Tuple[str, int],
                 certificate: Optional[Certificate],
                 tls: bool = True, insecure: bool = False):
        self.addr = addr
        self.certificate = certificate
        self.tls = tls
        self.insecure = insecure
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()
        self._next_id = 0

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=10)
        cert_data = None
        if self.tls:
            identity = (self.certificate
                        if (self.certificate
                            and self.certificate.key_pem
                            and self.certificate.cert_pem) else None)
            ctx = client_context(
                identity,
                ca_cert_pem=(self.certificate.ca_cert_pem
                             if self.certificate else b""),
                insecure=self.insecure)
            try:
                sock = ctx.wrap_socket(sock)
                if not self.insecure:
                    require_server_role(sock, "swarm-manager")
            except SecurityError:
                sock.close()
                raise
            except Exception as e:
                sock.close()
                raise PermissionError(f"TLS handshake failed: {e}")
        elif self.certificate:
            cert_data = self.certificate.to_bytes().decode()
        send_frame(sock, {"id": 0, "method": "hello",
                          "params": {"certificate": cert_data}})
        resp = recv_frame(sock)
        if resp.get("error"):
            sock.close()
            raise _ERROR_TYPES.get(resp.get("code"), RemoteError)(
                resp["error"])
        # only after a successful hello: streams may then block in recv
        # for arbitrarily long idle periods (the 10s timeout still bounds
        # the connect + handshake against half-open servers)
        sock.settimeout(None)
        return sock

    def call(self, method: str, params: Dict[str, Any]) -> Any:
        with self._mu:
            if self._sock is None:
                self._sock = self._connect()
            self._next_id += 1
            rid = self._next_id
            try:
                send_frame(self._sock, {"id": rid, "method": method,
                                        "params": params})
                resp = recv_frame(self._sock)
            except (ConnectionError, OSError):
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                raise
            if resp.get("error"):
                raise _ERROR_TYPES.get(resp.get("code"), RemoteError)(
                    resp["error"])
            return resp.get("result")

    def close(self) -> None:
        with self._mu:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


def issue_certificate(addr: Tuple[str, int], node_id: str,
                      token: str, tls: bool = True) -> Certificate:
    """Join: obtain a certificate with a join token (no cert needed).

    Bootstrap has no trust root yet, so the root fetch runs over an
    unverified connection and the downloaded root CA cert is checked
    against the digest embedded in the join token.  The secret token +
    CSR are then sent over a NEW connection with that root pinned — the
    digest check validates bytes, not the channel, so sending the token
    on the unverified link would hand it to an active MITM (reference:
    ca.DownloadRootCA then a verified NodeCA connection; the private key
    is generated locally and never travels)."""
    boot = _Connection(addr, None, tls=tls, insecure=True)
    try:
        root = boot.call("fetch_root_ca", {})
    finally:
        boot.close()
    ca_cert_pem = root["ca_cert"].encode()
    parts = token.split("-")
    if len(parts) != 4:
        raise InvalidToken("invalid join token")
    from ..security.ca import cert_digest
    if cert_digest(ca_cert_pem) != parts[2]:
        raise InvalidToken(
            "downloaded root CA does not match the join token digest")
    key_pem = generate_key_pem()
    conn = _Connection(addr, Certificate(cert_pem=b"", key_pem=b"",
                                         ca_cert_pem=ca_cert_pem),
                       tls=tls)
    try:
        resp = conn.call("issue_certificate", {
            "node_id": node_id, "token": token,
            "csr": make_csr(node_id, key_pem).decode()})
        return Certificate(cert_pem=resp["cert"].encode(),
                           key_pem=key_pem, ca_cert_pem=ca_cert_pem)
    finally:
        conn.close()


def renew_certificate(addr: Tuple[str, int],
                      certificate: Certificate,
                      tls: bool = True) -> Certificate:
    """Cert-gated renewal over the wire: fresh local key + CSR, same
    identity/role (reference: ca/renewer.go RequestAndSaveNewCertificates)."""
    conn = _Connection(addr, certificate, tls=tls)
    try:
        key_pem = generate_key_pem()
        resp = conn.call("renew_certificate", {
            "csr": make_csr(certificate.node_id, key_pem).decode()})
        return Certificate(cert_pem=resp["cert"].encode(),
                           key_pem=key_pem,
                           ca_cert_pem=resp["ca_cert"].encode())
    finally:
        conn.close()


def join_raft(addr: Tuple[str, int], certificate: Certificate,
              node_id: str, raft_addr: Optional[Tuple[str, int]] = None,
              api_addr: Optional[Tuple[str, int]] = None
              ) -> Dict[str, Any]:
    """Manager join: ask the leader to add us to the raft group; returns
    the known peer transport addresses.  A follower answers with a
    redirect to the leader's API address, which we chase (bounded)."""
    for _ in range(3):
        conn = _Connection(addr, certificate)
        try:
            resp = conn.call("raft_join", {
                "node_id": node_id,
                "addr": list(raft_addr) if raft_addr else None,
                "api_addr": list(api_addr) if api_addr else None})
        finally:
            conn.close()
        if "redirect" in resp:
            addr = tuple(resp["redirect"])
            continue
        return resp
    raise RemoteError("raft join kept getting redirected")


class RemoteAssignmentStream:
    """Client half of the assignments stream: reads pushed frames on a
    dedicated connection; same get()/close() surface as the in-process
    AssignmentStream."""

    def __init__(self, conn_factory, node_id: str, session_id: str):
        self._sock = conn_factory()
        send_frame(self._sock, {"id": 1, "method": "open_assignments",
                                "params": {"node_id": node_id,
                                           "session_id": session_id}})
        resp = recv_frame(self._sock)
        if resp.get("error"):
            self._sock.close()
            raise RemoteError(resp["error"])
        self._buf: List[Any] = []
        self._cond = threading.Condition()
        self._closed = False
        self.error: Optional[Exception] = None
        self._thread = threading.Thread(target=self._reader,
                                        name="assignments-reader",
                                        daemon=True)
        self._thread.start()

    def _reader(self) -> None:
        from ..manager.dispatcher import AssignmentsMessage
        try:
            while True:
                frame = recv_frame(self._sock)
                if frame.get("push") == "closed":
                    raise ConnectionError(frame.get("error")
                                          or "stream closed by server")
                changes = [
                    (c["action"], c["kind"],
                     serde.from_dict(_COLLECTIONS[
                         "tasks" if c["kind"] == "task"
                         else c["kind"] + "s"], c["obj"]))
                    for c in frame["changes"]]
                msg = AssignmentsMessage(frame["type"], frame["applies_to"],
                                         frame["results_in"], changes)
                with self._cond:
                    self._buf.append(msg)
                    self._cond.notify()
        except Exception as e:
            with self._cond:
                self.error = e
                self._closed = True
                self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            if not self._buf and not self._closed:
                self._cond.wait(timeout)
            if self._buf:
                return self._buf.pop(0)
            if self._closed:
                raise Closed()
            raise TimeoutError()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self, error: Optional[Exception] = None) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteDispatcherClient:
    """The agent's client surface over TCP."""

    def __init__(self, addr: Tuple[str, int], certificate: Certificate):
        self.addr = addr
        self.certificate = certificate
        self._conn = _Connection(addr, certificate)

    def register(self, node_id: str, description=None):
        result = self._conn.call("register", {
            "node_id": node_id,
            "description": serde.to_dict(description)
            if description is not None else None})
        return result["session_id"], result["period"]

    def heartbeat(self, node_id: str, session_id: str) -> float:
        resp = self._conn.call("heartbeat", {"node_id": node_id,
                                             "session_id": session_id})
        if isinstance(resp, dict):
            # the server piggybacks the current manager list on heartbeats
            # (reference: session Message.Managers); stash it for the
            # failover layer to feed into its Remotes tracker
            self.last_managers = [tuple(a) for a in
                                  resp.get("managers", [])]
            # ...and the active root digest, so the renewer reacts to a
            # CA rotation without waiting for cert half-life
            self.last_ca_digest = resp.get("ca_digest", "")
            # ...and the node's store-reconciled role, so promotion/
            # demotion is noticed within one heartbeat period
            self.last_role = resp.get("role")
            # ...and the dataplane encryption keys (reference:
            # SessionMessage.NetworkBootstrapKeys); the agent hands them
            # to its executor when the rotation clock advances
            if "network_keys" in resp:
                self.last_network_keys = resp["network_keys"]
                self.last_key_clock = resp.get("key_clock", 0)
            return resp["period"]
        return resp

    def publish_logs(self, node_id: str, session_id: str,
                     messages) -> None:
        import base64 as _b64
        self._conn.call("publish_logs", {
            "node_id": node_id, "session_id": session_id,
            "messages": [dict(m, data=_b64.b64encode(
                m["data"]).decode("ascii")) for m in messages]})

    def update_task_status(self, node_id: str, session_id: str,
                           updates: List[Tuple[str, TaskStatus]]) -> None:
        self._conn.call("update_task_status", {
            "node_id": node_id, "session_id": session_id,
            "updates": [{"task_id": tid, "status": serde.to_dict(st)}
                        for tid, st in updates]})

    def update_volume_status(self, node_id: str, session_id: str,
                             updates) -> None:
        self._conn.call("update_volume_status", {
            "node_id": node_id, "session_id": session_id,
            "updates": [[vid, bool(unpub)] for vid, unpub in updates]})

    def open_assignments(self, node_id: str,
                         session_id: str) -> RemoteAssignmentStream:
        return RemoteAssignmentStream(
            lambda: self._conn._connect(), node_id, session_id)

    def reset_connection(self) -> None:
        """Next call re-handshakes with the current certificate."""
        # sync a reassigned identity into the connection: it captured
        # the Certificate object at construction time
        self._conn.certificate = self.certificate
        self._conn.close()

    def close(self) -> None:
        self._conn.close()


class RemoteControlClient:
    """ControlAPI surface over TCP (for remote swarmctl)."""

    def __init__(self, addr: Tuple[str, int], certificate: Certificate):
        self._conn = _Connection(addr, certificate)

    def _call(self, method, **params):
        return self._conn.call(f"control.{method}", params)

    def create_service(self, spec):
        return _obj_in(self._call("create_service",
                                  spec=serde.to_dict(spec)))

    def update_service(self, service_id, version, spec):
        return _obj_in(self._call("update_service", service_id=service_id,
                                  version=version,
                                  spec=serde.to_dict(spec)))

    def remove_service(self, service_id):
        self._call("remove_service", service_id=service_id)

    def get_service(self, service_id):
        return _obj_in(self._call("get_service", service_id=service_id))

    def list_services(self, name_prefix: str = ""):
        return [_obj_in(o) for o in self._call(
            "list_services", name_prefix=name_prefix)]

    def list_service_statuses(self, service_ids):
        return self._call("list_service_statuses",
                          service_ids=list(service_ids))

    def list_nodes(self):
        return [_obj_in(o) for o in self._call("list_nodes")]

    def update_node(self, node_id, version, spec):
        return _obj_in(self._call("update_node", node_id=node_id,
                                  version=version,
                                  spec=serde.to_dict(spec)))

    def remove_node(self, node_id, force=False):
        self._call("remove_node", node_id=node_id, force=force)

    def list_tasks(self, service_id: str = "", node_id: str = ""):
        return [_obj_in(o) for o in self._call(
            "list_tasks", service_id=service_id, node_id=node_id)]

    def remove_task(self, task_id: str):
        self._call("remove_task", task_id=task_id)

    def collect_logs(self, service_id: str, duration: float = 2.0,
                     tail: int = -1, since: float = 0.0,
                     follow: bool = True, streams=None):
        import base64 as _b64
        return [dict(m, data=_b64.b64decode(m["data"]))
                for m in self._call("collect_logs",
                                    service_id=service_id,
                                    duration=duration, tail=tail,
                                    since=since, follow=follow,
                                    streams=list(streams or []))]

    def create_secret(self, spec):
        return _obj_in(self._call("create_secret",
                                  spec=serde.to_dict(spec)))

    def get_secret(self, secret_id):
        return _obj_in(self._call("get_secret", secret_id=secret_id))

    def get_config(self, config_id):
        return _obj_in(self._call("get_config", config_id=config_id))

    def list_secrets(self):
        return [_obj_in(o) for o in self._call("list_secrets")]

    def remove_secret(self, secret_id):
        self._call("remove_secret", secret_id=secret_id)

    def create_config(self, spec):
        return _obj_in(self._call("create_config",
                                  spec=serde.to_dict(spec)))

    def list_configs(self):
        return [_obj_in(o) for o in self._call("list_configs")]

    def remove_config(self, config_id):
        self._call("remove_config", config_id=config_id)

    def create_network(self, spec):
        return _obj_in(self._call("create_network",
                                  spec=serde.to_dict(spec)))

    def list_networks(self):
        return [_obj_in(o) for o in self._call("list_networks")]

    def remove_network(self, network_id):
        self._call("remove_network", network_id=network_id)

    def create_volume(self, spec):
        return _obj_in(self._call("create_volume",
                                  spec=serde.to_dict(spec)))

    def update_volume(self, volume_id, version, spec):
        return _obj_in(self._call("update_volume", volume_id=volume_id,
                                  version=version,
                                  spec=serde.to_dict(spec)))

    def get_volume(self, volume_id):
        return _obj_in(self._call("get_volume", volume_id=volume_id))

    def list_volumes(self, name_prefix: str = ""):
        return [_obj_in(o) for o in self._call("list_volumes",
                                               name_prefix=name_prefix)]

    def remove_volume(self, volume_id, force=False):
        self._call("remove_volume", volume_id=volume_id, force=force)

    def create_extension(self, annotations, description=""):
        return _obj_in(self._call("create_extension",
                                  annotations=serde.to_dict(annotations),
                                  description=description))

    def list_extensions(self):
        return [_obj_in(o) for o in self._call("list_extensions")]

    def remove_extension(self, extension_id):
        self._call("remove_extension", extension_id=extension_id)

    def create_resource(self, annotations, kind, payload=b""):
        import base64 as _b64
        return _obj_in(self._call(
            "create_resource", annotations=serde.to_dict(annotations),
            kind=kind, payload=_b64.b64encode(payload).decode("ascii")))

    def list_resources(self, kind: str = ""):
        return [_obj_in(o) for o in self._call("list_resources",
                                               kind=kind)]

    def remove_resource(self, resource_id):
        self._call("remove_resource", resource_id=resource_id)

    def rotate_join_token(self, role):
        return self._call("rotate_join_token", role=int(role))

    def get_default_cluster(self):
        return _obj_in(self._call("get_default_cluster"))

    def list_clusters(self):
        return [_obj_in(o) for o in self._call("list_clusters")]

    def health(self, service: str = "") -> str:
        return self._conn.call("health", {"service": service})["status"]

    def rotate_ca(self):
        return self._call("rotate_ca")

    def set_autolock(self, enabled: bool):
        return self._call("set_autolock", enabled=enabled)

    def get_unlock_key(self):
        return self._call("get_unlock_key")

    def close(self) -> None:
        self._conn.close()
