"""TCP raft transport: manager↔manager consensus traffic over the network.

Reference: manager/state/raft/transport/ (per-peer gRPC streams with
ordered delivery, mTLS via ca/transport.go).  Each member listens on a
TCP port; sends go over one persistent, ordered connection per peer with
automatic reconnect.  Implements the same two-method surface as
transport.LocalNetwork, so RaftNode is transport-agnostic.

Security: with ``tls_identity`` (a manager Certificate) every link is
mutual TLS — both sides must present manager-role certs chaining to the
cluster root.  The ``auth_key`` HMAC-hello is the plaintext fallback knob.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import queue
import socket
import socketserver
import ssl
import threading
from typing import Callable, Dict, Optional, Tuple

from ..security.ca import SecurityError
from ..state import serde
from ..state.raft.core import Message
from .wire import recv_frame, send_frame

log = logging.getLogger("net.raft")


class TCPRaftTransport:
    def __init__(self, node_id: str, host: str = "127.0.0.1",
                 port: int = 0, auth_key: Optional[bytes] = None,
                 tls_identity=None):
        """``tls_identity``: this manager's Certificate (with key + trust
        root) — enables mutual TLS with CERT_REQUIRED and manager-role
        authorization both ways.  ``auth_key``: shared-secret HMAC hello,
        the plaintext fallback — consensus traffic is manager-only either
        way."""
        self.node_id = node_id
        self.auth_key = auth_key
        self.tls_identity = None
        self._server_ctx = None
        self._client_ctx = None
        if tls_identity is not None:
            self.set_identity(tls_identity)
        self._handler: Optional[Callable[[Message], None]] = None
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._send_queues: Dict[str, "queue.Queue"] = {}
        self._senders: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        # live inbound connections: must be force-closed on shutdown or a
        # peer's established socket keeps feeding a DEAD transport — the
        # peer never redials, and a restarted member on the same port
        # never hears from it (no elections ever complete)
        self._conns: set = set()
        self._conns_mu = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    ctx = outer._server_ctx
                    if ctx is not None:
                        try:
                            sock = ctx.wrap_socket(sock, server_side=True)
                            outer._authorize_peer(sock)
                        except Exception as e:
                            log.warning("rejected raft peer: %s", e)
                            return
                    elif outer.auth_key is not None:
                        hello = recv_frame(sock)
                        sig = hello.get("hello", "")
                        if not hmac.compare_digest(sig, outer._hello_sig()):
                            log.warning("rejected unauthenticated raft peer")
                            return
                    with outer._conns_mu:
                        outer._conns.add(sock)
                    while not outer._stop.is_set():
                        frame = recv_frame(sock)
                        handler = outer._handler
                        if handler is None:
                            break   # unregistered: force the peer to redial
                        handler(serde.from_dict(Message, frame))
                except (ConnectionError, OSError, ValueError):
                    pass
                finally:
                    with outer._conns_mu:
                        outer._conns.discard(sock)

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = self._server.server_address
        threading.Thread(target=self._server.serve_forever,
                         name=f"raft-listen-{node_id}",
                         daemon=True).start()

    def _hello_sig(self) -> str:
        return hmac.new(self.auth_key or b"", b"raft-transport-v1",
                        hashlib.sha256).hexdigest()

    def set_identity(self, tls_identity) -> None:
        """(Re)build TLS contexts — also used when a restarted bootstrap
        manager adopts the replicated cluster's CA."""
        from ..security.tls import client_context, server_context
        self.tls_identity = tls_identity
        self._server_ctx = server_context(tls_identity,
                                          require_client_cert=True)
        self._client_ctx = client_context(tls_identity)

    @staticmethod
    def _authorize_peer(ssl_sock) -> None:
        """Both raft-link directions require the manager role."""
        from ..security.tls import require_server_role
        require_server_role(ssl_sock, "swarm-manager")

    # ------------------------------------------------------------- topology

    def set_peer(self, node_id: str, addr: Tuple[str, int]) -> None:
        """reference: transport.go:157 AddPeer / UpdatePeer."""
        self._peers[node_id] = tuple(addr)

    def remove_peer(self, node_id: str) -> None:
        self._peers.pop(node_id, None)
        q = self._send_queues.pop(node_id, None)
        if q is not None:
            q.put(None)

    # ------------------------------------------------------ RaftNode surface

    def register(self, node_id: str,
                 handler: Callable[[Message], None]) -> None:
        self._handler = handler

    def unregister(self, node_id: str) -> None:
        self._handler = None
        self._stop.set()
        for q in self._send_queues.values():
            q.put(None)
        self._server.shutdown()
        self._server.server_close()
        # server_close only stops the accept loop; established inbound
        # sockets live in handler threads and must be closed too, or
        # peers keep sending into this dead transport instead of
        # redialing our successor on the same port
        with self._conns_mu:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass

    def send(self, msg: Message) -> None:
        """Ordered, best-effort delivery per peer (raft tolerates loss)."""
        q = self._send_queues.get(msg.dst)
        if q is None:
            if msg.dst not in self._peers:
                return
            q = self._send_queues.setdefault(msg.dst, queue.Queue(
                maxsize=1024))
            t = threading.Thread(target=self._sender_loop,
                                 args=(msg.dst, q),
                                 name=f"raft-send-{msg.dst}", daemon=True)
            self._senders[msg.dst] = t
            t.start()
        try:
            q.put_nowait(msg)
        except queue.Full:
            pass  # drop under backpressure; raft retries

    def _sender_loop(self, peer: str, q: "queue.Queue") -> None:
        sock: Optional[socket.socket] = None
        while not self._stop.is_set():
            msg = q.get()
            if msg is None:
                break
            addr = self._peers.get(peer)
            if addr is None:
                continue
            for attempt in (1, 2):
                try:
                    if sock is None:
                        sock = socket.create_connection(addr, timeout=5)
                        if self._client_ctx is not None:
                            sock = self._client_ctx.wrap_socket(sock)
                            self._authorize_peer(sock)
                        elif self.auth_key is not None:
                            send_frame(sock, {"hello": self._hello_sig()})
                    send_frame(sock, serde.to_dict(msg))
                    break
                except (ssl.SSLError, ConnectionError, OSError,
                        SecurityError):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
                    # second attempt reconnects; then drop the message
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
