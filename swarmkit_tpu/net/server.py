"""Manager network server: dispatcher + control API + CA over mTLS TCP.

Reference role: the manager's gRPC servers (manager.go:475-563) — the
worker-facing Dispatcher service, the user-facing Control service, and the
NodeCA issuance service — all behind mutual TLS rooted at the cluster CA
(reference: ca/transport.go).

One thread per connection (the control plane is low-rate); the assignments
stream switches its connection into push mode.  The TLS handshake
authenticates the peer: its verified client certificate is the identity
every method is gated on.  ``fetch_root_ca``/``issue_certificate`` remain
reachable without a client cert (gated by join token instead, like the
reference's token-gated NodeCA.IssueNodeCertificate).  ``tls=False`` falls
back to hello-frame certificate attestation over plaintext — a debugging
knob only, since a bearer attestation is replayable.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import ssl
import threading
import time
from typing import Any, Dict, Optional

from ..models.objects import STORE_OBJECT_TYPES
from ..models.specs import NodeSpec, SecretSpec, ServiceSpec
from ..models.types import NodeDescription, TaskStatus
from ..security.ca import Certificate, SecurityError
from ..security.tls import peer_certificate, server_context
from ..state import serde
from ..state.watch import Closed
from ..utils.metrics import registry as metrics
from .wire import recv_frame, send_frame

log = logging.getLogger("net.server")


class NotLeaderError(Exception):
    """This manager is not the leader: the dispatcher/control surface
    lives on the leader (agents should rotate to another manager)."""

    code = "not_leader"


class ManagerServer:
    def __init__(self, manager, host: str = "127.0.0.1", port: int = 0,
                 tls: bool = True,
                 tls_identity: Optional[Certificate] = None):
        self.manager = manager
        self.tls = tls
        if tls:
            if tls_identity is None or not tls_identity.key_pem:
                # self-issue the API server's identity from the cluster CA
                # (the reference manager serves with its own node cert)
                from ..models.types import NodeRole
                from ..utils import new_id
                tls_identity = manager.root_ca.issue(
                    "manager-api-" + new_id()[:8], NodeRole.MANAGER)
            self.tls_identity = tls_identity
            self._ssl_ctx = server_context(tls_identity)
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer._handle_conn(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="manager-server", daemon=True)
        self._thread.start()

    def set_tls_identity(self, tls_identity: Certificate) -> None:
        """Swap the serving identity (renewal / root rotation); new
        connections handshake with the fresh cert."""
        self.tls_identity = tls_identity
        self._ssl_ctx = server_context(tls_identity)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ---------------------------------------------------------- connections

    def _handle_conn(self, sock: socket.socket) -> None:
        cert: Optional[Certificate] = None
        try:
            if self.tls:
                try:
                    sock = self._ssl_ctx.wrap_socket(sock,
                                                     server_side=True)
                except (ssl.SSLError, ConnectionError, OSError) as e:
                    log.debug("TLS handshake failed: %s", e)
                    return
                # identity = the TLS-authenticated client cert (chain and
                # validity checked by the handshake; issuer re-checked
                # against the *current* root in case of rotation)
                cert = peer_certificate(sock)
                if cert is not None:
                    try:
                        self.manager.root_ca.verify(cert)
                    except SecurityError:
                        cert = None
            hello = recv_frame(sock)
            if hello.get("method") != "hello":
                send_frame(sock, {"id": hello.get("id"),
                                  "error": "expected hello"})
                return
            cert_data = hello.get("params", {}).get("certificate")
            if cert_data and not self.tls:
                # plaintext fallback: hello-frame attestation (replayable
                # bearer — debugging only)
                try:
                    cert = Certificate.from_bytes(cert_data.encode())
                    self.manager.root_ca.verify(cert)
                except SecurityError as e:
                    send_frame(sock, {"id": hello.get("id"),
                                      "error": str(e),
                                      "code": "unauthenticated"})
                    return
            send_frame(sock, {"id": hello.get("id"), "result": "ok"})

            while True:
                req = recv_frame(sock)
                method = req.get("method", "")
                params = req.get("params", {}) or {}
                rid = req.get("id")
                if method == "open_assignments":
                    # stream mode: this connection now only pushes
                    try:
                        self._stream_assignments(sock, cert, params, rid)
                    except (ConnectionError, OSError):
                        pass
                    except Exception as e:
                        send_frame(sock, {
                            "id": rid, "error": str(e),
                            "code": getattr(e, "code", "internal")})
                    return
                # per-RPC count + latency + error metrics, the
                # grpc-prometheus interceptor equivalent (reference:
                # manager.go:552,563); surfaced by /metrics.  The method
                # label on successes is bounded by the dispatch table
                # (unknown methods always error); error counters carry
                # only the code, so client-chosen strings can never grow
                # the registry or corrupt the exposition format.
                t0 = time.perf_counter()
                error = None
                try:
                    result = self._dispatch(method, params, cert)
                except Exception as e:
                    error = e
                metrics.timer("swarm_rpc_latency").observe(
                    time.perf_counter() - t0)
                if error is None:
                    metrics.counter(
                        f'swarm_rpc{{method="{method}"}}')
                    send_frame(sock, {"id": rid, "result": result})
                else:
                    code = getattr(error, "code", "internal")
                    metrics.counter(f'swarm_rpc_errors{{code="{code}"}}')
                    send_frame(sock, {"id": rid, "error": str(error),
                                      "code": code})
        except (ConnectionError, OSError):
            pass
        except Exception:
            log.exception("connection handler failed")
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _dispatcher(self):
        d = self.manager.dispatcher
        if d is None:
            raise NotLeaderError(
                "this manager is not the leader; retry another manager")
        return d

    @staticmethod
    def _require_cert(cert: Optional[Certificate], node_id: str = "") -> None:
        if cert is None:
            raise SecurityError("certificate required")
        if node_id and cert.node_id != node_id:
            raise SecurityError("certificate/node mismatch")

    @staticmethod
    def _require_manager_cert(cert: Optional[Certificate],
                              what: str) -> None:
        from ..models.types import NodeRole
        ManagerServer._require_cert(cert)
        if NodeRole(cert.role) != NodeRole.MANAGER:
            raise SecurityError(
                f"a manager certificate is required {what}")

    def _network_keys(self):
        """Current dataplane encryption keys + lamport clock, serialized
        for the heartbeat piggyback; cached per clock value so steady-
        state heartbeats reuse the serialized form (the key manager only
        bumps the clock on rotation)."""
        from ..models.objects import Cluster
        from ..state import serde
        try:
            cluster = self.manager.store.view(
                lambda tx: next(iter(tx.find(Cluster)), None))
        except Exception:
            return None, 0
        if cluster is None or not cluster.network_bootstrap_keys:
            return None, 0
        clock = cluster.encryption_key_lamport_clock
        cached = getattr(self, "_netkey_cache", None)
        if cached is not None and cached[0] == clock:
            return cached[1], clock
        keys = [serde.to_dict(k) for k in cluster.network_bootstrap_keys]
        self._netkey_cache = (clock, keys)
        return keys, clock

    def _store_role(self, cert: Optional[Certificate]):
        """The caller's current role per its store Node record (the role
        manager keeps this reconciled with spec.desired_role); falls back
        to the cert's role for nodes not yet registered."""
        if cert is None:
            return None
        from ..models.objects import Node as NodeObject
        try:
            node = self.manager.store.view(
                lambda tx: tx.get(NodeObject, cert.node_id))
        except Exception:
            node = None
        return node.role if node is not None else cert.role

    # -------------------------------------------------------------- methods

    def _dispatch(self, method: str, params: Dict[str, Any],
                  cert: Optional[Certificate]) -> Any:
        m = self.manager

        # ---- CA (token-gated, no client cert needed)
        if method == "fetch_root_ca":
            # bootstrap: the joiner verifies this against its token digest
            # (reference: ca.DownloadRootCA GetRootCACertificate).  The
            # bundle carries both roots during a rotation; the token
            # digest matches the FIRST (current) root.
            return {"ca_cert": m.root_ca.trust_bundle().decode()}
        if method == "issue_certificate":
            # a follower validates against replicated cluster state; pull
            # the latest adoption synchronously so a token minted on the
            # leader moments ago is honored here too
            if hasattr(m, "_adopt_ca_state"):
                m._adopt_ca_state()
            csr = params.get("csr")
            if csr:
                cert_pem = m.ca_server.issue_node_certificate(
                    params["node_id"], params["token"],
                    csr_pem=csr.encode())
                return {"cert": cert_pem.decode(),
                        "ca_cert": m.root_ca.trust_bundle().decode()}
            # certless legacy path: key generated server-side
            issued = m.ca_server.issue_node_certificate(
                params["node_id"], params["token"])
            return {"cert": issued.cert_pem.decode(),
                    "key": issued.key_pem.decode(),
                    "ca_cert": m.root_ca.trust_bundle().decode()}
        if method == "renew_certificate":
            # gated on the caller's valid cert: same identity, fresh
            # validity.  The role comes from the node's STORE record (the
            # role manager's reconciled role), not the old cert — this is
            # the channel by which promotion/demotion reaches the node
            # (reference: ca/server.go:377, role_manager.go reconcile)
            self._require_cert(cert)
            cert_pem = m.ca_server.renew(cert,
                                         csr_pem=params["csr"].encode(),
                                         role=self._store_role(cert))
            return {"cert": cert_pem.decode(),
                    "ca_cert": m.root_ca.trust_bundle().decode()}

        # ---- dispatcher surface (cert-gated to the calling node)
        if method == "register":
            self._require_cert(cert, params["node_id"])
            # leader check FIRST: the node-record write below proposes
            # through raft, and a follower would surface that as an
            # opaque internal error instead of a not_leader the client
            # can rotate on
            dispatcher = self._dispatcher()
            description = serde.from_dict(
                NodeDescription, params.get("description"))
            self._ensure_node_registered(params["node_id"], cert,
                                         description)
            session, period = dispatcher.register(
                params["node_id"], description=description)
            self._record_cert_issuer(cert)
            return {"session_id": session, "period": period}
        if method == "heartbeat":
            self._require_cert(cert, params["node_id"])
            period = self._dispatcher().heartbeat(params["node_id"],
                                                  params["session_id"])
            self._record_cert_issuer(cert)
            # the active root digest rides along so agents renew promptly
            # when a rotation begins (reference: the session stream ships
            # the RootCA; ca/renewer reacts)
            # the node's reconciled role rides along too so a promoted/
            # demoted node renews (and transitions) without waiting out
            # its cert half-life (reference: the session stream carries
            # the Node object; node.go:947 waitRole reacts)
            resp = {"period": period, "managers": m.manager_api_addrs(),
                    "ca_digest": m.root_ca.active_digest,
                    "role": self._store_role(cert)}
            # dataplane encryption keys ride along so agents pick up key-
            # manager rotations (reference: SessionMessage.
            # NetworkBootstrapKeys, api/dispatcher.proto; agent.go
            # handleSessionMessage -> executor.SetNetworkBootstrapKeys)
            keys, clock = self._network_keys()
            if keys is not None:
                resp["network_keys"] = keys
                resp["key_clock"] = clock
            return resp
        if method == "update_task_status":
            self._require_cert(cert, params["node_id"])
            updates = [(u["task_id"],
                        serde.from_dict(TaskStatus, u["status"]))
                       for u in params["updates"]]
            self._dispatcher().update_task_status(
                params["node_id"], params["session_id"], updates)
            return "ok"

        if method == "update_volume_status":
            self._require_cert(cert, params["node_id"])
            self._dispatcher().update_volume_status(
                params["node_id"], params["session_id"],
                [(u[0], u[1]) for u in params["updates"]])
            return "ok"

        if method == "publish_logs":
            self._require_cert(cert, params["node_id"])
            import base64 as _b64
            # the sender's identity is the CERT's, not whatever the
            # payload claims — prevents cross-node log spoofing
            msgs = [dict(m, data=_b64.b64decode(m["data"]),
                         node_id=params["node_id"])
                    for m in params["messages"]]
            self._dispatcher().publish_logs(
                params["node_id"], params["session_id"], msgs)
            return "ok"

        # ---- health (cert-gated; reference: authenticated Health.Check)
        if method == "health":
            self._require_cert(cert)
            return {"status": m.health_check(params.get("service", ""))}

        # ---- manager join (MANAGER-cert gated)
        if method == "raft_join":
            self._require_cert(cert, params["node_id"])
            self._require_manager_cert(cert, "to join raft")
            return m.join_raft(params["node_id"],
                               addr=params.get("addr"),
                               api_addr=params.get("api_addr"))

        # ---- control surface (MANAGER-cert gated: the reference serves the
        # control API only on the operator socket / to manager-role mTLS
        # identities — a worker cert must NOT be able to mutate cluster
        # state, or any compromised worker could promote itself)
        api = m.control_api
        if method.startswith("control."):
            self._require_manager_cert(cert, "for the control API")
            return self._dispatch_control(api, method[len("control."):],
                                          params)
        raise ValueError(f"unknown method {method!r}")

    def _record_cert_issuer(self, cert: Optional[Certificate]) -> None:
        """Track which root this node's TLS identity chains to — the
        CA-rotation reconciler's progress signal (reference:
        ca/reconciler.go watching node cert states)."""
        if cert is None:
            return
        m = self.manager
        try:
            digest = m.root_ca.issuer_digest(cert)
        except Exception:
            return
        if not digest:
            return
        from ..models.objects import Node as NodeObject
        node_id = cert.node_id
        cur = m.store.raw_get(NodeObject, node_id)
        if cur is None or cur.certificate_issuer == digest:
            return

        def cb(tx):
            n = tx.get(NodeObject, node_id)
            if n is None or n.certificate_issuer == digest:
                return
            n = n.copy()
            n.certificate = cert.cert_pem
            n.certificate_issuer = digest
            tx.update(n)

        try:
            m.store.update(cb)
        except Exception:
            log.debug("recording cert issuer failed", exc_info=True)

    def _ensure_node_registered(self, node_id: str, cert: Certificate,
                                description) -> None:
        """Self-registration of joined nodes (in-process mode does this in
        Node.start; over the network the manager does it on first
        register, reference: dispatcher register + node store)."""
        from ..models.objects import Node as NodeObject
        from ..models.types import Annotations, NodeRole

        def cb(tx):
            if tx.get(NodeObject, node_id) is not None:
                return
            name = description.hostname if description else node_id[:8]
            tx.create(NodeObject(
                id=node_id,
                spec=NodeSpec(annotations=Annotations(name=name),
                              desired_role=NodeRole(cert.role)),
                description=description,
                role=int(cert.role)))

        self.manager.store.update(cb)

    def _dispatch_control(self, api, method: str,
                          params: Dict[str, Any]) -> Any:
        def obj_out(obj):
            return None if obj is None else {
                "collection": obj.collection, "obj": serde.to_dict(obj)}

        if method == "create_service":
            return obj_out(api.create_service(
                serde.from_dict(ServiceSpec, params["spec"])))
        if method == "update_service":
            return obj_out(api.update_service(
                params["service_id"], params["version"],
                serde.from_dict(ServiceSpec, params["spec"])))
        if method == "remove_service":
            api.remove_service(params["service_id"])
            return "ok"
        if method == "get_service":
            return obj_out(api.get_service(params["service_id"]))
        if method == "collect_logs":
            import base64 as _b64
            return [dict(m, data=_b64.b64encode(m["data"]).decode())
                    for m in api.collect_logs(
                        params["service_id"],
                        duration=params.get("duration", 2.0),
                        tail=params.get("tail", -1),
                        since=params.get("since", 0.0),
                        follow=params.get("follow", True),
                        streams=params.get("streams") or [])]
        if method == "list_services":
            return [obj_out(s) for s in api.list_services(
                name_prefix=params.get("name_prefix", ""))]
        if method == "list_service_statuses":
            return api.list_service_statuses(
                list(params.get("service_ids", [])))
        if method == "list_nodes":
            return [obj_out(n) for n in api.list_nodes()]
        if method == "update_node":
            return obj_out(api.update_node(
                params["node_id"], params["version"],
                serde.from_dict(NodeSpec, params["spec"])))
        if method == "remove_node":
            api.remove_node(params["node_id"],
                            force=params.get("force", False))
            return "ok"
        if method == "list_tasks":
            return [obj_out(t) for t in api.list_tasks(
                service_id=params.get("service_id", ""),
                node_id=params.get("node_id", ""))]
        if method == "remove_task":
            api.remove_task(params["task_id"])
            return "ok"
        if method == "create_secret":
            return obj_out(api.create_secret(
                serde.from_dict(SecretSpec, params["spec"])))
        if method == "get_secret":
            return obj_out(api.get_secret(params["secret_id"]))
        if method == "get_config":
            return obj_out(api.get_config(params["config_id"]))
        if method == "list_secrets":
            return [obj_out(s) for s in api.list_secrets()]
        if method == "remove_secret":
            api.remove_secret(params["secret_id"])
            return "ok"
        if method == "create_config":
            from ..models.specs import ConfigSpec
            return obj_out(api.create_config(
                serde.from_dict(ConfigSpec, params["spec"])))
        if method == "list_configs":
            return [obj_out(c) for c in api.list_configs()]
        if method == "remove_config":
            api.remove_config(params["config_id"])
            return "ok"
        if method == "create_network":
            from ..models.specs import NetworkSpec
            return obj_out(api.create_network(
                serde.from_dict(NetworkSpec, params["spec"])))
        if method == "list_networks":
            return [obj_out(n) for n in api.list_networks()]
        if method == "remove_network":
            api.remove_network(params["network_id"])
            return "ok"
        if method == "create_volume":
            from ..models.specs import VolumeSpec
            return obj_out(api.create_volume(
                serde.from_dict(VolumeSpec, params["spec"])))
        if method == "update_volume":
            from ..models.specs import VolumeSpec
            return obj_out(api.update_volume(
                params["volume_id"], params["version"],
                serde.from_dict(VolumeSpec, params["spec"])))
        if method == "get_volume":
            return obj_out(api.get_volume(params["volume_id"]))
        if method == "list_volumes":
            return [obj_out(v) for v in api.list_volumes(
                name_prefix=params.get("name_prefix", ""))]
        if method == "remove_volume":
            api.remove_volume(params["volume_id"],
                              force=params.get("force", False))
            return "ok"
        if method == "create_extension":
            from ..models.types import Annotations
            return obj_out(api.create_extension(
                serde.from_dict(Annotations, params["annotations"]),
                params.get("description", "")))
        if method == "list_extensions":
            return [obj_out(e) for e in api.list_extensions()]
        if method == "remove_extension":
            api.remove_extension(params["extension_id"])
            return "ok"
        if method == "create_resource":
            import base64 as _b64
            from ..models.types import Annotations
            return obj_out(api.create_resource(
                serde.from_dict(Annotations, params["annotations"]),
                params["kind"],
                _b64.b64decode(params.get("payload", ""))))
        if method == "list_resources":
            return [obj_out(r) for r in api.list_resources(
                kind=params.get("kind", ""))]
        if method == "remove_resource":
            api.remove_resource(params["resource_id"])
            return "ok"
        if method == "rotate_join_token":
            return api.rotate_join_token(params["role"])
        if method == "get_default_cluster":
            return obj_out(api.get_default_cluster())
        if method == "list_clusters":
            return [obj_out(c) for c in api.list_clusters()]
        if method == "rotate_ca":
            return api.rotate_ca()
        if method == "set_autolock":
            return api.set_autolock(bool(params["enabled"]))
        if method == "get_unlock_key":
            return api.get_unlock_key()
        raise ValueError(f"unknown control method {method!r}")

    # ------------------------------------------------------------- streaming

    def _stream_assignments(self, sock: socket.socket,
                            cert: Optional[Certificate],
                            params: Dict[str, Any], rid) -> None:
        self._require_cert(cert, params["node_id"])
        stream = self._dispatcher().open_assignments(
            params["node_id"], params["session_id"])
        send_frame(sock, {"id": rid, "result": "streaming"})
        try:
            while True:
                try:
                    msg = stream.get(timeout=0.5)
                except TimeoutError:
                    # liveness probe: a vanished peer would otherwise leak
                    # this thread + its dispatcher stream until the next
                    # push attempt.  On TLS sockets a would-block read
                    # surfaces as SSLWantReadError, not BlockingIOError.
                    sock.setblocking(False)
                    try:
                        if sock.recv(1) == b"":
                            return  # peer closed
                    except (BlockingIOError, InterruptedError,
                            ssl.SSLWantReadError):
                        pass
                    finally:
                        sock.setblocking(True)
                    continue
                except Closed:
                    send_frame(sock, {"push": "closed",
                                      "error": str(stream.error or "")})
                    return
                send_frame(sock, {
                    "push": "assignments",
                    "type": msg.type,
                    "applies_to": msg.applies_to,
                    "results_in": msg.results_in,
                    "changes": [
                        {"action": action, "kind": kind,
                         "obj": serde.to_dict(obj)}
                        for action, kind, obj in msg.changes],
                })
        except (ConnectionError, OSError):
            pass
        finally:
            stream.close()
