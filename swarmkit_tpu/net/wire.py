"""Wire protocol: length-prefixed JSON frames over TCP.

Reference role: the gRPC/mTLS links of the reference (api/*.proto services
over DCN).  Framing is 4-byte big-endian length + UTF-8 JSON; every
connection opens with a ``hello`` frame carrying the peer's certificate
attestation, which the server verifies against the cluster root CA — the
mTLS handshake stand-in (see security/ca.py's scope note).

Frame shapes:
  request:  {"id": n, "method": str, "params": {...}}
  response: {"id": n, "result": ...} | {"id": n, "error", "code"}
  push:     {"push": ..., ...}      (server-initiated, streams)
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Optional

MAX_FRAME = 64 << 20


class WireError(Exception):
    pass


def send_frame(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(data) > MAX_FRAME:
        raise WireError("frame too large")
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise WireError("frame too large")
    return json.loads(_recv_exact(sock, length).decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)
