"""Node: the deployable unit — always an agent, optionally a manager.

Reference: node/node.go (run :286, runAgent :576, runManager :983,
loadSecurityConfig :799).

Joins a cluster via a join token presented to the CA server, persists its
certificate through the KeyReadWriter, registers itself in the cluster
store, and supervises agent (+ manager) lifecycles.  Transport is the
in-process dispatcher surface; a network client with the same methods
slots in unchanged.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from .agent import Agent
from .agent.exec import Executor
from .models.objects import Node as NodeObject
from .models.specs import NodeSpec
from .models.types import Annotations, NodeDescription, NodeRole
from .security.ca import CAServer, Certificate, KeyReadWriter, SecurityError
from .utils import new_id

log = logging.getLogger("node")


class LocalDispatcherClient:
    """In-process agent→dispatcher adapter.

    Same surface as the wire dispatcher client, plus the heartbeat
    piggyback the wire path gets from the server — network bootstrap keys
    (reference: SessionMessage.NetworkBootstrapKeys) read straight from
    the co-located store, so a manager's own agent follows key-manager
    rotations exactly like remote workers do."""

    def __init__(self, dispatcher):
        self._dispatcher = dispatcher
        self.last_network_keys = None
        self.last_key_clock = None

    def __getattr__(self, name):
        return getattr(self._dispatcher, name)

    def heartbeat(self, node_id: str, session_id: str) -> float:
        period = self._dispatcher.heartbeat(node_id, session_id)
        try:
            from .models.objects import Cluster
            cluster = self._dispatcher.store.view(
                lambda tx: next(iter(tx.find(Cluster)), None))
            if cluster is not None and cluster.network_bootstrap_keys:
                self.last_network_keys = list(
                    cluster.network_bootstrap_keys)
                self.last_key_clock = \
                    cluster.encryption_key_lamport_clock
        except Exception:
            log.exception("reading network bootstrap keys failed")
        return period


class Node:
    def __init__(self, executor: Executor, state_dir: str,
                 node_id: Optional[str] = None,
                 kek: Optional[bytes] = None):
        self.executor = executor
        self.state_dir = state_dir
        self.node_id = node_id or new_id()
        self.certificate: Optional[Certificate] = None
        self.key_rw = KeyReadWriter(
            os.path.join(state_dir, "certificates", "node.key"), kek=kek)
        self.agent: Optional[Agent] = None
        self.manager = None

    # ---------------------------------------------------------------- joining

    def load_or_join(self, ca_server: CAServer, join_token: str) -> None:
        """Obtain (or reload) this node's identity
        (reference: node.go:799 loadSecurityConfig)."""
        try:
            cert, _ = self.key_rw.read()
            ca_server.root_ca.verify(cert)
            self.certificate = cert
            self.node_id = cert.node_id
            if ca_server.root_ca.needs_renewal(cert):
                self.certificate = ca_server.renew(cert)
                self.key_rw.write(self.certificate, b"")
            return
        except (FileNotFoundError, SecurityError):
            pass
        cert = ca_server.issue_node_certificate(self.node_id, join_token)
        self.key_rw.write(cert, b"")
        self.certificate = cert

    @property
    def role(self) -> NodeRole:
        if self.certificate is None:
            return NodeRole.WORKER
        return NodeRole(self.certificate.role)

    # ------------------------------------------------------------- lifecycle

    def start(self, dispatcher_client, store=None,
              hostname: str = "") -> None:
        """Register in the cluster and run the agent; ``store`` is the
        manager-side store for self-registration (in-process mode)."""
        if store is not None:
            desc = None
            try:
                desc = self.executor.describe()
            except Exception:
                desc = NodeDescription(hostname=hostname or self.node_id[:8])
            node_obj = NodeObject(
                id=self.node_id,
                spec=NodeSpec(
                    annotations=Annotations(name=hostname or
                                            self.node_id[:8]),
                    desired_role=self.role),
                description=desc,
                role=int(self.role))

            def cb(tx):
                if tx.get(NodeObject, self.node_id) is None:
                    tx.create(node_obj)

            store.update(cb)
        if hasattr(dispatcher_client, "store"):
            # a bare in-process Dispatcher: wrap it so the heartbeat
            # piggyback (network bootstrap keys) works like the wire path
            dispatcher_client = LocalDispatcherClient(dispatcher_client)
        self.agent = Agent(
            self.node_id, self.executor, dispatcher_client,
            task_db_path=os.path.join(self.state_dir, "worker", "tasks.db"))
        self.agent.start()

    def stop(self) -> None:
        if self.agent is not None:
            self.agent.stop()
            self.agent = None
