"""Observability layer: span tracing, task-lifecycle latency, reports,
flight recorder, time-series sampler, and the health/SLO plane.

``tracer`` is the process-wide span recorder (disabled by default; bench,
the simulator, and ``/debug/trace`` enable/serve it).  ``flightrec`` is
the process-wide black box: bounded rings of recent spans (tapped from
the tracer), metric samples (``Sampler``), store events, and raft role
transitions, dumped as one post-mortem JSON (``/debug/flightrec``, sim
invariant violations, bench variance-guard trips).  ``HealthEvaluator``
judges declarative SLO checks over the registry and serves
``/debug/health``.  Metrics counters and timers live in
``utils.metrics.registry`` — this package adds the span/trace dimension
and the derived planes on top.
"""

from . import debugpages  # noqa: F401  (installs /debug/* endpoint hook)
from . import devicetelemetry  # noqa: F401  (device-plane ledger)
from . import planes  # noqa: F401  (per-plane saturation signals)
from .flightrec import FlightRecorder, flightrec
from .health import Check, HealthEvaluator
from .journey import JourneyLedger, journeys
from .lifecycle import LifecycleTracker
from .report import (
    device_table, diff_phase_tables, format_device_table, format_diff,
    format_table, phase_table, validate_chrome_trace,
)
from .sampler import Sampler
from .trace import Span, Tracer, tracer

__all__ = [
    "Check", "FlightRecorder", "HealthEvaluator", "JourneyLedger",
    "LifecycleTracker", "Sampler", "Span", "Tracer",
    "device_table", "devicetelemetry", "diff_phase_tables", "flightrec",
    "format_device_table", "format_diff", "format_table", "journeys",
    "phase_table", "planes", "tracer", "validate_chrome_trace",
]
