"""Observability layer: span tracing, task-lifecycle latency, reports.

``tracer`` is the process-wide span recorder (disabled by default; bench,
the simulator, and ``/debug/trace`` enable/serve it).  Metrics counters
and timers live in ``utils.metrics.registry`` — this package adds the
span/trace dimension and the lifecycle tracker on top.
"""

from .lifecycle import LifecycleTracker
from .report import format_table, phase_table, validate_chrome_trace
from .trace import Span, Tracer, tracer

__all__ = [
    "LifecycleTracker", "Span", "Tracer", "format_table", "phase_table",
    "tracer", "validate_chrome_trace",
]
