"""The obs package's /debug/* endpoints for utils.httpdebug.DebugServer.

These handlers used to live inside httpdebug itself, lazily importing
obs — an inversion of the layering matrix (utils must import nothing
above itself; swarmlint's ``layering`` rule now enforces that).  They
register here instead, through the server's default-endpoint hook:
importing the obs package is what makes any subsequently constructed
DebugServer serve /debug/trace, /debug/health and /debug/flightrec.
"""

from __future__ import annotations

import json
from typing import Tuple

from ..utils import httpdebug


def _h_trace(server, query) -> Tuple[bytes, int, str]:
    from .trace import tracer
    enable = query.get("enable")
    if enable:
        value = enable[0].lower()
        if value in ("1", "true", "on", "yes"):
            tracer.reset()
            tracer.enable()
            return b"tracing enabled\n", 200, "text/plain"
        if value in ("0", "false", "off", "no"):
            tracer.disable()
            return b"tracing disabled\n", 200, "text/plain"
        return (f"bad enable value {value!r}; use 1/0\n".encode(),
                400, "text/plain")
    return tracer.to_json().encode(), 200, "application/json"


def _h_health(server, query) -> Tuple[bytes, int, str]:
    ev = server._evaluator
    if ev is None:
        from .health import evaluator
        ev = server._evaluator = evaluator
    report = ev.report()
    # probes consume the status code; humans the JSON body
    code = 503 if report["status"] == "fail" else 200
    body = json.dumps(report, sort_keys=True, indent=1).encode()
    return body, code, "application/json"


def _h_flightrec(server, query) -> Tuple[bytes, int, str]:
    from .flightrec import flightrec
    return flightrec.dump_json().encode(), 200, "application/json"


def _h_planes(server, query) -> Tuple[bytes, int, str]:
    """Per-plane saturation report + journey-ledger summary.  Must
    render on a fresh manager with zero observations and on a deposed
    ex-leader alike (ISSUE 17 bugfix sweep): both arms below only read
    module-level state that always exists."""
    from .journey import journeys
    from .planes import report_all
    doc = {"planes": report_all(), "journeys": journeys.summary()}
    body = json.dumps(doc, sort_keys=True, indent=1).encode()
    return body, 200, "application/json"


def _h_device(server, query) -> Tuple[bytes, int, str]:
    """Device-plane telemetry ledger: kernel rows, per-reason transfer
    bytes, the compile-cache ledger, memory watermarks, and the
    donation balance.  Renders on a fresh manager (all tables empty)
    and on a deposed ex-leader (module-level state always exists) — the
    _h_planes discipline."""
    from .devicetelemetry import snapshot
    from .planes import DEVICE, report_all
    doc = {"device_telemetry": snapshot(),
           "device_plane": report_all().get(DEVICE, {})}
    body = json.dumps(doc, sort_keys=True, indent=1).encode()
    return body, 200, "application/json"


def _install(server: "httpdebug.DebugServer") -> None:
    server.register("/debug/trace",
                    lambda query: _h_trace(server, query),
                    "Chrome trace-event JSON of the span tracer "
                    "(?enable=1/0 toggles recording)")
    server.register("/debug/health",
                    lambda query: _h_health(server, query),
                    "SLO check report (JSON); 503 while any check "
                    "is failing")
    server.register("/debug/flightrec",
                    lambda query: _h_flightrec(server, query),
                    "flight-recorder post-mortem dump (JSON): recent "
                    "spans, metric samples, store events, raft "
                    "transitions")
    server.register("/debug/planes",
                    lambda query: _h_planes(server, query),
                    "per-plane saturation report (occupancy, queue "
                    "depth, oldest-item age, drops/defers) + journey "
                    "ledger summary")
    server.register("/debug/device",
                    lambda query: _h_device(server, query),
                    "device-plane telemetry: kernel ledger, per-reason "
                    "transfer bytes, compile-cache ledger, memory "
                    "watermarks, donation balance")


httpdebug.register_default_endpoints(_install)
