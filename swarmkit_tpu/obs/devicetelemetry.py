"""Device-plane telemetry: kernel / transfer / compile / memory ledger.

PR 17 made the *control* planes legible; the device plane — the part
this reproduction exists to accelerate — was observed only as a coarse
queue gauge plus a retroactive ``plan.compile`` span.  This module is
the deterministic, bounded ledger every device interaction routes
through (the xprof/JAX-profiler model of attributing time to compiles
vs transfers vs compute, kept Dapper-cheap):

* **kernel ledger** — one aggregate row per ``(bucket, route)``:
  dispatch count, group/task/node rows, dispatch ns, D2H ns, and the
  retroactively measured compile ns.  Keys are the existing static
  compile-bucket names (``nb..``, ``_st<id>``, ``_gfF``, ``feas_``,
  ``stream_``, ``preempt_``) — bounded label cardinality by
  construction, never entity ids (the swarmlint metric-hygiene rule
  polices the exported ``swarm_device_kernel_*`` names the same way).
* **transfer accounting** — every H2D upload / D2H fetch seam reports
  bytes with a *reason* from a fixed taxonomy; streaming's resident
  tier also reports the bytes its donated scatter AVOIDED moving, so
  the streaming win is a number, not an inference.
* **compile-cache ledger** — a per-process registry of every jit
  signature ever compiled (bucket, shapes hash, retro compile time,
  hit/miss counts), serialized into bench artifacts and flight-recorder
  dumps so "compiles 0 in the timed window" is auditable per-signature.
* **memory watermarks** — live-buffer byte estimates per resident tier
  (host mirror vs device copies), plus a donation-balance registry that
  cross-checks the swarmlint donation rule at *runtime*: buffers
  donated to XLA are registered, retirements balance them, and a read
  of a still-donated buffer is a counted violation.

Determinism discipline: this module NEVER consumes the time source
(``models.types.now``) — callers hand it durations they already
measured — so enabling it cannot shift frozen-clock byte-identity runs.
All ledger keys are strings aggregated in program order and snapshots
sort them, so output is independent of PYTHONHASHSEED.  Every table is
bounded (row caps with counted overflow), so a pathological workload
costs O(cap), never O(signatures).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Iterable, List, Optional

from ..utils.metrics import registry as _metrics

#: fixed transfer-reason taxonomy (bounded label cardinality).  Unknown
#: reasons lump into "other" rather than minting labels.
H2D_REASONS = (
    "cold_build",      # resident full upload / fused run node state
    "dirty_scatter",   # streaming donated scatter staging buffers
    "shard_scatter",   # per-shard staged scatter buffers (mesh tier)
    "wide_reupload",   # delta wider than the scatter buckets
    "mesh_reshard",    # NamedSharding device_put over the mesh
    "group_inputs",    # per-group kernel input columns
    "fused_inputs",    # fused chunk staging arrays
    "gang_inputs",     # gang feasibility input stacks
    "preempt_inputs",  # victim-selection candidate matrices
)
D2H_REASONS = (
    "fetch",           # plan outputs (fetch_plan seam)
    "feasibility",     # preassigned-validation mask/capacity
    "preempt",         # victim picks
    "probe",           # launch-overhead measurement
)
_OTHER = "other"

#: fixed memory tiers (watermark gauges)
TIERS = ("host_mirror", "device_resident")

#: row caps — counted overflow, never silent truncation
MAX_KERNEL_ROWS = 256
MAX_CACHE_ROWS = 512
MAX_DONATED_IDS = 4096
#: distinct (bucket, route) label combos exported to the live metrics
#: registry — tighter than MAX_KERNEL_ROWS because exposition-page
#: cardinality is the scarcer resource; past the cap, dispatches still
#: count but under bucket="__overflow__"
MAX_METRIC_SERIES = 48


def tree_nbytes(obj) -> int:
    """Total ``nbytes`` of a nested tuple/list/dict of array-likes —
    the one byte-count every transfer seam shares (host-side shapes
    only; never introspects device buffers)."""
    if obj is None:
        return 0
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, dict):
        return sum(tree_nbytes(v) for v in obj.values())
    if isinstance(obj, (tuple, list)):
        return sum(tree_nbytes(v) for v in obj)
    return 0


class DeviceTelemetry:
    """The bounded ledger.  Thread-safe; every note is a few dict ops
    under one lock (the PlaneStats cost model)."""

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        # (bucket, route) -> row
        self._kernel: Dict[tuple, Dict[str, int]] = {}
        self.kernel_overflow = 0
        # (bucket, route) combos already exported as live metric series
        self._metric_series: set = set()
        # direction -> reason -> {"bytes", "count"}
        self._transfers: Dict[str, Dict[str, Dict[str, int]]] = {
            "h2d": {}, "d2h": {}}
        self.bytes_avoided = 0
        # bucket -> {"shape_hash","compiles","compile_ns","hits","misses"}
        self._cache: Dict[str, Dict[str, int]] = {}
        self.cache_overflow = 0
        # tier -> {"bytes","peak"}
        self._mem: Dict[str, Dict[str, int]] = {}
        # donation balance: live ids of buffers donated to XLA
        # (insertion-ordered for FIFO eviction; value is a presence
        # marker — it must be truthy so note_retired's pop can tell a
        # balanced retirement from an id never donated)
        self._donated: Dict[int, bool] = {}
        self.donations = 0
        self.retirements = 0
        self.donation_violations = 0

    # ------------------------------------------------------- kernel ledger

    def note_kernel(self, bucket: str, route: str, *,
                    dispatch_s: float = 0.0, d2h_s: float = 0.0,
                    compile_s: float = 0.0, groups: int = 1,
                    task_rows: int = 0, node_rows: int = 0,
                    strategy_id: int = -1) -> None:
        """One device dispatch (or one fetch completing it), keyed by
        the static jit-signature bucket and the routing label."""
        if not self.enabled:
            return
        key = (bucket, route)
        with self._mu:
            row = self._kernel.get(key)
            if row is None:
                if len(self._kernel) >= MAX_KERNEL_ROWS:
                    self.kernel_overflow += 1
                    key = ("__overflow__", route)
                    row = self._kernel.get(key)
                if row is None:
                    row = self._kernel[key] = {
                        "dispatches": 0, "groups": 0, "task_rows": 0,
                        "node_rows": 0, "dispatch_ns": 0, "d2h_ns": 0,
                        "retro_compile_ns": 0, "strategy_id": -1}
            row["dispatches"] += 1
            row["groups"] += int(groups)
            row["task_rows"] += int(task_rows)
            row["node_rows"] = max(row["node_rows"], int(node_rows))
            row["dispatch_ns"] += int(dispatch_s * 1e9)
            row["d2h_ns"] += int(d2h_s * 1e9)
            row["retro_compile_ns"] += int(compile_s * 1e9)
            if strategy_id >= 0:
                row["strategy_id"] = int(strategy_id)
            mkey = key
            if mkey not in self._metric_series:
                if len(self._metric_series) >= MAX_METRIC_SERIES:
                    mkey = ("__overflow__", route)
                else:
                    self._metric_series.add(mkey)
        _metrics.counter(
            f'swarm_device_kernel_dispatches{{bucket="{mkey[0]}"'
            f',route="{route}"}}')

    # ---------------------------------------------------------- transfers

    def _note_transfer(self, direction: str, reasons: tuple,
                       reason: str, nbytes: int) -> None:
        if not self.enabled or nbytes < 0:
            return
        if reason not in reasons:
            reason = _OTHER
        with self._mu:
            table = self._transfers[direction]
            row = table.get(reason)
            if row is None:
                row = table[reason] = {"bytes": 0, "count": 0}
            row["bytes"] += int(nbytes)
            row["count"] += 1
        _metrics.counter(
            f'swarm_device_transfer_bytes{{dir="{direction}"'
            f',reason="{reason}"}}', int(nbytes))

    def note_h2d(self, reason: str, nbytes: int) -> None:
        """Host-to-device upload of ``nbytes`` (host-side shape math)."""
        self._note_transfer("h2d", H2D_REASONS, reason, nbytes)

    def note_d2h(self, reason: str, nbytes: int) -> None:
        """Device-to-host fetch of ``nbytes``."""
        self._note_transfer("d2h", D2H_REASONS, reason, nbytes)

    def note_bytes_avoided(self, nbytes: int) -> None:
        """Bytes a resident/donated fast path did NOT move (the
        streaming win, measured rather than inferred)."""
        if not self.enabled or nbytes <= 0:
            return
        with self._mu:
            self.bytes_avoided += int(nbytes)
        _metrics.counter("swarm_device_bytes_avoided", int(nbytes))

    # ------------------------------------------------ compile-cache ledger

    def _cache_row(self, bucket: str) -> Optional[Dict[str, int]]:
        row = self._cache.get(bucket)
        if row is None:
            if len(self._cache) >= MAX_CACHE_ROWS:
                self.cache_overflow += 1
                return None
            row = self._cache[bucket] = {
                # PYTHONHASHSEED-independent shapes hash (crc32, the
                # journey-sampling discipline)
                "shape_hash": zlib.crc32(bucket.encode()) & 0xFFFFFFFF,
                "compiles": 0, "compile_ns": 0, "hits": 0, "misses": 0}
        return row

    def note_compile(self, bucket: str, dt: float,
                     count: int = 1) -> None:
        """An observed XLA cache miss: ``count`` new signatures under
        ``bucket``, retro-measured at ``dt`` seconds."""
        if not self.enabled:
            return
        with self._mu:
            row = self._cache_row(bucket)
            if row is None:
                return
            row["compiles"] += int(count)
            row["misses"] += int(count)
            row["compile_ns"] += int(dt * 1e9)

    def note_cache_hit(self, bucket: str) -> None:
        """A dispatch whose jit cache did not grow — the common,
        load-bearing case the ledger exists to make auditable."""
        if not self.enabled:
            return
        with self._mu:
            row = self._cache_row(bucket)
            if row is not None:
                row["hits"] += 1

    def compile_cache_snapshot(self) -> Dict[str, Dict[str, int]]:
        """Sorted copy of the per-signature ledger (bench diffs the
        before/after of the timed window against this)."""
        with self._mu:
            return {b: dict(r) for b, r in sorted(self._cache.items())}

    # ---------------------------------------------------------- watermarks

    def set_watermark(self, tier: str, nbytes: int) -> None:
        """Live-buffer byte estimate for one resident tier."""
        if not self.enabled or tier not in TIERS:
            return
        with self._mu:
            row = self._mem.get(tier)
            if row is None:
                row = self._mem[tier] = {"bytes": 0, "peak": 0}
            row["bytes"] = int(nbytes)
            row["peak"] = max(row["peak"], int(nbytes))
        _metrics.gauge(
            f'swarm_device_mem_bytes{{tier="{tier}"}}', int(nbytes))

    # ----------------------------------------------------- donation balance

    def note_donated(self, ids: Iterable[int]) -> None:
        """Register buffers about to be donated to XLA (their host
        references must never be read again — the runtime twin of the
        swarmlint donated-arg-reuse rule)."""
        if not self.enabled:
            return
        with self._mu:
            for i in ids:
                if len(self._donated) >= MAX_DONATED_IDS:
                    # FIFO eviction keeps the registry bounded; an
                    # evicted id simply stops being checkable
                    self._donated.pop(next(iter(self._donated)))
                self._donated[int(i)] = True
                self.donations += 1

    def note_retired(self, ids: Iterable[int]) -> None:
        """Balance donated buffers once their rebind landed (the old
        references are provably unreachable)."""
        if not self.enabled:
            return
        with self._mu:
            for i in ids:
                if self._donated.pop(int(i), None) is None:
                    continue
                self.retirements += 1

    def check_live(self, ids: Iterable[int]) -> List[int]:
        """Assert none of ``ids`` is a still-donated buffer; returns the
        violating ids (counted + flight-recorded, never raising — obs
        must not take the data path down)."""
        if not self.enabled:
            return []
        with self._mu:
            bad = [int(i) for i in ids if int(i) in self._donated]
            self.donation_violations += len(bad)
        if bad:
            _metrics.counter("swarm_device_donation_violations",
                             len(bad))
            from .flightrec import flightrec
            flightrec.note(
                f"device donation-balance violation: {len(bad)} "
                f"donated buffer(s) read after donation")
        return bad

    # ------------------------------------------------------------ reading

    def snapshot(self) -> Dict[str, object]:
        """One deterministic document: sorted keys, aggregate ints only
        — the bench-artifact / flightrec-dump / ``/debug/device``
        surface.  Renders on a fresh process (all tables empty)."""
        with self._mu:
            kernel = {f"{b}|{r}": dict(row) for (b, r), row
                      in sorted(self._kernel.items())}
            transfers = {
                d: {reason: dict(row) for reason, row
                    in sorted(table.items())}
                for d, table in sorted(self._transfers.items())}
            cache = {b: dict(r) for b, r in sorted(self._cache.items())}
            mem = {t: dict(r) for t, r in sorted(self._mem.items())}
            return {
                "enabled": self.enabled,
                "kernel": kernel,
                "kernel_overflow": self.kernel_overflow,
                "transfers": transfers,
                "bytes_avoided": self.bytes_avoided,
                "compile_cache": cache,
                "compile_cache_overflow": self.cache_overflow,
                "memory": mem,
                "donation": {
                    "donated": self.donations,
                    "retired": self.retirements,
                    "outstanding": len(self._donated),
                    "violations": self.donation_violations,
                },
            }

    def transfer_totals(self) -> Dict[str, int]:
        """{"h2d": bytes, "d2h": bytes} — the scalar the crossover
        sweep and cfg10 diff around their timed windows."""
        with self._mu:
            return {d: sum(r["bytes"] for r in table.values())
                    for d, table in sorted(self._transfers.items())}

    def sub_plane_rows(self) -> Dict[str, object]:
        """Device-plane sub-rows for ``PlaneStats.report()``: where the
        plane's busy time and queue pressure actually went.  Empty dict
        on a fresh process (render-on-empty discipline)."""
        with self._mu:
            if not self._kernel and not any(self._transfers.values()):
                return {}
            disp = sum(r["dispatches"] for r in self._kernel.values())
            dns = sum(r["dispatch_ns"] for r in self._kernel.values())
            fns = sum(r["d2h_ns"] for r in self._kernel.values())
            cns = sum(r["compile_ns"] for r in self._cache.values())
            hits = sum(r["hits"] for r in self._cache.values())
            comp = sum(r["compiles"] for r in self._cache.values())
            h2d = sum(r["bytes"]
                      for r in self._transfers["h2d"].values())
            d2h = sum(r["bytes"]
                      for r in self._transfers["d2h"].values())
            return {
                "kernel_dispatches": disp,
                "dispatch_s": round(dns / 1e9, 6),
                "d2h_s": round(fns / 1e9, 6),
                "compile_s": round(cns / 1e9, 6),
                "compiles": comp,
                "cache_hits": hits,
                "h2d_bytes": h2d,
                "d2h_bytes": d2h,
                "bytes_avoided": self.bytes_avoided,
            }

    def journey_sub_attribution(self, plane_s: float
                                ) -> Optional[Dict[str, float]]:
        """Device sub-attribution for the journeys' ``planned``
        milestone: split the device ledger's busy time into dispatch /
        D2H / compile shares, clamped against the owning plane's
        seconds.  None when the ledger saw no device work (the
        critical-path report then stays byte-identical to PR 17)."""
        with self._mu:
            dns = sum(r["dispatch_ns"] for r in self._kernel.values())
            fns = sum(r["d2h_ns"] for r in self._kernel.values())
            cns = sum(r["compile_ns"] for r in self._cache.values())
        total = dns + fns + cns
        if total <= 0:
            return None
        out = {
            "dispatch_s": round(dns / 1e9, 9),
            "d2h_s": round(fns / 1e9, 9),
            "compile_s": round(cns / 1e9, 9),
            "dispatch_frac": round(dns / total, 6),
            "d2h_frac": round(fns / total, 6),
            "compile_frac": round(cns / total, 6),
        }
        if plane_s > 0:
            out["of_plane_frac"] = round(
                min(1.0, (total / 1e9) / plane_s), 6)
        return out


# ------------------------------------------------------------- module state
#
# One process-wide ledger, rebound (not cleared) by reset() so a
# save_state capture survives — the planes.py/flightrec lifecycle
# contract shared by every obs singleton.

_state = DeviceTelemetry()


def set_enabled(on: bool) -> None:
    """Toggle the whole ledger (bench's obs-overhead off-half)."""
    _state.enabled = bool(on)


def is_enabled() -> bool:
    return _state.enabled


def note_kernel(bucket: str, route: str, **kw) -> None:
    _state.note_kernel(bucket, route, **kw)


def note_h2d(reason: str, nbytes: int) -> None:
    _state.note_h2d(reason, nbytes)


def note_d2h(reason: str, nbytes: int) -> None:
    _state.note_d2h(reason, nbytes)


def note_bytes_avoided(nbytes: int) -> None:
    _state.note_bytes_avoided(nbytes)


def note_compile(bucket: str, dt: float, count: int = 1) -> None:
    _state.note_compile(bucket, dt, count)


def note_cache_hit(bucket: str) -> None:
    _state.note_cache_hit(bucket)


def set_watermark(tier: str, nbytes: int) -> None:
    _state.set_watermark(tier, nbytes)


def note_donated(ids: Iterable[int]) -> None:
    _state.note_donated(ids)


def note_retired(ids: Iterable[int]) -> None:
    _state.note_retired(ids)


def check_live(ids: Iterable[int]) -> List[int]:
    return _state.check_live(ids)


def snapshot() -> Dict[str, object]:
    return _state.snapshot()


def compile_cache_snapshot() -> Dict[str, Dict[str, int]]:
    return _state.compile_cache_snapshot()


def transfer_totals() -> Dict[str, int]:
    return _state.transfer_totals()


def sub_plane_rows() -> Dict[str, object]:
    return _state.sub_plane_rows()


def journey_sub_attribution(plane_s: float
                            ) -> Optional[Dict[str, float]]:
    return _state.journey_sub_attribution(plane_s)


def save_state():
    return _state


def restore_state(state) -> None:
    global _state
    _state = state


def reset() -> None:
    """Start fresh (tests, bench epoch, sim scenario entry).  The
    ledger is REBOUND, not cleared in place, so a ``save_state``
    capture survives."""
    global _state
    enabled = _state.enabled
    _state = DeviceTelemetry()
    _state.enabled = enabled
