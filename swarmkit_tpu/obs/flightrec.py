"""Black-box flight recorder: bounded ring buffers of recent control-
plane activity, dumpable as one post-mortem JSON.

Motivation (round-5 verdict): identical code swung 17x between bench
artifacts and sim invariant failures reported a verdict with no
surrounding state — the noise was *inferred*, never *observed*.  The
recorder keeps the last-N of everything cheap to capture continuously:

* **spans** — tapped from the PR-2 tracer via its ``sink`` hook (every
  ended span lands here even after the tracer's own buffer fills);
* **samples** — periodic registry snapshots recorded by
  ``obs/sampler.py`` (counter/timer-count deltas since ``rebase()``);
* **store events** — a block-aware subscription on a MemoryStore's
  watch queue, summarized to (action, kind, id, state) tuples;
* **raft transitions** — every ``RaftCore`` role change
  (follower/candidate/leader + term), via the core's ``on_transition``
  hook;
* **notes** — free-form marks (invariant violations, health
  transitions, fault injections).

Every record is stamped through ``models.types.now()`` — under the
simulator's VirtualClock a dump is a pure function of (scenario, seed),
byte for byte, which is what makes a post-mortem from a failing seed
*evidence* rather than anecdote (asserted in tests/test_flightrec.py).

Dump triggers: ``/debug/flightrec`` on the DebugServer (on demand),
``sim.scenario.run_scenario`` (automatically on invariant violation or
crashed-scenario exit; path + sha land in the report), and ``bench.py``
(when a trial trips the variance guard).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from ..models import types as _types

log = logging.getLogger("flightrec")


class Ring:
    """Bounded append-only buffer; evictions are counted, not silent."""

    __slots__ = ("_buf", "dropped")

    def __init__(self, maxlen: int):
        self._buf: deque = deque(maxlen=maxlen)
        self.dropped = 0

    def append(self, item: Any) -> None:
        buf = self._buf
        if len(buf) == buf.maxlen:
            # approximate under concurrent appends (no lock on the hot
            # path); exact in the single-threaded simulator
            self.dropped += 1
        buf.append(item)

    def items(self) -> List[Any]:
        return list(self._buf)

    def clear(self) -> None:
        self._buf.clear()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._buf)


class FlightRecorder:
    """Always-on black box.  Enable/disable is one attribute check on
    every record path, so an idle recorder costs nothing measurable."""

    def __init__(self, max_spans: int = 4096, max_samples: int = 512,
                 max_store_events: int = 4096, max_raft: int = 1024,
                 max_notes: int = 1024):
        self.enabled = False
        #: True while a deterministic capture (the simulator) owns the
        #: recorder: dumps omit anything wall-clock-tainted (live
        #: registry totals) so the sha is a pure function of the seed
        self.deterministic = False
        self._maxlens = (max_spans, max_samples, max_store_events,
                         max_raft, max_notes)
        self._fresh_rings()
        self._lock = threading.Lock()
        # store taps: queue id -> (queue, subscription).  A dict, not a
        # single slot, so two managers in one process (HA tests) can
        # each tap their own store without stealing the other's.
        self._store_subs: Dict[int, tuple] = {}
        #: optional per-raw-event tap (obs.journey.JourneyLedger
        #: .handle_event): the journey ledger rides the SAME store
        #: subscriptions instead of adding its own, so the watch plane
        #: pays one consumer for both
        self.journey_sink = None

    def _fresh_rings(self) -> None:
        (max_spans, max_samples, max_store_events, max_raft,
         max_notes) = self._maxlens
        self.spans = Ring(max_spans)
        self.samples = Ring(max_samples)
        self.store_events = Ring(max_store_events)
        self.raft = Ring(max_raft)
        self.notes = Ring(max_notes)

    # ------------------------------------------------------------- recording

    def record_span(self, sp) -> None:
        """Tracer sink callback (obs.trace.Tracer.sink): one compact row
        per ended span, kept even after the tracer's buffer fills."""
        if not self.enabled:
            return
        self.spans.append((sp.name, sp.cat, sp.start, sp.end,
                           sp.span_id, sp.parent_id))

    def record_sample(self, sample: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        self.samples.append(sample)

    def record_raft(self, member_id: str, role: str, term: int) -> None:
        if not self.enabled:
            return
        self.raft.append((_types.now(), member_id, role, term))

    def note(self, msg: str) -> None:
        if not self.enabled:
            return
        self.notes.append((_types.now(), msg))

    # ---------------------------------------------------------- store events

    def watch_store(self, store) -> None:
        """Subscribe to a MemoryStore's watch queue (block-aware).  The
        subscription buffers until ``poll_store`` drains it — call that
        from the sampler tick (production) or the engine (sim); a dump
        drains implicitly.  Idempotent per store; independent stores can
        be tapped concurrently."""
        q = store.queue
        if id(q) not in self._store_subs:
            self._store_subs[id(q)] = (
                q, q.subscribe(accepts_blocks=True))
        # watch-plane saturation probe: the recorder's taps are the
        # canonical store consumers, so their summed backlog (in store
        # versions — Subscription.backlog counts block expansions) is
        # the consumer plane's lag.  Registered here, not in state/
        # watch.py: the state layer must not import obs (layering rule).
        from . import planes as _planes
        _planes.plane(_planes.WATCH).set_probe(self._watch_backlog)

    def _watch_backlog(self) -> Dict[str, float]:
        depth = 0.0
        for _q, sub in list(self._store_subs.values()):
            try:
                depth += float(sub.backlog())
            except Exception:
                pass
        return {"depth": depth}

    def unwatch_store(self, store=None) -> None:
        """Detach a store tap — only ``store``'s when given (a stopping
        manager must not tear down another manager's tap), every tap
        when called bare."""
        if store is not None:
            entries = [self._store_subs.pop(id(store.queue), None)]
        else:
            entries = list(self._store_subs.values())
            self._store_subs.clear()
        for entry in entries:
            if entry is None:
                continue
            q, sub = entry
            try:
                q.unsubscribe(sub)
            except Exception:
                pass

    def poll_store(self) -> int:
        """Drain every store subscription into the ring; returns how
        many rows were recorded."""
        t = _types.now()
        n = 0
        sink = self.journey_sink
        for q, sub in list(self._store_subs.values()):
            while True:
                ev = sub.poll()
                if ev is None:
                    break
                if sink is not None:
                    try:
                        sink(ev)
                    except Exception:
                        log.exception("journey sink failed")
                row = self._summarize_event(t, ev)
                if row is not None and self.enabled:
                    self.store_events.append(row)
                    n += 1
        return n

    @staticmethod
    def _summarize_event(t: float, ev) -> Optional[tuple]:
        from ..state.events import Event, EventSnapshotRestore, \
            EventTaskBlock
        if isinstance(ev, EventTaskBlock):
            return (t, "task_block", "", int(ev.state), len(ev))
        if isinstance(ev, EventSnapshotRestore):
            return (t, "snapshot_restore", "", 0, 0)
        if isinstance(ev, Event):
            obj = ev.obj
            state = getattr(getattr(obj, "status", None), "state", 0)
            return (t, f"{ev.action} {type(obj).__name__.lower()}",
                    getattr(obj, "id", ""), int(state), 1)
        return None   # EventCommit / WAKE: too chatty to record

    # ------------------------------------------------------------- lifecycle

    def reset(self, deterministic: bool = False) -> None:
        """Start a fresh capture.  Rings are REBOUND, not cleared in
        place, so a state captured by ``save_state`` before the reset
        survives (same contract as Tracer.reset/save_state)."""
        with self._lock:
            self._fresh_rings()
            self.deterministic = deterministic

    def save_state(self):
        """Capture rings + flags + taps so an embedded recording session
        (the sim runner) can restore the embedding process's black box
        afterwards."""
        with self._lock:
            return (self.spans, self.samples, self.store_events,
                    self.raft, self.notes, self.enabled,
                    self.deterministic, dict(self._store_subs),
                    self.journey_sink)

    def restore_state(self, state) -> None:
        with self._lock:
            (self.spans, self.samples, self.store_events, self.raft,
             self.notes, self.enabled, self.deterministic,
             self._store_subs, self.journey_sink) = state

    # ----------------------------------------------------------------- dump

    def snapshot(self) -> Dict[str, Any]:
        """One post-mortem document.  Deterministic captures carry only
        seed-derived content; live captures additionally embed the
        current registry counters so a dump stands alone."""
        self.poll_store()
        with self._lock:
            doc: Dict[str, Any] = {
                "spans": [list(r) for r in self.spans.items()],
                "samples": self.samples.items(),
                "store_events": [list(r) for r in
                                 self.store_events.items()],
                "raft_transitions": [list(r) for r in self.raft.items()],
                "notes": [list(r) for r in self.notes.items()],
                "dropped": {
                    "spans": self.spans.dropped,
                    "samples": self.samples.dropped,
                    "store_events": self.store_events.dropped,
                    "raft_transitions": self.raft.dropped,
                    "notes": self.notes.dropped,
                },
            }
        if not self.deterministic:
            from ..utils.metrics import registry
            doc["counters"] = dict(sorted(
                registry.counters_snapshot().items()))
            # device-telemetry + compile-cache snapshot at dump time: a
            # post-mortem must distinguish a recompile storm from a
            # transfer storm without a second capture.  Omitted from
            # deterministic (sim) captures with the registry counters —
            # its ns fields are wall-clock-tainted.
            from . import devicetelemetry as _devtel
            doc["device_telemetry"] = _devtel.snapshot()
        # full journeys of invariant-implicated tasks: a violation note
        # naming a sampled task id gets that task's complete milestone
        # ledger in the post-mortem, so "task X stuck" arrives WITH
        # where in the pipeline it stuck.  Seed-pure in deterministic
        # captures (notes and milestones both are).
        ledger = getattr(self.journey_sink, "__self__", None)
        if ledger is not None and hasattr(ledger, "journeys"):
            viol = [str(m) for _t, m in doc["notes"]
                    if str(m).startswith("INVARIANT")]
            if viol:
                imp = {tid: ms
                       for tid, ms in ledger.journeys().items()
                       if any(tid in n for n in viol)}
                if imp:
                    doc["implicated_journeys"] = imp
        return doc

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":"))

    def dump(self, path: str) -> str:
        """Write the post-mortem JSON; returns its sha256 (the identity
        sim reports record next to the artifact path)."""
        body = self.dump_json()
        with open(path, "w") as f:
            f.write(body)
        return hashlib.sha256(body.encode()).hexdigest()


# the process-wide recorder; obs.trace installs it as the tracer sink
flightrec = FlightRecorder()


# --------------------------------------------------------- crash hook
#
# Control-loop threads (scheduler, orchestrators, dispatcher worker,
# the raft loop...) are daemon threads: an unhandled exception kills the
# thread silently and the manager limps on without it.  The crash hook
# turns that into evidence — the black box is dumped as a post-mortem
# (path + sha logged) BEFORE the thread dies, with the crash itself as
# the final note.  Installed by Manager.run, removed by Manager.stop;
# ref-counted so co-resident managers (HA tests) compose.

_crash_hook_lock = threading.Lock()
_crash_hook_refs = 0
_prev_excepthook = None
_crash_seq = 0


def _crash_dump(thread_name: str, exc_type, exc_value) -> None:
    global _crash_seq
    if not flightrec.enabled:
        return
    flightrec.note(f"thread {thread_name!r} crashed: "
                   f"{exc_type.__name__}: {exc_value}")
    safe = "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in thread_name) or "thread"
    d = os.environ.get("SWARM_FLIGHTREC_DIR") or "."
    with _crash_hook_lock:
        _crash_seq += 1
        seq = _crash_seq
    path = os.path.join(
        d, f"flightrec_crash_{safe}_{os.getpid()}_{seq}.json")
    try:
        sha = flightrec.dump(path)
    except OSError:
        log.exception("crash post-mortem dump failed")
        return
    log.error("thread %r died with %s; flight-recorder post-mortem "
              "dumped to %s (sha256 %s)", thread_name,
              exc_type.__name__, path, sha)


def _crash_excepthook(args) -> None:
    try:
        if args.exc_type is not SystemExit:
            _crash_dump(getattr(args.thread, "name", None) or "unknown",
                        args.exc_type, args.exc_value)
    except Exception:
        log.exception("flightrec crash hook failed")
    finally:
        prev = _prev_excepthook or threading.__excepthook__
        prev(args)


def install_crash_hook() -> None:
    """Route ``threading.excepthook`` through the flight recorder
    (chained: the previous hook still prints the traceback)."""
    global _crash_hook_refs, _prev_excepthook
    with _crash_hook_lock:
        _crash_hook_refs += 1
        if _crash_hook_refs == 1:
            _prev_excepthook = threading.excepthook
            threading.excepthook = _crash_excepthook


def uninstall_crash_hook() -> None:
    global _crash_hook_refs, _prev_excepthook
    with _crash_hook_lock:
        if _crash_hook_refs == 0:
            return
        _crash_hook_refs -= 1
        if _crash_hook_refs == 0 \
                and threading.excepthook is _crash_excepthook:
            threading.excepthook = \
                _prev_excepthook or threading.__excepthook__
            _prev_excepthook = None
