"""Declarative SLO/health evaluator over the metrics registry.

Each check reads a live signal (timer quantile, counter ratio) and
compares it against warn/fail thresholds; the evaluator tracks per-check
state transitions (pass -> warn -> fail -> recover), exports every state
as a ``swarm_health{check="..."}`` gauge (0=pass, 1=warn, 2=fail), and
notes every transition into the flight recorder so a post-mortem shows
*when* a signal degraded, not just that it did.

``/debug/health`` (utils/httpdebug) serves ``report()`` — pass/warn/fail
per check plus the offending sample window from the flight recorder's
time series — and returns HTTP 503 while any check is failing, so
load-balancer/probe consumers need no JSON parsing.

Checks with no data (a timer never observed, a counter never
incremented) report ``pass`` with ``value: null`` — a fresh manager is
healthy, not unknown-unhealthy.  Thresholds are constructor arguments;
the defaults are sized for the production-shape bench (100k-task ticks
well under a second of p99 budget).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..models import types as _types
from ..utils.metrics import Registry
from ..utils.metrics import registry as _default_registry
from .flightrec import FlightRecorder, flightrec

PASS, WARN, FAIL = "pass", "warn", "fail"
_STATE_VALUE = {PASS: 0, WARN: 1, FAIL: 2}


@dataclass
class Check:
    name: str
    value: Callable[[Registry], Optional[float]]
    warn: float
    fail: float
    unit: str = ""
    #: sampler-row key prefixes relevant to this check — report() uses
    #: them to attach the offending sample window from the recorder
    window_prefixes: Tuple[str, ...] = field(default_factory=tuple)

    def judge(self, v: Optional[float]) -> str:
        if v is None:
            return PASS
        if v >= self.fail:
            return FAIL
        if v >= self.warn:
            return WARN
        return PASS


# --------------------------------------------------------- value accessors

def timer_p99(name: str) -> Callable[[Registry], Optional[float]]:
    def get(reg: Registry) -> Optional[float]:
        t = reg.get_timer(name)
        if t is None or t.count == 0:
            return None
        return t.quantiles()[0.99]
    return get


def counter_ratio(numerator: str, denominators: Tuple[str, ...]
                  ) -> Callable[[Registry], Optional[float]]:
    """numerator / sum(denominators), None while the denominator is 0."""
    def get(reg: Registry) -> Optional[float]:
        total = sum(reg.get_counter(d) for d in denominators)
        if total <= 0:
            return None
        return reg.get_counter(numerator) / total
    return get


def gauge_value(name: str) -> Callable[[Registry], Optional[float]]:
    """Latest value of a gauge; None (pass) until first export."""
    def get(reg: Registry) -> Optional[float]:
        return reg.get_gauge(name)
    return get


_ROUTES = tuple(f'swarm_planner_groups{{route="{r}"}}'
                for r in ("device", "fallback", "host_small", "spill",
                          "breaker"))

_STATE_PREFIX = 'swarm_update_state{service="'


def stuck_rollout_value() -> Callable[[Registry], Optional[float]]:
    """Worst rollout condition across services: 0 = every rollout is
    progressing (pass), 1 = a rollout sits PAUSED / ROLLBACK_PAUSED
    after tripping its failure threshold (warn — operator attention,
    not an outage), 2 = an ACTIVE rollout has stamped no forward
    progress for longer than its own monitor window (fail — stuck, the
    supervisor should have either advanced a slot or declared a
    verdict by now).  None (pass) until a first update exports state.

    Reads the gauges orchestrator/update.py exports on every committed
    status write and slot completion: ``swarm_update_state{service=}``,
    ``swarm_update_last_progress{service=}`` (progress stamp) and
    ``swarm_update_monitor{service=}`` (per-rollout window)."""
    from ..models.types import UpdateState
    active = (float(UpdateState.UPDATING),
              float(UpdateState.ROLLBACK_STARTED))
    paused = (float(UpdateState.PAUSED),
              float(UpdateState.ROLLBACK_PAUSED))

    def get(reg: Registry) -> Optional[float]:
        states = reg.gauges_snapshot(_STATE_PREFIX)
        if not states:
            return None
        worst = 0.0
        t = _types.now()
        for name, state in states.items():
            svc = name[len(_STATE_PREFIX):-len('"}')]
            if state in paused:
                worst = max(worst, 1.0)
            elif state in active:
                last = reg.get_gauge(
                    f'swarm_update_last_progress{{service="{svc}"}}')
                monitor = reg.get_gauge(
                    f'swarm_update_monitor{{service="{svc}"}}')
                if last is not None and monitor is not None \
                        and t - last > monitor:
                    worst = max(worst, 2.0)
        return worst
    return get


def stale_read_risk_value(read_index_p99_bound: float = 2.0
                          ) -> Callable[[Registry], Optional[float]]:
    """Follower-served read plane risk: 2 (fail) the moment ANY stale
    serve was counted (``swarm_stale_reads`` — the invariant-adjacent
    counter the read barrier/lease checks increment when a view would
    have been served behind the committed frontier; correct operation
    keeps it at zero forever), 1 (warn) while lease reads are not being
    served (``swarm_lease_enabled`` = 0: the latest barrier fell back to
    a quorum round — clock-skew veto, lease churn, or no leader lease)
    AND the read-index fallback's p99 is above bound — reads are safe
    but every one pays a quorum round.  None (pass) until the read
    plane exports its first signal."""
    def get(reg: Registry) -> Optional[float]:
        if reg.get_counter("swarm_stale_reads") > 0:
            return 2.0
        lease = reg.get_gauge("swarm_lease_enabled")
        t = reg.get_timer("swarm_read_index_latency")
        if lease is None and (t is None or t.count == 0):
            return None
        if lease == 0.0 and t is not None and t.count \
                and t.quantiles()[0.99] > read_index_p99_bound:
            return 1.0
        return 0.0
    return get


_FLAP_PREFIX = 'swarm_autoscale_flapping{service="'
_OOB_PREFIX = 'swarm_autoscale_out_of_bounds{service="'


def autoscale_flapping_value() -> Callable[[Registry], Optional[float]]:
    """Autoscaler condition across services: 2 (fail) when any
    autoscaled service's replicas sit outside its [min, max] bounds —
    the loop wrote (or inherited) an out-of-policy state; 1 (warn)
    while any service's flap breaker is engaged — the policy froze
    itself after too many direction reversals and needs operator
    attention (or a better target); 0 otherwise.  None (pass) until a
    supervisor exports its first gauge.  Reads the gauges
    orchestrator/autoscaler.py exports on every drive."""
    def get(reg: Registry) -> Optional[float]:
        flaps = reg.gauges_snapshot(_FLAP_PREFIX)
        oob = reg.gauges_snapshot(_OOB_PREFIX)
        if not flaps and not oob:
            return None
        if any(v for v in oob.values()):
            return 2.0
        if any(v for v in flaps.values()):
            return 1.0
        return 0.0
    return get


def plane_saturation_value(plane_name: str, occ_warn: float = 0.85,
                           age_n: int = 4, age_floor: float = 0.5
                           ) -> Callable[[Registry], Optional[float]]:
    """Saturation condition for one serving plane (obs/planes.py): 1
    (warn) while the rolled occupancy sits at/above ``occ_warn`` — the
    plane is near its capacity ceiling; 2 (fail) when the plane's
    oldest-item age grew STRICTLY monotonically across the last
    ``age_n`` evaluations and is above ``age_floor`` — the backlog is
    unbounded, work is aging out faster than the plane drains it.
    None (pass) until the plane exports its first gauges (a fresh
    manager with zero observations is healthy, not unknown)."""
    occ_name = f'swarm_plane_occupancy{{plane="{plane_name}"}}'
    age_name = f'swarm_plane_oldest_age_s{{plane="{plane_name}"}}'
    history: deque = deque(maxlen=age_n)

    def get(reg: Registry) -> Optional[float]:
        occ = reg.get_gauge(occ_name)
        age = reg.get_gauge(age_name)
        if occ is None and age is None:
            return None
        if age is not None:
            history.append(age)
        if len(history) == age_n and history[-1] >= age_floor \
                and all(b > a for a, b in
                        zip(history, list(history)[1:])):
            return 2.0
        if occ is not None and occ >= occ_warn:
            return 1.0
        return 0.0
    return get


def apply_lag_value(warn_entries: float = 256.0, n: int = 4
                    ) -> Callable[[Registry], Optional[float]]:
    """Raft apply-plane lag (commit_index - applied_index, exported as
    the ``raft_apply`` plane's queue depth): 1 (warn) at/above
    ``warn_entries`` — the committer is behind but may be catching up;
    2 (fail) when the lag is over the bar AND grew strictly across the
    last ``n`` evaluations — a stalled committer, the backlog can only
    grow.  None (pass) before the raft plane exports."""
    name = 'swarm_plane_queue_depth{plane="raft_apply"}'
    history: deque = deque(maxlen=n)

    def get(reg: Registry) -> Optional[float]:
        lag = reg.get_gauge(name)
        if lag is None:
            return None
        history.append(lag)
        if len(history) == n and lag >= warn_entries \
                and all(b > a for a, b in
                        zip(history, list(history)[1:])):
            return 2.0
        if lag >= warn_entries:
            return 1.0
        return 0.0
    return get


def dispatcher_overload_value(n: int = 4
                              ) -> Callable[[Registry], Optional[float]]:
    """Dispatcher backpressure condition: 1 (warn) while admission
    sheds are actively being counted (``swarm_dispatcher_sheds`` grew
    since the last evaluation — the edge is rejecting work, clients are
    re-queuing under backoff); 2 (fail) when sheds grew STRICTLY across
    the last ``n`` evaluations — sustained overload, load is not
    subsiding and degraded service is the steady state.  None (pass)
    until the dispatcher exports its first overload signal."""
    history: deque = deque(maxlen=n)

    def get(reg: Registry) -> Optional[float]:
        sheds = reg.get_counter("swarm_dispatcher_sheds")
        if sheds <= 0 \
                and reg.get_gauge("swarm_dispatcher_pending_updates") \
                is None:
            return None
        history.append(sheds)
        if len(history) == n and all(b > a for a, b in
                                     zip(history, list(history)[1:])):
            return 2.0
        if len(history) >= 2 and history[-1] > history[-2]:
            return 1.0
        return 0.0
    return get


def heartbeat_stretch_value(stretch_warn: float = 2.0
                            ) -> Callable[[Registry], Optional[float]]:
    """Heartbeat-stretch condition: 2 (fail) the moment ANY premature
    expiration is counted (``swarm_dispatcher_premature_expirations`` —
    a node marked DOWN inside the window the dispatcher PROMISED it;
    correct stretching keeps it at zero forever, the
    heartbeat-liveness-under-stretch invariant in live form); 1 (warn)
    while the advertised stretch factor is at/over ``stretch_warn`` —
    agents have been told to slow down materially, the session plane is
    loaded.  None (pass) until the stretch plane exports."""
    def get(reg: Registry) -> Optional[float]:
        if reg.get_counter("swarm_dispatcher_premature_expirations") > 0:
            return 2.0
        s = reg.get_gauge("swarm_dispatcher_hb_stretch")
        if s is None \
                and reg.get_counter("swarm_dispatcher_hb_stretches") <= 0:
            return None
        if s is not None and s >= stretch_warn:
            return 1.0
        return 0.0
    return get


def default_checks(tick_warn: float = 5.0, tick_fail: float = 30.0,
                   edge_warn: float = 10.0, edge_fail: float = 60.0,
                   fallback_warn: float = 0.1, fallback_fail: float = 0.5,
                   propose_warn: float = 2.0, propose_fail: float = 10.0,
                   hb_warn: float = 0.05, hb_fail: float = 0.25
                   ) -> List[Check]:
    return [
        Check("tick_p99", timer_p99("swarm_scheduler_tick_latency"),
              tick_warn, tick_fail, "s",
              ("swarm_scheduler_",)),
        Check("lifecycle_assign_p99",
              timer_p99('swarm_task_lifecycle'
                        '{from="pending",to="assigned"}'),
              edge_warn, edge_fail, "s",
              ("swarm_task_lifecycle",)),
        Check("planner_fallback_rate",
              counter_ratio('swarm_planner_groups{route="fallback"}',
                            _ROUTES),
              fallback_warn, fallback_fail, "ratio",
              ("swarm_planner_",)),
        Check("raft_propose_p99", timer_p99("swarm_raft_propose_latency"),
              propose_warn, propose_fail, "s",
              ("swarm_raft_",)),
        Check("heartbeat_miss_rate",
              counter_ratio("swarm_dispatcher_heartbeat_expirations",
                            ("swarm_dispatcher_heartbeats",)),
              hb_warn, hb_fail, "ratio",
              ("swarm_dispatcher_heartbeat",)),
        # device-path circuit breaker (ops/planner.py PlannerBreaker):
        # 0=closed (pass), 1=half-open probing (warn), 2=open — every
        # group on host fallback (fail).  Degraded throughput, not an
        # outage: placements stay valid, so this is the check that says
        # "the device is sick", not "the manager is down".
        Check("planner_breaker",
              gauge_value("swarm_planner_breaker_state"),
              1.0, 2.0, "state",
              ("swarm_planner_",)),
        # rolling updates (orchestrator/update.py): 1 = paused at the
        # failure threshold (warn), 2 = an active rollout stopped
        # making progress past its monitor window (fail)
        Check("stuck_rollout", stuck_rollout_value(),
              1.0, 2.0, "state",
              ("swarm_update_",)),
        # priority inversions (scheduler/preempt.py): pending positive-
        # priority tasks still unplaced after the preemption pass while
        # lower-priority work holds capacity — warn on the first one
        # (budget/cooldown may legitimately defer a tick or two), fail
        # when the important band is piling up behind the cheap one
        Check("priority_inversion",
              gauge_value("swarm_priority_inversion"),
              1.0, 8.0, "tasks",
              ("swarm_priority_", "swarm_preempt")),
        # follower-served reads (state/raft read-index + leader lease):
        # fail = a stale serve was ever counted (safety breach — the
        # read plane served behind the committed frontier), warn = lease
        # disabled AND the read-index fallback is slow (every read pays
        # a quorum round)
        Check("stale_read_risk", stale_read_risk_value(),
              1.0, 2.0, "state",
              ("swarm_read_", "swarm_lease_", "swarm_stale_",
               "swarm_leader_read_")),
        # autoscaler (orchestrator/autoscaler.py): 1 = a flap breaker is
        # engaged (policy frozen after direction reversals), 2 = an
        # autoscaled service's replicas are outside [min, max]
        Check("autoscale_flapping", autoscale_flapping_value(),
              1.0, 2.0, "state",
              ("swarm_autoscale_", "swarm_tenant_quota_")),
        # per-plane saturation (obs/planes.py, ISSUE 17): 1 = the
        # scheduler plane's tick occupancy is sustained at/over 85%,
        # 2 = its pending-backlog age grows without bound
        Check("scheduler_occupancy", plane_saturation_value("scheduler"),
              1.0, 2.0, "state",
              ("swarm_plane_", "swarm_scheduler_")),
        # raft apply plane: 1 = apply lag over the entry bar, 2 = a
        # stalled committer (lag over the bar and strictly growing)
        Check("apply_lag", apply_lag_value(),
              1.0, 2.0, "state",
              ("swarm_plane_", "swarm_raft_")),
        # dispatcher backpressure (manager/dispatcher.py overload
        # plane): 1 = admission sheds actively counted, 2 = sheds
        # growing strictly across evaluations (sustained overload)
        Check("dispatcher_overload", dispatcher_overload_value(),
              1.0, 2.0, "state",
              ("swarm_dispatcher_", "swarm_plane_")),
        # heartbeat stretching: 1 = agents told to slow down >= 2x,
        # 2 = a node was DOWNed inside its promised window (liveness
        # breach — the stretch the expiry deadline forgot)
        Check("heartbeat_stretch", heartbeat_stretch_value(),
              1.0, 2.0, "state",
              ("swarm_dispatcher_h",)),
    ]


class HealthEvaluator:
    def __init__(self, registry: Optional[Registry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 checks: Optional[List[Check]] = None):
        self.registry = registry or _default_registry
        self.recorder = recorder or flightrec
        self.checks = checks if checks is not None else default_checks()
        self._state: Dict[str, str] = {}
        self._value: Dict[str, Optional[float]] = {}
        #: (t, check, old_state, new_state) history — a deque keeps the
        #: NEWEST entries when it fills (the recent degradation is the
        #: evidence /debug/health exists for, not the oldest one)
        self.transitions: deque = deque(maxlen=256)

    # ------------------------------------------------------------ evaluating

    def evaluate(self) -> Dict[str, str]:
        """Run every check once; returns {check: state}.  Exports
        ``swarm_health{check=...}`` gauges and notes state changes to
        the flight recorder."""
        t = _types.now()
        out: Dict[str, str] = {}
        for c in self.checks:
            try:
                v = c.value(self.registry)
            except Exception:
                v = None
            state = c.judge(v)
            prev = self._state.get(c.name, PASS)
            if state != prev:
                self.transitions.append((t, c.name, prev, state))
                self.recorder.note(
                    f"health {c.name}: {prev} -> {state}"
                    f" (value={v!r} warn={c.warn} fail={c.fail})")
            self._state[c.name] = state
            self._value[c.name] = v
            self.registry.gauge(f'swarm_health{{check="{c.name}"}}',
                                _STATE_VALUE[state])
            out[c.name] = state
        return out

    def failing(self) -> bool:
        return FAIL in self._state.values()

    def status(self) -> str:
        states = self._state.values()
        if FAIL in states:
            return FAIL
        if WARN in states:
            return WARN
        return PASS

    # --------------------------------------------------------------- report

    def _window(self, prefixes: Tuple[str, ...], n: int = 10) -> list:
        """The offending sample window: the recorder's most recent rows
        trimmed to this check's metric families."""
        rows = []
        for row in self.recorder.samples.items()[-n:]:
            keep = {}
            for section in ("counters", "timer_counts", "timer_totals",
                            "gauges"):
                vals = row.get(section) or {}
                hit = {k: v for k, v in vals.items()
                       if any(k.startswith(p) for p in prefixes)}
                if hit:
                    keep[section] = hit
            if keep:
                keep["t"] = row.get("t")
                rows.append(keep)
        return rows

    def report(self) -> Dict[str, object]:
        self.evaluate()
        checks = {}
        for c in self.checks:
            state = self._state[c.name]
            entry: Dict[str, object] = {
                "state": state,
                "value": self._value[c.name],
                "warn": c.warn, "fail": c.fail, "unit": c.unit,
            }
            if state != PASS:
                entry["window"] = self._window(c.window_prefixes)
            checks[c.name] = entry
        return {
            "status": self.status(),
            "checks": checks,
            "transitions": [
                {"t": t, "check": name, "from": a, "to": b}
                for t, name, a, b in list(self.transitions)[-32:]],
        }


# the default evaluator /debug/health and the Manager share
evaluator = HealthEvaluator()
