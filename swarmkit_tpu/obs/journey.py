"""Causal per-task journeys: a bounded, sampled milestone ledger.

A *journey* is the ordered milestone record of one task's path to
RUNNING::

    created -> admitted -> planned -> committed -> assigned_sent
            -> agent_ack -> running

Every milestone except ``assigned_sent`` is minted from REPLICATED
store state — the stamped ``status.timestamp`` of the watch event's
task (``meta.created_at`` for creation) plus the store's version token
(``state.events.event_version``) — never from observation time.  Both
are identical on every member: the leader and a follower watching the
same committed changes mint byte-identical milestones, which is what
makes a journey survive leader failover *stitched* (the successor's
events dedup against the milestones the deposed leader already
produced) rather than truncated.  ``assigned_sent`` is the one
leader-local milestone: the dispatcher's fan-out stamps it at send
time through ``models.types.now()`` — deterministic under the sim's
virtual clock, absent on members that never served the session (edges
simply skip missing milestones).

Sampling is deterministic and PYTHONHASHSEED-independent:
``zlib.crc32(task_id)`` against ``sample_rate`` decides admission (the
same task is sampled on every member), and a hard cap
(``JOURNEY_CAP``, SERVICE_TIMER_CAP-style) bounds memory at O(sample)
whatever the cluster size; refusals are counted, never silent.

``critical_path()`` is the attribution join: over the slowest
time-to-running cohort it splits each journey into per-edge durations,
charges each edge to the later milestone's owning plane, and
normalizes — "62% scheduler, 21% dispatcher, …".  The
``planned -> committed`` edge is zero-width today (both ride the same
replicated stamp; the version token still records the commit) so the
commit plane's share surfaces through the plane-occupancy windows
(obs/planes.py) that ``scripts/trace_report.py --critical-path``
prints alongside.
"""

from __future__ import annotations

import json
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..models import types as _types
from ..models.objects import Task
from ..models.types import TaskState
from ..state.events import (
    Event, EventSnapshotRestore, EventTaskBlock, event_version,
)

#: hard cap on distinct sampled tasks (SERVICE_TIMER_CAP discipline):
#: beyond it new tasks are refused and counted on ``overflow`` — a
#: million-task tick costs O(cap), not O(tasks)
JOURNEY_CAP = 4096

#: milestone grammar: name -> (order, owning plane).  An edge between
#: consecutive present milestones is charged to the LATER one's plane.
MILESTONES: Dict[str, Tuple[int, str]] = {
    "created": (0, "api"),
    "admitted": (1, "orchestrator"),
    "planned": (2, "scheduler"),
    "committed": (3, "commit"),
    "assigned_sent": (4, "dispatcher"),
    "agent_ack": (5, "agent"),
    "running": (6, "agent"),
}

_STATE_MILESTONE = {
    int(TaskState.PENDING): "admitted",
    int(TaskState.ACCEPTED): "agent_ack",
    int(TaskState.RUNNING): "running",
}


def _sampled(task_id: str, rate: float) -> bool:
    """Deterministic, hash-order-independent admission: the crc32 of
    the task id against ``rate`` — NOT ``hash()``, which varies with
    PYTHONHASHSEED and would sample different tasks per process."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(task_id.encode()) & 0xFFFFFFFF) < rate * 2**32


class JourneyLedger:
    """Bounded milestone ledger.  Enable/disable is one attribute check
    per event; disabled it costs nothing measurable (the Tracer
    contract)."""

    def __init__(self, sample_rate: float = 1.0, cap: int = JOURNEY_CAP):
        self.enabled = False
        self.sample_rate = sample_rate
        self.cap = cap
        self._mu = threading.Lock()
        # task_id -> {milestone: (ts, version)}
        self._tasks: Dict[str, Dict[str, Tuple[float, int]]] = {}
        self.overflow = 0
        self.refused = 0   # rate-rejected sightings (distinct events)

    # ------------------------------------------------------------- recording

    def _admit(self, task_id: str) -> Optional[Dict]:
        """The task's milestone map, or None when sampled out / over
        cap.  Caller holds no lock."""
        with self._mu:
            m = self._tasks.get(task_id)
            if m is not None:
                return m
            if not _sampled(task_id, self.sample_rate):
                self.refused += 1
                return None
            if len(self._tasks) >= self.cap:
                self.overflow += 1
                return None
            m = self._tasks[task_id] = {}
            return m

    def _mark(self, task_id: str, milestone: str, ts: float,
              version: int = 0) -> None:
        m = self._admit(task_id)
        if m is None or milestone in m:
            return   # dedup: replicated stamps make re-sightings
        #          (other members, post-failover replays) idempotent
        m[milestone] = (float(ts), int(version))

    def note_sent(self, task_id: str, ts: Optional[float] = None) -> None:
        """Dispatcher fan-out milestone (the one leader-local stamp):
        the assignment left the manager for the agent's session."""
        if not self.enabled:
            return
        self._mark(task_id, "assigned_sent",
                   _types.now() if ts is None else ts)

    def observe_task(self, t, version: int = 0,
                     created: bool = False) -> None:
        """Mint the milestones one task sighting carries."""
        status = getattr(t, "status", None)
        if status is None:
            return
        state = int(status.state)
        ts = status.timestamp or 0.0
        if created:
            meta = getattr(t, "meta", None)
            created_at = meta.created_at if meta is not None else 0.0
            if created_at:
                self._mark(t.id, "created", created_at, version)
        if state == int(TaskState.ASSIGNED):
            # one replicated stamp carries both the plan decision and
            # the committed write; the version token is the commit's
            self._mark(t.id, "planned", ts, version)
            self._mark(t.id, "committed", ts, version)
            return
        name = _STATE_MILESTONE.get(state)
        if name is not None and ts:
            self._mark(t.id, name, ts, version)

    def handle_event(self, ev) -> None:
        """Watch-queue tap (flightrec.poll_store drives this in both
        production and the sim)."""
        if not self.enabled:
            return
        if isinstance(ev, EventTaskBlock):
            base, ts = ev.base_version, ev.ts
            for i, old in enumerate(ev.olds):
                self._mark(old.id, "planned", ts, base + 1 + i)
                self._mark(old.id, "committed", ts, base + 1 + i)
            return
        if isinstance(ev, EventSnapshotRestore):
            return   # journeys ride replicated stamps: nothing to drop
        if isinstance(ev, Event) and isinstance(ev.obj, Task):
            if ev.action == "delete":
                return
            self.observe_task(ev.obj, event_version(ev),
                              created=ev.action == "create")

    # --------------------------------------------------------------- reading

    def journeys(self) -> Dict[str, List[Tuple[str, float, int]]]:
        """task_id -> ordered [(milestone, ts, version), ...] —
        sorted by milestone order then task id, for stable output."""
        with self._mu:
            snap = {tid: dict(m) for tid, m in self._tasks.items()}
        out = {}
        for tid in sorted(snap):
            ms = snap[tid]
            out[tid] = [(name, ms[name][0], ms[name][1])
                        for name in sorted(ms,
                                           key=lambda n: MILESTONES[n][0])]
        return out

    def edges(self, milestones: List[Tuple[str, float, int]]
              ) -> List[Tuple[str, float, str]]:
        """Per-edge durations of one journey: [(edge, dt, plane)]
        between consecutive present milestones, charged to the later
        milestone's plane.  Clamped at 0 — a replicated stamp never
        runs backwards, but a leader-local ``assigned_sent`` under
        clock skew may."""
        out = []
        for (a, ta, _va), (b, tb, _vb) in zip(milestones, milestones[1:]):
            out.append((f"{a}->{b}", max(0.0, tb - ta), MILESTONES[b][1]))
        return out

    def critical_path(self, quantile: float = 0.99
                      ) -> Dict[str, object]:
        """Per-plane attribution of time-to-running at ``quantile``:
        take the slowest cohort of complete (created..running)
        journeys, sum each journey's per-edge durations by plane, and
        normalize.  The fractions sum to ~1.0 because the edges of one
        journey partition exactly its created->running interval."""
        complete = []
        for tid, ms in self.journeys().items():
            names = {name for name, _ts, _v in ms}
            if "created" in names and "running" in names:
                total = ms[-1][1] - ms[0][1]
                complete.append((tid, ms, max(0.0, total)))
        if not complete:
            return {"tasks": 0, "cohort": 0, "p": quantile,
                    "total_s": 0.0, "planes": {}}
        totals = sorted(t for _tid, _ms, t in complete)
        # nearest-rank quantile (utils.metrics.Timer discipline)
        idx = max(0, min(len(totals) - 1,
                         int(round(quantile * len(totals))) - 1))
        bar = totals[idx]
        cohort = [(tid, ms, t) for tid, ms, t in complete if t >= bar]
        by_plane: Dict[str, float] = {}
        grand = 0.0
        for _tid, ms, _t in cohort:
            for _edge, dt, plane in self.edges(ms):
                by_plane[plane] = by_plane.get(plane, 0.0) + dt
                grand += dt
        planes = {
            p: {"seconds": round(s, 9),
                "frac": round(s / grand, 6) if grand > 0 else 0.0}
            for p, s in sorted(by_plane.items())}
        # device sub-attribution for the ``planned`` milestone: the
        # scheduler plane's edge gains a NESTED breakdown (dispatch vs
        # d2h vs compile from the device-telemetry ledger) — nested,
        # not a sibling plane row, so per-plane fracs still sum to ~1.0
        # (the trace_report --critical-path invariant).
        sched_row = planes.get("scheduler")
        if sched_row is not None:
            from . import devicetelemetry as _devtel
            sub = _devtel.journey_sub_attribution(sched_row["seconds"])
            if sub:
                sched_row["device_sub"] = sub
        return {"tasks": len(complete), "cohort": len(cohort),
                "p": quantile, "total_s": round(grand, 9),
                "planes": planes}

    def summary(self) -> Dict[str, object]:
        with self._mu:
            n = len(self._tasks)
            complete = sum(1 for m in self._tasks.values()
                           if "created" in m and "running" in m)
            return {"sampled_tasks": n, "complete": complete,
                    "overflow": self.overflow, "refused": self.refused,
                    "cap": self.cap, "sample_rate": self.sample_rate}

    def journey_of(self, task_id: str
                   ) -> List[Tuple[str, float, int]]:
        """One task's milestones (empty when unsampled) — the flight
        recorder dumps these for invariant-implicated tasks."""
        with self._mu:
            ms = dict(self._tasks.get(task_id) or {})
        return [(name, ms[name][0], ms[name][1])
                for name in sorted(ms, key=lambda n: MILESTONES[n][0])]

    # ------------------------------------------------------------------ dump

    def dump(self) -> Dict[str, object]:
        return {"summary": self.summary(), "journeys": self.journeys()}

    def dump_bytes(self) -> bytes:
        """Canonical bytes: the byte-identity surface the sim's
        determinism assertions compare across seeds and re-runs."""
        return json.dumps(self.dump(), sort_keys=True,
                          separators=(",", ":")).encode()

    # ------------------------------------------------------------- lifecycle

    def reset(self, sample_rate: Optional[float] = None,
              cap: Optional[int] = None) -> None:
        with self._mu:
            self._tasks = {}
            self.overflow = 0
            self.refused = 0
            if sample_rate is not None:
                self.sample_rate = sample_rate
            if cap is not None:
                self.cap = cap

    def save_state(self):
        with self._mu:
            return (self._tasks, self.overflow, self.refused,
                    self.enabled, self.sample_rate, self.cap)

    def restore_state(self, state) -> None:
        with self._mu:
            (self._tasks, self.overflow, self.refused, self.enabled,
             self.sample_rate, self.cap) = state


# the process-wide ledger: the Manager, the sim runner, and bench all
# tap the same instance (flightrec.journey_sink feeds it store events)
journeys = JourneyLedger()
