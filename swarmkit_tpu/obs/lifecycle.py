"""Task-lifecycle latency tracker.

The reference instruments exactly one FSM edge — the dispatcher's
scheduling-delay timer (dispatcher.go:72-77, time from task creation to
the node receiving it).  This generalizes that to *every* forward edge of
the task FSM: created→pending, pending→assigned, assigned→accepted, …,
starting→running.  Each observed edge feeds a labeled registry timer

    swarm_task_lifecycle{from="pending",to="assigned"}

so ``/metrics`` exports per-edge p50/p90/p99, and ``summary()`` gives the
same numbers programmatically (bench/tests).

Latencies are computed from the *stamped* status timestamps (and
``meta.created_at`` for the creation edge), not from observation time —
so the numbers measure the control plane, not the watcher's queue, and
are deterministic under the simulator's virtual clock.

Use it two ways:

* passively — call ``handle_event(ev)`` from an existing event loop
  (the simulator, tests);
* actively — ``start()``/``stop()`` runs a store-subscribed thread like
  manager.metrics.Collector (the Manager wires this).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..models.objects import Task
from ..models.types import TERMINAL_STATES, TaskState
from ..state.events import Event, EventSnapshotRestore, EventTaskBlock
from ..state.watch import Closed
from ..utils.metrics import Registry
from ..utils.metrics import registry as _default_registry


def _edge_timer_name(frm: str, to: str) -> str:
    return f'swarm_task_lifecycle{{from="{frm}",to="{to}"}}'


def service_edge_timer_name(service_id: str) -> str:
    """Per-service pending->assigned timer (the autoscaler's
    ``target_p99`` signal — orchestrator/autoscaler.py reads it)."""
    return f'swarm_task_lifecycle_service{{service="{service_id}"}}'


#: bounded per-service timer cardinality: beyond this many distinct
#: services the per-service edge stops growing new timers (counted on
#: ``swarm_task_lifecycle_service_overflow``) — the global edge timer
#: keeps covering them, so no latency sample is ever lost
SERVICE_TIMER_CAP = 64


class LifecycleTracker:
    def __init__(self, store=None, registry: Optional[Registry] = None):
        self.store = store
        self.registry = registry or _default_registry
        self._mu = threading.Lock()
        # task id -> (state, stamped timestamp of that state)
        self._last: Dict[str, Tuple[int, float]] = {}
        # services with a per-service pending->assigned timer (bounded)
        self._svc_timers: Dict[str, None] = {}
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- observing

    def _observe_edge(self, from_state: int, to_state: int,
                      dt: float, service_id: str = "") -> None:
        frm = ("created" if from_state < 0
               else TaskState(from_state).name.lower())
        to = TaskState(to_state).name.lower()
        self.registry.timer(_edge_timer_name(frm, to)).observe(
            max(0.0, dt))
        # the scheduling-latency edge additionally feeds a per-service
        # timer (bounded cardinality) so per-service SLO policies — the
        # autoscaler's target_p99 — read their OWN signal instead of
        # the cluster-wide aggregate
        if (service_id
                and from_state == int(TaskState.PENDING)
                and to_state == int(TaskState.ASSIGNED)):
            if service_id not in self._svc_timers:
                if len(self._svc_timers) >= SERVICE_TIMER_CAP:
                    self.registry.counter(
                        "swarm_task_lifecycle_service_overflow")
                    return
                self._svc_timers[service_id] = None
            self.registry.timer(
                service_edge_timer_name(service_id)).observe(
                max(0.0, dt))

    def observe_task(self, t: Task, old: Optional[Task] = None) -> None:
        """Record the FSM edge a create/update event represents."""
        state = int(t.status.state)
        ts = t.status.timestamp or 0.0
        with self._mu:
            prev = self._last.get(t.id)
            if prev is None and old is not None:
                prev = (int(old.status.state), old.status.timestamp or 0.0)
            if prev is None:
                # first sighting: the creation edge, off meta.created_at
                created = t.meta.created_at if t.meta else 0.0
                if created and ts >= created:
                    self._observe_edge(-1, state, ts - created)
            elif state > prev[0]:
                if prev[1]:
                    self._observe_edge(prev[0], state, ts - prev[1],
                                       getattr(t, "service_id", ""))
            else:
                # same-state refresh or a backward write (never a forward
                # edge): keep the earlier stamp
                return
            if TaskState(state) in TERMINAL_STATES:
                self._last.pop(t.id, None)
            else:
                self._last[t.id] = (state, ts)

    def forget(self, task_id: str) -> None:
        with self._mu:
            self._last.pop(task_id, None)

    def handle_event(self, ev) -> None:
        if isinstance(ev, EventTaskBlock):
            # columnar assignment: N edges stamped with one shared ts
            for old in ev.olds:
                self.observe_task(_BlockView(old, ev.state, ev.ts), old)
            return
        if isinstance(ev, EventSnapshotRestore):
            with self._mu:
                self._last.clear()
            return
        if isinstance(ev, Event) and isinstance(ev.obj, Task):
            if ev.action == "delete":
                self.forget(ev.obj.id)
            else:
                self.observe_task(ev.obj, ev.old)

    # --------------------------------------------------------------- summary

    def summary(self) -> Dict[str, Dict[str, float]]:
        """{"pending->assigned": {"count": n, "p50": s, ...}, ...}"""
        out: Dict[str, Dict[str, float]] = {}
        prefix = "swarm_task_lifecycle{"
        for name, timer in list(self.registry.timers.items()):
            if not name.startswith(prefix):
                continue
            labels = name[len(prefix):-1]
            parts = dict(p.split("=", 1) for p in labels.split(","))
            edge = (parts['from'].strip('"') + "->"
                    + parts['to'].strip('"'))
            q = timer.quantiles()
            out[edge] = {"count": timer.count,
                         "total": timer.total,
                         **{f"p{int(k * 100)}": v for k, v in q.items()}}
        return out

    # ------------------------------------------------------- store-attached

    def start(self) -> None:
        if self.store is None:
            raise RuntimeError("LifecycleTracker needs a store to start()")
        self._thread = threading.Thread(target=self.run, name="lifecycle",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._done.wait(timeout=5)

    def run(self) -> None:
        try:
            def init(tx):
                for t in tx.find(Task):
                    state = int(t.status.state)
                    if TaskState(state) not in TERMINAL_STATES:
                        self._last[t.id] = (state,
                                            t.status.timestamp or 0.0)

            _, sub = self.store.view_and_watch(init, accepts_blocks=True)
            try:
                while not self._stop.is_set():
                    try:
                        ev = sub.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except Closed:
                        return
                    self.handle_event(ev)
            finally:
                self.store.queue.unsubscribe(sub)
        finally:
            self._done.set()


class _BlockView:
    """Minimal Task-shaped view of one block-committed assignment (id +
    new status), avoiding per-task materialization on the watch path."""

    __slots__ = ("id", "meta", "status", "service_id")

    def __init__(self, old: Task, state: int, ts: float):
        self.id = old.id
        self.meta = old.meta
        self.service_id = old.service_id
        self.status = _StatusView(state, ts)


class _StatusView:
    __slots__ = ("state", "timestamp")

    def __init__(self, state: int, ts: float):
        self.state = state
        self.timestamp = ts
