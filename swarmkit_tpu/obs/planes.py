"""Per-plane saturation signals: one uniform shape for every serving
plane.

USE-style saturation methodology (PAPERS.md): attribute a tail-latency
regression to the saturated *resource*, not the symptom.  Every serving
plane — raft commit, raft apply, scheduler, dispatcher, device, watch —
exports the same four signals through one ``PlaneStats`` per plane:

* **occupancy** — busy_s / wall_s per roll window (how much of the
  window the plane spent doing work), gauge
  ``swarm_plane_occupancy{plane="..."}``;
* **queue depth** — items waiting (proposal inbox, apply lag entries,
  pending backlog, sessions, dispatch queue, watch buffer), gauge
  ``swarm_plane_queue_depth{plane="..."}``;
* **oldest-item age** — seconds the head of that queue has waited,
  gauge ``swarm_plane_oldest_age_s{plane="..."}``;
* **drops / defers** — counters
  ``swarm_plane_drops{plane="..."}`` / ``swarm_plane_defers{plane=...}``.

Busy time is accumulated at the call sites (``note_busy`` / the
``busy()`` context manager); depth and age are either pushed
(``set_depth`` / ``set_oldest_age``) or pulled through a registered
``probe`` at roll time — the probe form keeps hot paths untouched for
signals that are just an attribute read away (raft inbox qsize, apply
lag).  ``roll_all()`` is driven by the sampler tick (production) and by
the sim engine / bench explicitly, so gauge freshness follows the same
cadence as every other sampled signal.

Time flows through ``models.types.now()`` — under the simulator's
VirtualClock occupancy windows are a pure function of the seed.  All
label values here are the fixed plane names below: bounded cardinality
by construction (swarmlint's metric-hygiene cardinality shapes enforce
the same rule tree-wide).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from ..models import types as _types
from ..utils.metrics import Registry
from ..utils.metrics import registry as _default_registry

# the fixed plane taxonomy (docs/architecture.md "planes & journeys")
RAFT = "raft"              # proposal inbox + fsync/WAL batch plane
RAFT_APPLY = "raft_apply"  # committed-entry apply plane (lag entries)
SCHEDULER = "scheduler"    # tick occupancy + pending backlog
DISPATCHER = "dispatcher"  # sessions + assignment fan-out flush
DEVICE = "device"          # planner dispatch queue + d2h stalls
WATCH = "watch"            # subscription lag (versions / buffer depth)

ALL_PLANES = (RAFT, RAFT_APPLY, SCHEDULER, DISPATCHER, DEVICE, WATCH)


class PlaneStats:
    """Saturation signals for one plane.  Thread-safe; cheap enough to
    call from hot paths (one lock, a few float adds)."""

    def __init__(self, name: str, registry: Optional[Registry] = None):
        self.name = name
        self.registry = registry or _default_registry
        self._mu = threading.Lock()
        self._busy_s = 0.0
        # opened lazily at the first roll(): constructing a PlaneStats
        # must not consume the time source (lazy plane() creation would
        # otherwise shift frozen-clock byte-identity runs)
        self._window_start: Optional[float] = None
        self._depth = 0.0
        self._oldest_age = 0.0
        self._drops = 0
        self._defers = 0
        self._probe: Optional[Callable[[], Dict[str, float]]] = None
        self.last_occupancy = 0.0

    # ------------------------------------------------------------ recording

    def note_busy(self, dt: float) -> None:
        """Accumulate ``dt`` seconds of busy time into the current
        window (retroactive form — pairs with existing phase timers)."""
        if dt <= 0:
            return
        with self._mu:
            self._busy_s += dt

    @contextmanager
    def busy(self):
        """Context-manager form of ``note_busy`` for inline sections."""
        t0 = _types.now()
        try:
            yield
        finally:
            self.note_busy(_types.now() - t0)

    def set_depth(self, n: float) -> None:
        with self._mu:
            self._depth = float(n)

    def set_oldest_age(self, seconds: float) -> None:
        with self._mu:
            self._oldest_age = max(0.0, float(seconds))

    def drop(self, n: int = 1) -> None:
        with self._mu:
            self._drops += n
        self.registry.counter(
            f'swarm_plane_drops{{plane="{self.name}"}}', n)

    def defer(self, n: int = 1) -> None:
        with self._mu:
            self._defers += n
        self.registry.counter(
            f'swarm_plane_defers{{plane="{self.name}"}}', n)

    def set_probe(self, probe: Optional[Callable[[], Dict[str, float]]]
                  ) -> None:
        """Register a pull-probe run at roll time; it returns any of
        ``{"depth": n, "oldest_age": s, "busy_s": dt}`` — the cheap way
        to sample signals that are an attribute read away (raft inbox
        qsize, commit_index - applied_index) without touching the hot
        path that produces them."""
        self._probe = probe

    # -------------------------------------------------------------- rolling

    def roll(self) -> Dict[str, float]:
        """Close the current occupancy window and export the gauges.
        Returns the rolled snapshot (also kept for ``report()``)."""
        probe = self._probe
        if probe is not None:
            try:
                probed = probe() or {}
            except Exception:
                probed = {}   # a dying component must not take obs down
            if "depth" in probed:
                self.set_depth(probed["depth"])
            if "oldest_age" in probed:
                self.set_oldest_age(probed["oldest_age"])
            if "busy_s" in probed:
                self.note_busy(probed["busy_s"])
        t = _types.now()
        with self._mu:
            start = self._window_start
            wall = t - start if start is not None else 0.0
            occ = min(1.0, self._busy_s / wall) if wall > 0 else 0.0
            self._busy_s = 0.0
            self._window_start = t
            self.last_occupancy = occ
            depth, oldest = self._depth, self._oldest_age
        reg = self.registry
        reg.gauge(f'swarm_plane_occupancy{{plane="{self.name}"}}',
                  round(occ, 6))
        reg.gauge(f'swarm_plane_queue_depth{{plane="{self.name}"}}',
                  depth)
        reg.gauge(f'swarm_plane_oldest_age_s{{plane="{self.name}"}}',
                  round(oldest, 6))
        return {"occupancy": round(occ, 6), "queue_depth": depth,
                "oldest_age_s": round(oldest, 6)}

    def report(self) -> Dict[str, float]:
        with self._mu:
            out = {
                "occupancy": round(self.last_occupancy, 6),
                "queue_depth": self._depth,
                "oldest_age_s": round(self._oldest_age, 6),
                "drops": self._drops,
                "defers": self._defers,
            }
        if self.name == DEVICE:
            # sub-plane rows from the device-telemetry ledger: where the
            # plane's busy time went (dispatch vs d2h vs compile) and
            # what it moved.  Lazy import, device plane only — the
            # ledger imports nothing above utils, so no cycle; an empty
            # ledger contributes nothing (fresh-manager rendering stays
            # byte-identical to PR 17).
            from . import devicetelemetry as _devtel
            sub = _devtel.sub_plane_rows()
            if sub:
                out["sub"] = sub
        return out


# ------------------------------------------------------------- module state

_lock = threading.Lock()
_planes: Dict[str, PlaneStats] = {}


def plane(name: str) -> PlaneStats:
    """The process-wide ``PlaneStats`` singleton for ``name`` (created
    on first use so importing a component never allocates planes it
    does not export)."""
    with _lock:
        p = _planes.get(name)
        if p is None:
            p = _planes[name] = PlaneStats(name)
        return p


def roll_all() -> Dict[str, Dict[str, float]]:
    """Roll every registered plane (sampler tick / bench window edge);
    returns {plane: rolled snapshot} in sorted order."""
    with _lock:
        items = sorted(_planes.items())
    return {name: p.roll() for name, p in items}


def report_all() -> Dict[str, Dict[str, float]]:
    """Deterministically ordered report for ``/debug/planes`` and the
    bench artifact.  Safe on a fresh process: an empty taxonomy reports
    an empty dict, never raises."""
    with _lock:
        items = sorted(_planes.items())
    return {name: p.report() for name, p in items}


def save_state():
    """Capture the plane table so an embedded capture session (the sim
    runner) can restore the embedding process's planes afterwards —
    same contract as Tracer.save_state/FlightRecorder.save_state."""
    with _lock:
        state = dict(_planes)
    return state


def restore_state(state) -> None:
    global _planes
    with _lock:
        _planes = dict(state)


def reset() -> None:
    """Start fresh (tests, sim scenario entry).  The table is REBOUND,
    not cleared in place, so a ``save_state`` capture survives."""
    global _planes
    with _lock:
        _planes = {}
